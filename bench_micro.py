"""Micro-benchmarks for the decode roofline investigation (VERDICT r4 #2).

Isolates where the gap between measured decode tok/s and the
weight-bandwidth bound goes:

  * quant-matmul variants at decode shapes — bf16, w8 (dequant-in-matmul),
    w8a8 (native int8 MXU dot), w4 — measuring effective HBM bandwidth.
    If w8 materializes a bf16 weight copy (the docstring'd suspect in
    models/quant.py), its GB/s will read ~1/3 of bf16's instead of ~2x.
  * forward-only vs forward+sampling decode step (sampling overhead).
  * KV-cache attention read cost vs context length.

Run on the real chip: `python bench_micro.py` (JSON lines to stdout).
Not driver-facing — bench.py remains the one-line contract.
"""

import json
import time

import numpy as np


def _timeit(fn, *args, n=20, warmup=3):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def bench_quant_matmuls(M=8, K=4096, N=14336, steps=64):
    """One decode-shaped matmul per variant, looped inside jit so dispatch
    amortizes; reports effective weight-read bandwidth."""
    import jax
    import jax.numpy as jnp

    from localai_tpu.models import quant as qnt

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(M, K)), jnp.bfloat16)
    w_f = rng.normal(size=(K, N)).astype(np.float32) * 0.02
    variants = {
        "bf16": (jnp.asarray(w_f, jnp.bfloat16), 2),
        "w8": (qnt.quantize_tensor(w_f, axis=0), 1),
        "w8a8": (qnt.QuantizedTensor(
            q=qnt.quantize_tensor(w_f, axis=0).q,
            scale=qnt.quantize_tensor(w_f, axis=0).scale,
            axis=0, mode="w8a8"), 1),
        # w4 traffic includes the group-wise f32 scales: 0.5 B/weight for
        # the nibbles + 4 B per `group` weights of scale rows
        "w4": (qnt.quantize_tensor4(w_f, axis=0), 0.5 + 4.0 / 128),
    }
    if jax.default_backend() == "tpu":
        from localai_tpu.ops import qmatmul

        w8 = variants["w8"][0]
        w4 = variants["w4"][0]

        def kernel_mm(h):
            return qmatmul.w8_matmul(h, w8.q, w8.scale)

        def kernel_mm4(h):
            return qmatmul.w4_matmul(h, w4.q, w4.scale)

        variants["w8_pallas"] = (kernel_mm, 1)
        variants["w4_pallas"] = (kernel_mm4, 0.5 + 4.0 / 128)
    out = {}
    for name, (w, bytes_per) in variants.items():
        if callable(w) and not hasattr(w, "shape"):
            def make_k(f):
                def body(x):
                    def step(h, _):
                        y = f(h)
                        return h + y[:, :K].astype(h.dtype) * 1e-6, None
                    h, _ = jax.lax.scan(step, x, None, length=steps)
                    return h
                return jax.jit(body)

            dt = _timeit(make_k(w), x) / steps
            gb = K * N * bytes_per / 1e9
            out[name] = {"ms_per_matmul": round(dt * 1e3, 4),
                         "weight_gb": round(gb, 3),
                         "eff_gbps": round(gb / dt, 1)}
            continue

        def make(w):
            def body(x):
                def step(h, _):
                    y = qnt.matmul(h, w)
                    # feed a slice back so the loop isn't dead-code-elim'd
                    return h + y[:, :K].astype(h.dtype) * 1e-6, None
                h, _ = jax.lax.scan(step, x, None, length=steps)
                return h
            return jax.jit(body)

        f = make(w)
        dt = _timeit(f, x) / steps
        gb = K * N * bytes_per / 1e9
        out[name] = {"ms_per_matmul": round(dt * 1e3, 4),
                     "weight_gb": round(gb, 3),
                     "eff_gbps": round(gb / dt, 1)}
    return out


def bench_step_breakdown(preset="1b", quant="int8", multi=32, paged=False):
    """Full decode step vs forward-only (sampling cost) on the engine."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from localai_tpu.engine import kvcache as kvc
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models import llama as mdl
    from localai_tpu.models.registry import (
        DEBUG_PRESETS,
        synthetic_quantized_params,
    )

    cfg = dataclasses.replace(DEBUG_PRESETS[preset], dtype="bfloat16")
    params = synthetic_quantized_params(cfg, quant)
    runner = ModelRunner(cfg, params, num_slots=8, max_ctx=1024,
                         prefill_buckets=[128], kv_dtype="int8",
                         paged=paged)
    prompt = list(range(1, 101))
    for _ in range(8):
        runner.admit(runner.acquire_slot(), prompt, temperature=0.0)

    full = _timeit(lambda: runner.step_n(multi), n=5) / multi

    # forward-only: same shapes, no sampling/top_k/counts
    @jax.jit
    def fwd_only(params, kv, state):
        pos = state.positions
        mask = kvc.decode_mask(cfg, pos, runner.max_ctx)
        write = kvc.decode_write(pos, raw=False)
        hidden, _ = mdl.forward(
            cfg, params, state.tokens[:, None], pos[:, None],
            write, kv.stacked(), mask, runner.rope)
        return mdl.logits_from_hidden(cfg, params, hidden[:, 0])

    f_dt = _timeit(lambda: fwd_only(runner.params, runner.kv, runner.state),
                   n=10)
    return {
        "full_step_ms": round(full * 1e3, 3),
        "forward_logits_ms": round(f_dt * 1e3, 3),
        "sampling_overhead_ms": round((full - f_dt) * 1e3, 3),
        "tok_s_at_bs8": round(8 / full, 1),
    }


def machine_index(n=512, steps=24, repeats=3):
    """Effective GFLOP/s of a fixed jitted matmul loop — the machine-speed
    normalizer for tools/perf_smoke.py, so a decode-throughput baseline
    committed from one box transfers to a differently-sized CI runner.
    Best-of-``repeats``: a capability measure must not be dragged down by
    a noisy neighbor stealing one measurement window."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((n, n), jnp.float32)

    @jax.jit
    def body(x):
        def step(h, _):
            return jnp.tanh(h @ x) * 0.5, None
        h, _ = jax.lax.scan(step, x, None, length=steps)
        return h

    dt = min(_timeit(body, x, n=5) for _ in range(repeats))
    return 2 * n * n * n * steps / dt / 1e9


def decode_smoke(paged: bool, preset: str = "tiny", num_slots: int = 4,
                 max_ctx: int = 512, multi: int = 16, repeats: int = 5,
                 mesh_devices: int = 0, kv_dtype: str = "float32",
                 kv_block_tokens: int = 0):
    """Steady-state batched decode tok/s of a debug preset — the CI perf
    smoke measurement. Best-of-``repeats`` (fastest sample): shared
    runners have multi-x contention spikes, and one clean window measures
    the code's capability; a median would gate on the neighbors.

    ``mesh_devices`` > 1 runs the meshed layout: a pure tensor-parallel
    mesh over that many devices (model axis), params sharded with the
    production partition rules — the CI pin that the pjit/shard_map serving
    path stays alive on a multi-device host (tools/perf_smoke.py gates the
    meshed-paged ratio; callers must check the device count first).

    ``kv_dtype`` selects the pool dtype (``int4`` exercises the nibble-
    packed paged pool + fused dequant); ``kv_block_tokens`` overrides the
    pool block size (0 = runner default / tuned table)."""
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import resolve_model

    model = resolve_model(f"debug:{preset}", dtype="float32")
    mesh = None
    params = model.params
    if mesh_devices > 1:
        import jax

        from localai_tpu.parallel import sharding as shd
        from localai_tpu.parallel.mesh import MeshPlan, build_mesh

        mesh = build_mesh(MeshPlan(model=mesh_devices),
                          devices=jax.devices()[:mesh_devices])
        params = shd.shard_params(params, model.cfg, mesh)
    runner = ModelRunner(model.cfg, params, num_slots=num_slots,
                         max_ctx=max_ctx, prefill_buckets=[128],
                         kv_dtype=kv_dtype, paged=paged, mesh=mesh,
                         kv_block_tokens=kv_block_tokens or None)
    prompt = list(range(1, 65))
    for _ in range(num_slots):
        runner.admit(runner.acquire_slot(), prompt, temperature=0.0)
    best = 0.0
    for _ in range(repeats):
        dt = _timeit(lambda: runner.step_n(multi), n=3, warmup=1)
        best = max(best, multi * num_slots / dt)
    return best


def anatomy_smoke(preset: str = "tiny", num_slots: int = 4,
                  max_ctx: int = 512, multi: int = 16,
                  dispatches: int = 24, depth: int = 2,
                  kv_dtype: str = "float32"):
    """Dispatch-anatomy summary of the pipelined paged decode smoke.

    The same loop shape as bench.py's pipelined decode (async dispatch +
    copy_to_host_async + deferred drain), with measured launch/sync and
    gap-by-exclusion phase attribution into a private FlightRecorder
    (obs.anatomy interval tiling; the smoke loop has no admit work, so
    sched=0). Returns ``FlightRecorder.phases()`` — tools/perf_smoke.py
    records and gates ``host_overhead_fraction`` from it, the ratchet the
    fused-dispatch work must drive down. Warmup compiles outside the
    measured window, so no compile row ever lands in the ring."""
    from collections import deque

    import jax

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import resolve_model
    from localai_tpu.obs.flight import FlightRecorder

    model = resolve_model(f"debug:{preset}", dtype="float32")
    runner = ModelRunner(model.cfg, model.params, num_slots=num_slots,
                         max_ctx=max_ctx, prefill_buckets=[128],
                         kv_dtype=kv_dtype, paged=True)
    prompt = list(range(1, 65))
    for _ in range(num_slots):
        runner.admit(runner.acquire_slot(), prompt, temperature=0.0)
    runner.step_n(multi)  # compile outside the measurement
    jax.block_until_ready(runner.state.tokens)
    flight = FlightRecorder(capacity=max(dispatches + 2, 8))
    q: deque = deque()
    launch_acc = 0.0
    last_t = time.monotonic()

    def drain() -> None:
        nonlocal last_t, launch_acc
        ts = time.perf_counter()
        np.asarray(q.popleft())
        sync_ms = (time.perf_counter() - ts) * 1e3
        now = time.monotonic()
        wall_ms = (now - last_t) * 1e3
        sync_ms = min(sync_ms, wall_ms)
        launch_ms = min(launch_acc, wall_ms - sync_ms)
        flight.record(
            program="decode_n", steps=multi, dispatch_ms=wall_ms,
            occupancy=1.0, queue_depth=0, kv_utilization=0.0,
            tokens=multi * num_slots,
            gap_ms=max(0.0, wall_ms - launch_ms - sync_ms),
            launch_ms=launch_ms, sync_ms=sync_ms,
        )
        launch_acc = 0.0
        last_t = now

    for _ in range(dispatches):
        tl = time.perf_counter()
        toks = runner.step_n_async(multi)
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass
        launch_acc += (time.perf_counter() - tl) * 1e3
        q.append(toks)
        if len(q) >= depth:
            drain()
    while q:
        drain()
    return flight.phases()


def main():
    import jax

    print(json.dumps({"backend": jax.default_backend(),
                      "devices": len(jax.devices())}))
    print(json.dumps({"quant_matmul_8b_ffn":
                      bench_quant_matmuls(M=8, K=4096, N=14336)}))
    print(json.dumps({"quant_matmul_lm_head":
                      bench_quant_matmuls(M=8, K=2048, N=128256, steps=16)}))
    print(json.dumps({"step_breakdown_1b_int8": bench_step_breakdown()}))
    print(json.dumps({"step_breakdown_1b_int8_paged":
                      bench_step_breakdown(paged=True)}))


if __name__ == "__main__":
    main()
