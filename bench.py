"""Benchmark: steady-state decode throughput of the TPU llama engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: Llama-3.2-1B-class shapes (synthetic weights — the reference
publishes no absolute numbers and this environment has zero egress, see
BASELINE.md), 8 concurrent slots, 128-token prefill each, then timed batched
decode. Weights are served int8 per-channel (models/quant.py) with scaled
int8 KV — the TPU analogue of the reference's default q4-GGUF serving format
(aio/cpu/text-to-text.yaml); set BENCH_QUANT=none for the bf16 variant.
This is the hot loop the north star measures (/v1/chat/completions output
tok/s); the API layers add microseconds, the engine dominates.

vs_baseline: ratio against 800 tok/s aggregate — a documented proxy for
llama.cpp-CUDA-class serving of a 1B model at batch 8 (~100 tok/s/stream).
The reference itself publishes no numbers (BASELINE.md), so this constant is
the stand-in target until a measured reference run exists; it is held fixed
across rounds so the trend is comparable.

Round-3 measurement (for the record, in case the end-of-round run hits
tunnel trouble): 1246.37 tok/s = 1.558x with the int8 default on the real
chip (2026-07-30, before a multi-hour axon tunnel outage that began
~07:30 UTC). Sweeps the same day: bf16 1180 (int8 +6% — decode is NOT
purely weight-bandwidth-bound on this tunneled chip), multi_step 16/32/64
within noise (1234/1246/1261), so the next lever is on-device per-step
work (attention over padded KV / sampling), not dispatch amortization.
"""

import json
import os
import time

BASELINE_TOK_S = 800.0


def main() -> None:
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import resolve_model

    import jax

    # env knobs for smoke runs (the driver uses the defaults)
    preset = os.environ.get("BENCH_MODEL", "debug:1b")
    steps = int(os.environ.get("BENCH_STEPS", "192"))
    multi = int(os.environ.get("BENCH_MULTI_STEP", "32"))
    depth = int(os.environ.get("BENCH_DEPTH", "4"))
    quant = os.environ.get("BENCH_QUANT", "int8")

    model = resolve_model(preset, dtype="bfloat16")
    params = model.params
    kv_dtype = "bfloat16"
    if quant == "int8":
        from localai_tpu.models.quant import quantize_params

        params = quantize_params(params, "int8")
        kv_dtype = "int8"
    num_slots = 8
    runner = ModelRunner(
        model.cfg, params, num_slots=num_slots, max_ctx=1024,
        prefill_buckets=[128], kv_dtype=kv_dtype,
    )

    prompt = list(range(1, 101))  # 100-token synthetic prompt
    for _ in range(num_slots):
        slot = runner.acquire_slot()
        runner.admit(slot, prompt, temperature=0.0)

    # warmup (compile + first dispatches)
    runner.step_n(multi)
    runner.step_n(multi)
    jax.block_until_ready(runner.state.tokens)

    # pipelined multi-step loop — the scheduler's production pattern: each
    # dispatch decodes `multi` tokens per slot inside one compiled lax.scan
    # program (amortizing dispatch/tunnel RTT), depth-2 dispatches stay in
    # flight with async D2H copies, so neither the device nor the host
    # round-trip sits on the critical path
    from collections import deque

    import numpy as np

    dispatches = max(1, steps // multi)
    t0 = time.perf_counter()
    q: deque = deque()
    for _ in range(dispatches):
        toks = runner.step_n_async(multi)
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass
        q.append(toks)
        if len(q) >= depth:
            np.asarray(q.popleft())
    while q:
        np.asarray(q.popleft())
    dt = time.perf_counter() - t0

    tok_s = dispatches * multi * num_slots / dt
    print(json.dumps({
        "metric": "decode_throughput_llama1b_bs8",
        "value": round(tok_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / BASELINE_TOK_S, 4),
    }))


if __name__ == "__main__":
    main()
