"""Benchmark: steady-state decode throughput of the TPU llama engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

PRIMARY metric (north star, VERDICT r3 #1): Llama-3-8B-shaped serving
(debug:llama3-8b — exact 8B dims, synthetic weights generated directly in
quantized form; BASELINE.md records that the reference publishes no absolute
numbers and this environment has zero egress). 8 concurrent slots, 100-token
prompts, then timed batched decode. Weights are served int8 per-channel with
scaled int8 KV — the TPU analogue of the reference's default q4-GGUF serving
(aio/cpu/text-to-text.yaml); the int8-KV decode path runs the Pallas flash
kernel with fused dequant + per-slot length-aware block skipping
(ops/attention.py). BENCH_QUANT=int4 serves group-wise int4 (closer to q4's
bits, faster still); =none serves bf16 (1B only — 8B bf16 exceeds one chip).

BASELINE (8B): 400 tok/s aggregate. Derivation: llama.cpp (the reference's
serving engine) on an A100-class GPU decodes 8B q4 at ~110-130 tok/s
single-stream (community llama-bench figures); its slot-parallel server at
--parallel 8 reaches ~3-4x aggregate, i.e. ~350-500 tok/s. 400 is the
midpoint, held fixed across rounds so the trend stays comparable. For
scale: one v5e chip's weight-bandwidth roofline for int8-8B decode is
819 GB/s / 8.03 GB ~ 102 steps/s ~ 816 tok/s at batch 8 — vs_baseline 2.0
is the physical ceiling for int8 (int4 raises it to ~4).

SECONDARY metric: the rounds-1-3 1B-class config (800 tok/s baseline proxy,
same constant as before) so the cross-round trend is not lost.
Round-3 1B reference points, same chip (2026-07-30): int8 1246 tok/s
(XLA decode, pre-Pallas-int8), bf16 1180, multi_step 16/32/64 within noise.
"""

import json
import os
import time

BASELINES = {
    "llama8b": 400.0,   # see module docstring for the derivation
    "llama1b": 800.0,   # rounds 1-3 proxy constant (bench.py history)
}


def run_decode_bench(preset: str, quant: str, steps: int, multi: int,
                     depth: int, num_slots: int = 8, max_ctx: int = 1024):
    """Prefill 8 slots, then timed pipelined multi-step decode.

    Returns aggregate decode tok/s. The pipelined loop is the scheduler's
    production pattern: each dispatch decodes `multi` tokens per slot inside
    one compiled lax.scan program (amortizing dispatch/tunnel RTT);
    `depth` dispatches stay in flight with async D2H copies, so neither the
    device nor the host round-trip sits on the critical path.
    """
    from collections import deque

    import jax
    import numpy as np

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import (
        DEBUG_PRESETS,
        resolve_model,
        synthetic_quantized_params,
    )

    kv_dtype = "bfloat16"
    if quant in ("int8", "int4"):
        import dataclasses

        cfg = dataclasses.replace(DEBUG_PRESETS[preset], dtype="bfloat16")
        params = synthetic_quantized_params(cfg, quant)
        kv_dtype = "int8"
    else:
        model = resolve_model(f"debug:{preset}", dtype="bfloat16")
        cfg, params = model.cfg, model.params

    runner = ModelRunner(
        cfg, params, num_slots=num_slots, max_ctx=max_ctx,
        prefill_buckets=[128], kv_dtype=kv_dtype,
    )

    prompt = list(range(1, 101))  # 100-token synthetic prompt
    for _ in range(num_slots):
        slot = runner.acquire_slot()
        runner.admit(slot, prompt, temperature=0.0)

    # warmup (compile + first dispatches)
    runner.step_n(multi)
    runner.step_n(multi)
    jax.block_until_ready(runner.state.tokens)

    dispatches = max(1, steps // multi)
    t0 = time.perf_counter()
    q: deque = deque()
    for _ in range(dispatches):
        toks = runner.step_n_async(multi)
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass
        q.append(toks)
        if len(q) >= depth:
            np.asarray(q.popleft())
    while q:
        np.asarray(q.popleft())
    dt = time.perf_counter() - t0
    return dispatches * multi * num_slots / dt


def main() -> None:
    # env knobs for smoke runs (the driver uses the defaults); the historic
    # "debug:1b" form is accepted alongside the bare preset name
    preset = os.environ.get("BENCH_MODEL", "llama3-8b")
    preset = preset.removeprefix("debug:")
    steps = int(os.environ.get("BENCH_STEPS", "192"))
    multi = int(os.environ.get("BENCH_MULTI_STEP", "32"))
    depth = int(os.environ.get("BENCH_DEPTH", "4"))
    quant = os.environ.get("BENCH_QUANT", "int8")
    with_secondary = os.environ.get("BENCH_SECONDARY", "1") != "0"

    short = "llama8b" if "8b" in preset else "llama1b" if "1b" in preset \
        else preset
    try:
        tok_s = run_decode_bench(preset, quant, steps, multi, depth)
        base = BASELINES.get(short, 800.0)
        result = {
            "metric": f"decode_throughput_{short}_bs8_{quant}",
            "value": round(tok_s, 2),
            "unit": "tok/s",
            "vs_baseline": round(tok_s / base, 4),
        }
    except Exception as e:  # noqa: BLE001 — keep a number on the board
        result = {
            "metric": f"decode_throughput_{short}_bs8_{quant}",
            "value": 0.0,
            "unit": "tok/s",
            "vs_baseline": 0.0,
            "note": f"{type(e).__name__}: {e}"[:300],
        }

    if with_secondary and "1b" not in preset:
        try:
            tok_1b = run_decode_bench("1b", "int8", steps, multi, depth)
            sec = {
                "metric": "decode_throughput_llama1b_bs8_int8",
                "value": round(tok_1b, 2),
                "unit": "tok/s",
                "vs_baseline": round(tok_1b / BASELINES["llama1b"], 4),
            }
            if result["value"]:
                result["secondary"] = sec
            else:  # primary failed — promote the 1B line, keep the note
                sec["note"] = result.get("note", "primary run failed")
                result = sec
        except Exception:
            pass

    print(json.dumps(result))


if __name__ == "__main__":
    main()
