"""Benchmark: steady-state decode throughput of the TPU llama engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} — and
is engineered to ALWAYS print it (VERDICT r4 #1): all measurement runs in a
worker thread while the main thread holds a hard deadline
(BENCH_BUDGET_S, default 1320 s) and flushes the best result seen so far the
moment the budget expires, even if the TPU tunnel hangs mid-dispatch (the
r3/r4 failure modes: backend-init UNAVAILABLE and a mid-run tunnel stall).

Phase order is cheapest-first so a number is on the board within minutes:
  1. 1B-class int8 (the rounds-1-3 trend config)   → landed as primary
  2. Llama-3-8B-shaped int8 (the north star)       → promoted to primary,
     1B demoted to "secondary", IF the remaining budget can fit it.

PRIMARY metric (north star, VERDICT r3 #1): Llama-3-8B-shaped serving
(debug:llama3-8b — exact 8B dims, synthetic weights generated directly in
quantized form; BASELINE.md records that the reference publishes no absolute
numbers and this environment has zero egress). 8 concurrent slots, 100-token
prompts, then timed batched decode. Weights are served int8 — the TPU
analogue of the reference's default q4-GGUF serving (aio/cpu/text-to-text
.yaml); the int8-KV decode path runs the Pallas flash kernel with fused
dequant + per-slot length-aware block skipping (ops/attention.py).
BENCH_QUANT=int4 serves group-wise int4; =int8_w8a8 runs the native int8-MXU
dot; =none serves bf16 (1B only — 8B bf16 exceeds one chip).

BASELINE (8B): 400 tok/s aggregate. Derivation: llama.cpp (the reference's
serving engine) on an A100-class GPU decodes 8B q4 at ~110-130 tok/s
single-stream (community llama-bench figures); its slot-parallel server at
--parallel 8 reaches ~3-4x aggregate, i.e. ~350-500 tok/s. 400 is the
midpoint, held fixed across rounds so the trend stays comparable. For
scale: one v5e chip's weight-bandwidth roofline for int8-8B decode is
819 GB/s / 8.03 GB ~ 102 steps/s ~ 816 tok/s at batch 8 — vs_baseline 2.0
is the physical ceiling for int8 (int4 raises it to ~4).

SECONDARY metric: the rounds-1-3 1B-class config (800 tok/s baseline proxy,
same constant as before). Round-3 reference points, same chip (2026-07-30):
int8 1246 tok/s (XLA decode, pre-Pallas-int8), bf16 1180.

STALL FORENSICS (round 6, obs subsystem): the r3/r4/r5 failure mode is a
dead axon tunnel that hangs a dispatch silently. Every phase now runs under
the obs.watchdog stall detector (no heartbeat for BENCH_STALL_S, default
90 s → the phase is abandoned, its thread left parked, and the run moves
on) and the device is liveness-probed (obs.device.probe_device, a tiny jit
round-trip joined with a timeout) before the first phase and after any
stall. Extra output fields:

  "device_health": {"ok", "seconds", "error", "device"} — the LAST probe
      result (after-stall probes overwrite the boot probe, so a dead
      tunnel shows up here, not just as a missing number);
  "stall_phase":   the phase label ("bench:<preset>:<quant>") whose
      dispatch heartbeat went silent past BENCH_STALL_S;
  "stall_age_s":   seconds of silence when the watchdog tripped.

A failed boot probe skips all device phases and reports value 0.0 with the
probe error in "note" — seconds spent, not the 1320 s budget.

FLIGHT RECORDER (round 7, obs.flight): every phase feeds a per-dispatch
ring, so a stalled or budget-expired round reports MEASURED progress
instead of a bare 0.0 (the BENCH r5 gap). Extra output fields:

  "step_ms_p50"/"step_ms_p99": windowed per-token step-time percentiles
      over the phase's drained dispatches (successful phases carry them
      inline in their metric line too);
  "partial_tokens": tokens the abandoned phase had decoded before its
      heartbeat went silent — 0 means it never reached the timed loop.
"""

import json
import os
import sys
import threading
import time

BASELINES = {
    "llama8b": 400.0,   # see module docstring for the derivation
    "llama1b": 800.0,   # rounds 1-3 proxy constant (bench.py history)
}


def _apply_platform() -> None:
    """Smoke runs: sitecustomize presets JAX_PLATFORMS=axon before any env
    override can land, so route via jax.config (honored until the backend
    initializes — same trick as tests/conftest.py). Idempotent; must run
    before the FIRST jax dispatch (including the device probe)."""
    if os.environ.get("BENCH_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (BENCH r4/r5 budget fix): the 8B
    phase's multi-minute compiles are paid once and reused across phases
    (the w8probe rebuilds its runner → fresh jit wrappers, same HLO) AND
    across bench rounds. Disable with BENCH_COMPILE_CACHE=0; best-effort —
    a cache failure must never cost the run its number."""
    path = os.environ.get("BENCH_COMPILE_CACHE", "")
    if path == "0":
        return
    if not path:
        path = os.path.expanduser("~/.cache/localai_tpu/xla-cache")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except AttributeError:
            pass
    except Exception as e:  # noqa: BLE001 — cache ≠ measurement
        sys.stderr.write(f"compile cache disabled: {e}\n")


def _cached_weights(preset: str, quant: str, cfg, gen):
    """Disk-cached synthetic quantized weights (BENCH r4/r5 budget fix).

    Generation for the 8B phase costs weight-gen dispatches plus its own
    share of the budget every round; the pickled pytree (numpy leaves —
    QuantizedTensor dataclasses pickle intact) is written once and
    reloaded on later rounds. Only phases whose generation actually took
    meaningful time are cached (cheap 1B gen would lose to the 8+ GB of
    disk+H2D traffic), there must be ample free disk, and every failure
    path falls back to ``gen()``. BENCH_WEIGHT_CACHE=0 disables; a
    directory overrides the default ~/.cache location."""
    import hashlib
    import pickle
    import shutil

    conf = os.environ.get("BENCH_WEIGHT_CACHE", "")
    if conf == "0" or quant == "int4":
        # int4 leaves (jnp.int4) don't round-trip the numpy pickle path
        return gen()
    cache_dir = (conf if conf not in ("", "1")
                 else os.path.expanduser("~/.cache/localai_tpu/bench-weights"))
    # the key fingerprints the model config: a changed DEBUG_PRESETS dim or
    # dtype must miss (not load wrong-shaped weights that crash every
    # phase — the 0.0-row class this cache exists to prevent)
    fp = hashlib.sha1(repr(cfg).encode()).hexdigest()[:10]
    path = os.path.join(cache_dir, f"{preset}_{quant}_seed0_{fp}.pkl")
    if os.path.exists(path):
        try:
            import jax.numpy as jnp

            t0 = time.monotonic()
            with open(path, "rb") as f:
                host = pickle.load(f)
            import jax

            params = jax.tree.map(jnp.asarray, host)
            sys.stderr.write(
                f"weight cache hit: {path} "
                f"({time.monotonic() - t0:.1f}s)\n")
            return params
        except Exception as e:  # noqa: BLE001 — torn cache → regenerate
            sys.stderr.write(f"weight cache unreadable ({e}); regenerating\n")
            try:
                os.unlink(path)
            except OSError:
                pass
    t0 = time.monotonic()
    params = gen()
    gen_s = time.monotonic() - t0
    min_gen_s = float(os.environ.get("BENCH_WEIGHT_CACHE_MIN_GEN_S", "20"))
    if gen_s < min_gen_s:
        return params  # regeneration is cheaper than the disk round-trip
    try:
        import jax
        import numpy as np

        host = jax.tree.map(np.asarray, params)
        size = sum(a.nbytes for a in jax.tree.leaves(host))
        os.makedirs(cache_dir, exist_ok=True)
        if shutil.disk_usage(cache_dir).free < size * 1.5:
            return params
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(host, f, protocol=4)
        os.replace(tmp, path)
        sys.stderr.write(f"weight cache stored: {path} (gen {gen_s:.0f}s)\n")
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"weight cache store failed: {e}\n")
    return params


def run_decode_bench(preset: str, quant: str, steps: int, multi: int,
                     depth: int, num_slots: int = 8, max_ctx: int = 1024,
                     watchdog=None, channel: str = "bench", flight=None,
                     meshed: bool = False):
    """Prefill 8 slots, then timed pipelined multi-step decode.

    Returns aggregate decode tok/s. The pipelined loop is the scheduler's
    production pattern: each dispatch decodes `multi` tokens per slot inside
    one compiled lax.scan program (amortizing dispatch/tunnel RTT);
    `depth` dispatches stay in flight with async D2H copies, so neither the
    device nor the host round-trip sits on the critical path.

    ``watchdog``/``channel``: each milestone (weights ready, runner built,
    every admit, every drained dispatch) heartbeats the stall watchdog —
    the hang point of a dead tunnel is whichever blocking call stopped the
    pulses, and the caller abandons the phase instead of the budget.

    ``flight``: an obs.flight.FlightRecorder fed one record per drained
    dispatch in the timed loop. The caller reads it after a stall for
    partial progress (the ring is shared host memory, readable even while
    the abandoned thread stays parked on its dead dispatch).
    """
    from collections import deque

    import jax

    _apply_platform()
    import numpy as np

    def pulse() -> None:
        if watchdog is not None:
            watchdog.pulse(channel)

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import (
        DEBUG_PRESETS,
        resolve_model,
        synthetic_quantized_params,
    )

    kv_dtype = "bfloat16"
    if quant in ("int8", "int4", "int8_w8a8"):
        import dataclasses

        cfg = dataclasses.replace(DEBUG_PRESETS[preset], dtype="bfloat16")
        params = _cached_weights(
            preset, quant, cfg,
            lambda: synthetic_quantized_params(cfg, quant))
        kv_dtype = "int8"
    else:
        model = resolve_model(f"debug:{preset}", dtype="bfloat16")
        cfg, params = model.cfg, model.params
    jax.block_until_ready(jax.tree.leaves(params)[0])
    pulse()

    # paged KV is the serving default — bench it unless BENCH_PAGED=0
    # (the contiguous escape hatch for round-over-round A/B)
    paged = os.environ.get("BENCH_PAGED", "1") != "0"
    mesh = None
    if meshed:
        # the meshed-paged serving default (ISSUE 8): all visible chips
        # on the 'model' axis (widest split the q-head count allows),
        # params sharded with the production partition rules
        from localai_tpu.parallel import sharding as shd
        from localai_tpu.parallel.mesh import (MeshPlan, build_mesh,
                                               default_tensor_parallel)

        devs = jax.devices()
        tp = default_tensor_parallel(len(devs), cfg.num_heads)
        if tp < 2:
            raise RuntimeError(
                f"meshed phase needs >=2 devices with a head-divisible "
                f"split; have {len(devs)} device(s), {cfg.num_heads} heads")
        mesh = build_mesh(MeshPlan(model=tp), devices=devs[:tp])
        params = shd.shard_params(params, cfg, mesh)
    runner = ModelRunner(
        cfg, params, num_slots=num_slots, max_ctx=max_ctx,
        prefill_buckets=[128], kv_dtype=kv_dtype, paged=paged, mesh=mesh,
    )
    pulse()

    prompt = list(range(1, 101))  # 100-token synthetic prompt
    for _ in range(num_slots):
        slot = runner.acquire_slot()
        runner.admit(slot, prompt, temperature=0.0)
        pulse()

    # warmup (compile + first dispatches)
    runner.step_n(multi)
    runner.step_n(multi)
    jax.block_until_ready(runner.state.tokens)
    pulse()

    def note_drain(last_t: float, launch_ms: float,
                   sync_ms: float) -> float:
        """One drained dispatch: heartbeat + flight record (the ring is
        what survives an abandoned phase — see module docstring). Phase
        attribution mirrors the scheduler's interval tiling (obs.anatomy):
        measured launch (async enqueue span) + sync (the asarray block),
        gap by exclusion; the bench loop has no admit work, so sched=0."""
        now = time.monotonic()
        if flight is not None:
            wall_ms = (now - last_t) * 1e3
            sync_ms = min(max(0.0, sync_ms), wall_ms)
            launch_ms = min(max(0.0, launch_ms), wall_ms - sync_ms)
            flight.record(
                program="decode_n", steps=multi,
                dispatch_ms=wall_ms,
                occupancy=1.0, queue_depth=0,
                kv_utilization=min(1.0, (100 + steps) / max_ctx),
                tokens=multi * num_slots,
                gap_ms=max(0.0, wall_ms - launch_ms - sync_ms),
                launch_ms=launch_ms, sync_ms=sync_ms,
            )
        pulse()
        return now

    dispatches = max(1, steps // multi)
    t0 = time.perf_counter()
    last_t = time.monotonic()
    q: deque = deque()
    launch_acc = 0.0  # enqueue ms since the last drain (obs.anatomy)
    for _ in range(dispatches):
        tl = time.perf_counter()
        toks = runner.step_n_async(multi)
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass
        launch_acc += (time.perf_counter() - tl) * 1e3
        q.append(toks)
        if len(q) >= depth:
            ts = time.perf_counter()
            np.asarray(q.popleft())
            sync_ms = (time.perf_counter() - ts) * 1e3
            last_t = note_drain(last_t, launch_acc, sync_ms)
            launch_acc = 0.0
    while q:
        ts = time.perf_counter()
        np.asarray(q.popleft())
        sync_ms = (time.perf_counter() - ts) * 1e3
        last_t = note_drain(last_t, launch_acc, sync_ms)
        launch_acc = 0.0
    dt = time.perf_counter() - t0
    # phase provenance for the output line (ISSUE 14 satellite): which
    # attention kernel actually served the measurement, the KV dtype, and
    # the dispatch amortization — "1002 tok/s" means nothing round-over-
    # round without knowing whether the flash kernel or the gather
    # fallback produced it
    impl = (runner.paged_attn_impl if paged
            else runner.decode_attn_impl)
    info = {
        "kernel_impl": "pallas" if impl == "pallas" else "lax",
        "kv_dtype": str(runner.kv_dtype),
        "tokens_per_dispatch": multi * num_slots,
    }
    return dispatches * multi * num_slots / dt, info


def run_spec_bench(preset: str, quant: str, steps: int,
                   num_slots: int = 8, max_ctx: int = 1024,
                   gamma: int = 4, watchdog=None, channel: str = "bench",
                   flight=None):
    """Paged + speculative decode (localai_tpu.spec): the n-gram
    self-drafter over repetitive prompts, one verify-k window per
    dispatch. Returns (tok/s, accept_rate, tokens_per_dispatch).

    Windows serialize (the host drafter proposes from drained history),
    so the measured number is the honest end-to-end speculative TPOT —
    host proposal time included. A lookup miss falls back to one plain
    decode dispatch, exactly like the scheduler's lane."""
    import jax

    _apply_platform()
    import numpy as np

    def pulse() -> None:
        if watchdog is not None:
            watchdog.pulse(channel)

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import (
        DEBUG_PRESETS,
        resolve_model,
        synthetic_quantized_params,
    )
    from localai_tpu.spec import NGramDrafter, SpecEngine

    kv_dtype = "bfloat16"
    if quant in ("int8", "int4", "int8_w8a8"):
        import dataclasses

        cfg = dataclasses.replace(DEBUG_PRESETS[preset], dtype="bfloat16")
        params = _cached_weights(
            preset, quant, cfg,
            lambda: synthetic_quantized_params(cfg, quant))
        kv_dtype = "int8"
    else:
        model = resolve_model(f"debug:{preset}", dtype="bfloat16")
        cfg, params = model.cfg, model.params
    jax.block_until_ready(jax.tree.leaves(params)[0])
    pulse()
    runner = ModelRunner(
        cfg, params, num_slots=num_slots, max_ctx=max_ctx,
        prefill_buckets=[128], kv_dtype=kv_dtype, paged=True,
    )
    eng = SpecEngine(runner, NGramDrafter(num_slots, gamma))
    pulse()
    prompt = list(range(1, 4)) * 33 + [1]  # 100-token repetitive prompt
    slots = []
    for _ in range(num_slots):
        slot = eng.acquire_slot()
        eng.admit(slot, prompt, temperature=0.0)
        slots.append(slot)
        pulse()
    # warmup: compile the verify window + the plain fallback. The plain
    # step's tokens MUST feed the drafter history like the fallback
    # branch below — a silently-dropped token desyncs every slot's
    # n-gram record and the measured accept rate becomes fiction.
    try:
        eng.step_spec()
    except RuntimeError:
        pass
    toks = np.asarray(runner.step())
    for s in slots:
        eng.drafter.observe(s, [int(toks[s])])
    jax.block_until_ready(runner.state.tokens)
    pulse()
    eng0_emitted, eng0_windows = eng.total_emitted, eng.total_windows
    target_tokens = steps * num_slots
    emitted = 0
    dispatches = 0
    t0 = time.perf_counter()
    last_t = time.monotonic()
    while emitted < target_tokens and dispatches < steps * 2:
        dispatches += 1
        tl = time.perf_counter()
        rows = eng.step_spec_async()
        launch_ms = (time.perf_counter() - tl) * 1e3
        if rows is None:  # lookup miss everywhere — plain fallback
            toks = np.asarray(runner.step())
            # the runner split its own wall (obs.anatomy scratch); the
            # declined proposal's host span above stays in gap
            launch_ms = runner.last_launch_ms
            sync_ms = runner.last_sync_ms
            for s in slots:
                eng.drafter.observe(s, [int(toks[s])])
            emitted += num_slots
            w = None
        else:
            ts = time.perf_counter()
            rows = np.asarray(rows)
            sync_ms = (time.perf_counter() - ts) * 1e3
            w = eng.observe_window(rows)
            emitted += w["emitted"]
        now = time.monotonic()
        if flight is not None:
            wall_ms = (now - last_t) * 1e3
            sync_ms = min(max(0.0, sync_ms), wall_ms)
            launch_ms = min(max(0.0, launch_ms), wall_ms - sync_ms)
            flight.record(
                program="spec" if w else "decode", steps=1,
                dispatch_ms=wall_ms, occupancy=1.0,
                queue_depth=0, kv_utilization=0.0,
                tokens=w["emitted"] if w else num_slots,
                spec_proposed=w["proposed"] if w else 0,
                spec_accepted=w["accepted"] if w else 0,
                gap_ms=max(0.0, wall_ms - launch_ms - sync_ms),
                launch_ms=launch_ms, sync_ms=sync_ms,
            )
        last_t = now
        pulse()
    dt = time.perf_counter() - t0
    d_emit = eng.total_emitted - eng0_emitted
    d_win = eng.total_windows - eng0_windows
    info = {
        "kernel_impl": ("pallas" if runner.paged_attn_impl == "pallas"
                        else "lax"),
        "kv_dtype": str(runner.kv_dtype),
        # batch-level emitted tokens per verify dispatch (the per-slot
        # figure rides spec_tokens_per_dispatch)
        "tokens_per_dispatch": round(d_emit / d_win, 4) if d_win else 0.0,
    }
    return (emitted / dt, eng.accept_rate,
            (d_emit / (d_win * num_slots)) if d_win else 0.0, info)


def _measure_spec(board, preset: str, quant: str, steps: int,
                  watchdog=None, channel: str = "bench:spec",
                  flight=None) -> None:
    """Speculative phase: rides the output under its own ``spec`` key
    (like the meshed phase — it must never displace the single-device
    trend line). BENCH_SPEC=0 skips it."""
    short = "llama8b" if "8b" in preset else "llama1b" if "1b" in preset \
        else preset
    t0 = time.monotonic()
    try:
        tok_s, accept, per_dispatch, info = run_spec_bench(
            preset, quant, steps, watchdog=watchdog, channel=channel,
            flight=flight)
        line = {
            "metric": f"decode_throughput_{short}_bs8_{quant}_spec",
            "value": round(tok_s, 2),
            "unit": "tok/s",
            "phase_s": round(time.monotonic() - t0, 1),
            "kv": "paged+spec",
            "spec_accept_rate": round(accept, 4),
            "spec_tokens_per_dispatch": round(per_dispatch, 4),
            **info,
        }
        if flight is not None:
            pct = flight.percentiles()
            if pct["step_ms_p50"] is not None:
                line["step_ms_p50"] = pct["step_ms_p50"]
                line["step_ms_p99"] = pct["step_ms_p99"]
            line.update(_anatomy_fields(flight))
        board.annotate("spec", line)
    except Exception as e:  # noqa: BLE001 — keep a diagnosable line
        board.annotate("spec", {
            "metric": f"decode_throughput_{short}_bs8_{quant}_spec",
            "value": 0.0, "unit": "tok/s",
            "note": f"{type(e).__name__}: {e}"[:300],
        })


class _Board:
    """The one-JSON-line contract: whoever prints, prints best-known-now."""

    def __init__(self):
        self.lock = threading.Lock()
        self.result = None       # current best primary line (dict)
        self.extras = {}         # forensics merged at flush (device_health,
                                 # stall_phase, partial step timings...);
                                 # the result line always wins a key clash,
                                 # so a stalled phase's partial percentiles
                                 # can never mask a successful phase's
                                 # measured ones
        self.printed = False
        # thread idents of ABANDONED stalled phases: if the tunnel comes
        # back minutes later and the parked thread finishes, its timing
        # includes the hang — a poisoned number that must never reach the
        # board (it could replace a good primary via the promote branch)
        self.dead_threads: set = set()

    def abandon_current_thread_of(self, ident: int) -> None:
        with self.lock:
            self.dead_threads.add(ident)

    def thread_dead(self) -> bool:
        with self.lock:
            return threading.get_ident() in self.dead_threads

    def annotate(self, key: str, value) -> None:
        with self.lock:
            self.extras[key] = value

    def offer(self, result: dict, primary: bool) -> None:
        with self.lock:
            if threading.get_ident() in self.dead_threads:
                return  # a stalled phase's late result is not a measurement
            if self.result is None:
                self.result = result
            elif primary and self.result.get("value"):
                # promote: previous (1B) result becomes the secondary
                sec = {k: v for k, v in self.result.items() if k != "secondary"}
                result["secondary"] = sec
                self.result = result
            elif primary:
                self.result = result
            elif self.result.get("value") == 0.0 and result.get("value"):
                # primary placeholder failed — promote the working number
                result.setdefault("note", self.result.get("note", ""))
                self.result = result

    def flush(self) -> None:
        with self.lock:
            if self.printed:
                return
            self.printed = True
            out = dict(self.extras)
            out.update(self.result or {
                "metric": "decode_throughput", "value": 0.0, "unit": "tok/s",
                "vs_baseline": 0.0, "note": "no phase completed in budget",
            })
            sys.stdout.write(json.dumps(out) + "\n")
            sys.stdout.flush()


def _anatomy_fields(flight) -> dict:
    """Dispatch-anatomy attribution for a bench phase line (obs.anatomy):
    windowless ring summary → host/sync p50 + the bubble estimate, so the
    line names its bottleneck instead of reporting another blind tok/s."""
    ph = flight.phases()
    if not ph.get("samples") or ph.get("host_ms_p50") is None:
        return {}
    return {
        "host_ms_p50": ph["host_ms_p50"],
        "sync_ms_p50": ph["sync_ms_p50"],
        "bubble": ph["device_bubble_fraction"],
        "host_overhead_fraction": ph["host_overhead_fraction"],
    }


def _measure(board: _Board, preset: str, quant: str, steps: int, multi: int,
             depth: int, primary: bool, watchdog=None,
             channel: str = "bench", flight=None, meshed: bool = False) -> None:
    short = "llama8b" if "8b" in preset else "llama1b" if "1b" in preset \
        else preset
    base = BASELINES.get(short, 800.0)
    t0 = time.monotonic()
    # measurements taken with the Pallas dequant kernel active are a
    # different serving configuration — mark them so round-over-round
    # comparisons never silently mix the two
    w8k = "_w8k" if os.environ.get("LOCALAI_W8_KERNEL") else ""
    paged = os.environ.get("BENCH_PAGED", "1") != "0"
    note = ""
    try:
        try:
            tok_s, info = run_decode_bench(
                preset, quant, steps, multi, depth, watchdog=watchdog,
                channel=channel, flight=flight, meshed=meshed)
        except Exception as e:  # noqa: BLE001
            if not paged or board.thread_dead() or meshed:
                # the meshed phase has no contiguous fallback: its result
                # is the mesh×paged configuration or nothing
                raise
            # the paged path (block tables + paged-attention kernel) died —
            # a number measured on the contiguous layout still beats a 0.0
            # row, clearly marked so the regression is visible
            note = f"paged_fallback: {type(e).__name__}: {e}"[:300]
            os.environ["BENCH_PAGED"] = "0"
            try:
                paged = False
                tok_s, info = run_decode_bench(
                    preset, quant, steps, multi, depth, watchdog=watchdog,
                    channel=channel, flight=flight)
                info["kernel_impl"] = "fallback"
            finally:
                os.environ["BENCH_PAGED"] = "1"
        mesh_tag = "_meshed" if meshed else ""
        line = {
            "metric": f"decode_throughput_{short}_bs8_{quant}{w8k}{mesh_tag}",
            "value": round(tok_s, 2),
            "unit": "tok/s",
            "vs_baseline": round(tok_s / base, 4),
            "phase_s": round(time.monotonic() - t0, 1),
            "kv": ("paged+mesh" if meshed and paged
                   else "paged" if paged else "contig"),
            **info,
        }
        if note:
            line["note"] = note
        if flight is not None:
            pct = flight.percentiles()
            if pct["step_ms_p50"] is not None:
                line["step_ms_p50"] = pct["step_ms_p50"]
                line["step_ms_p99"] = pct["step_ms_p99"]
            line.update(_anatomy_fields(flight))
        if meshed:
            # the meshed line rides the output as its own key — offer()
            # only keeps primaries/promotions, and the meshed phase must
            # never displace the round-over-round single-device trend
            board.annotate("meshed", line)
        else:
            board.offer(line, primary)
    except Exception as e:  # noqa: BLE001 — keep a number on the board
        note = f"{type(e).__name__}: {e}"[:300]
        mesh_tag = "_meshed" if meshed else ""
        fail_line = {
            "metric": f"decode_throughput_{short}_bs8_{quant}{w8k}{mesh_tag}",
            "value": 0.0,
            "unit": "tok/s",
            "vs_baseline": 0.0,
            "note": note,
        }
        if meshed:
            board.annotate("meshed", fail_line)
            return
        board.offer(fail_line, primary and board.result is None)
        if primary and not board.thread_dead():
            # a crashed north-star phase must stay diagnosable no matter
            # which line ends up printing — annotate it under its own key
            with board.lock:
                if (board.result is not None
                        and board.result.get("metric")
                        != f"decode_throughput_{short}_bs8_{quant}{w8k}"):
                    board.result["primary_note"] = note


def main() -> None:
    # env knobs for smoke runs (the driver uses the defaults); the historic
    # "debug:1b" form is accepted alongside the bare preset name
    preset = os.environ.get("BENCH_MODEL", "llama3-8b")
    preset = preset.removeprefix("debug:")
    steps = int(os.environ.get("BENCH_STEPS", "192"))
    multi = int(os.environ.get("BENCH_MULTI_STEP", "32"))
    depth = int(os.environ.get("BENCH_DEPTH", "4"))
    quant = os.environ.get("BENCH_QUANT", "int8")
    budget = float(os.environ.get("BENCH_BUDGET_S", "1320"))
    # minimum remaining budget to even start the 8B phase: weight gen +
    # prefill/decode compiles + timed run, measured ~200-400 s on a healthy
    # tunnel — 480 leaves margin for a slow compile without risking the board
    min_8b = float(os.environ.get("BENCH_8B_MIN_S", "480"))
    deadline = time.monotonic() + budget

    stall_s = float(os.environ.get("BENCH_STALL_S", "90"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "30"))
    # obs.watchdog/device/flight import no jax at module level — safe
    # pre-init
    from localai_tpu.obs.device import probe_device
    from localai_tpu.obs.flight import FlightRecorder
    from localai_tpu.obs.watchdog import Watchdog

    wd = Watchdog(deadline=stall_s, poll_interval=max(1.0, stall_s / 8))
    wd.start()

    board = _Board()
    # BENCH_PHASES=1b,8b,meshed,spec — comma-list phase selector so a
    # triage round can run ONE phase at a time instead of dying opaquely
    # mid-sequence (ROADMAP item 1: r03 crashed, r04 timed out, r05
    # completed zero phases — with the selector the next round bisects).
    # Empty/unset = every phase (the driver default). Unknown names are
    # ignored so a selector typo degrades to a skipped phase, never a
    # crashed round.
    sel = {t.strip().removeprefix("debug:")
           for t in os.environ.get("BENCH_PHASES", "").split(",")
           if t.strip()}

    def phase_on(*names: str) -> bool:
        return not sel or any(n in sel for n in names)

    # BENCH_PROFILE=<phase> (1b / 8b / meshed / spec): wrap EXACTLY ONE
    # matching phase in a jax.profiler capture and record the artifact
    # path in the output JSON — the ROADMAP item 1 hardware round needs
    # slow-phase attribution (which program, which gap), not another
    # blind retry. One phase only: profiling is real device overhead and
    # a whole-round capture would skew every number on the board.
    profile_sel = (os.environ.get("BENCH_PROFILE", "")
                   .strip().removeprefix("debug:"))
    profiled = {"armed": bool(profile_sel)}

    def maybe_profiled(names: tuple, fn):
        if not profiled["armed"] or profile_sel not in names:
            return fn
        profiled["armed"] = False  # exactly one phase captures

        def wrapped():
            import jax

            path = os.path.join(
                os.environ.get("BENCH_PROFILE_DIR", "bench_profile"),
                f"phase-{profile_sel}")
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            try:
                fn()
            finally:
                jax.profiler.stop_trace()
                board.annotate("profile_phase", profile_sel)
                board.annotate("profile_dir", path)
        return wrapped

    phases: list[tuple] = []
    if preset in ("llama3-8b", "8b"):          # cheap trend config first,
        if phase_on("1b"):                     # then the north star
            phases.append(("1b", "int8", not phase_on("8b", "llama3-8b")))
        if phase_on("8b", "llama3-8b"):
            phases.append(("llama3-8b", quant, True))
    elif phase_on(preset):
        phases.append((preset, quant, True))

    def probe_w8_kernel():
        """Self-tune for the 8B north-star phase: time a kernel-on 1B run
        (same steps — comparable regime) against the measured kernel-off
        number; keep LOCALAI_W8_KERNEL for the 8B phase only on a >3% win.
        The Pallas dequant matmul (ops/qmatmul.py) removes the XLA w8
        path's possible bf16 weight materialization — whether that
        materialization actually happens is hardware-dependent, so measure
        instead of assuming. The 1B trend line is NEVER overwritten (the
        probe annotates it under w8_kernel_tok_s only); any metric measured
        with the kernel active carries a _w8k suffix (see _measure). A
        user-set LOCALAI_W8_KERNEL is left alone."""
        if os.environ.get("BENCH_PROBE_KERNEL", "1") == "0":
            return
        if os.environ.get("LOCALAI_W8_KERNEL"):
            return  # explicit operator choice wins
        base_line = board.result
        if not base_line or not base_line.get("value"):
            return
        if deadline - time.monotonic() < min_8b + 240:
            return
        os.environ["LOCALAI_W8_KERNEL"] = "1"
        try:
            t_on, _ = run_decode_bench("1b", "int8", steps, multi, depth,
                                       watchdog=wd, channel="bench:w8probe")
        except Exception:  # noqa: BLE001 — probe failure → stay off
            t_on = 0.0
        if board.thread_dead():
            # this probe stalled and was abandoned: its timing includes the
            # hang, and the kernel it was validating must stay OFF
            os.environ.pop("LOCALAI_W8_KERNEL", None)
            return
        if t_on > base_line["value"] * 1.03:
            with board.lock:
                board.result["w8_kernel_tok_s"] = round(t_on, 2)
        else:
            os.environ.pop("LOCALAI_W8_KERNEL", None)

    def guarded(label: str, fn) -> bool:
        """Run one phase in its own daemon thread under watchdog channel
        ``label``. Returns False on stall or budget exhaustion — the hung
        thread is ABANDONED (left parked on its dead dispatch; daemon, so
        it cannot keep the process alive past the hard exit) and its
        channel left armed so the forensic trace stands."""
        done = threading.Event()

        def run():
            try:
                fn()
            finally:
                done.set()

        wd.arm(label)
        t = threading.Thread(target=run, daemon=True,
                             name=f"bench-{label}")
        t.start()
        while not done.wait(1.0):
            if wd.stalled(label):
                st = wd.status().get(label, {})
                board.annotate("stall_phase", label)
                board.annotate(
                    "stall_age_s",
                    st.get("last_progress_age_seconds", stall_s))
                board.abandon_current_thread_of(t.ident)
                return False
            if time.monotonic() >= deadline:
                board.abandon_current_thread_of(t.ident)
                return False
        wd.disarm(label)
        return True

    def work():
        _apply_platform()  # must precede the first jax use (the probe)
        _enable_compile_cache()
        probe = probe_device(timeout=probe_timeout)
        board.annotate("device_health", probe.to_dict())
        if not probe.ok:
            # dead tunnel detected in seconds: report it instead of
            # burning the budget discovering it one hung phase at a time
            board.offer({
                "metric": "decode_throughput", "value": 0.0,
                "unit": "tok/s", "vs_baseline": 0.0,
                "note": f"device probe failed: {probe.error}",
            }, primary=True)
            return
        # derived from the PRESET, not the selector-filtered phase list:
        # BENCH_PHASES=meshed on an 8b run must still measure the meshed/
        # spec phases on the ("1b","int8") config every unfiltered run
        # uses, or the bisected phase isn't the phase that failed
        has_8b = preset in ("llama3-8b", "8b")
        for p, q, primary in phases:
            remaining = deadline - time.monotonic()
            if remaining <= 30:
                return
            if "8b" in p and remaining < min_8b:
                return  # can't fit the 8B phase — the 1B line stands
            label = f"bench:{p}:{q}"
            # per-phase flight ring: on a stall the abandoned thread's
            # measured progress is still readable from here (partial
            # tokens + step-time percentiles instead of a bare 0.0)
            flight = FlightRecorder(512)
            phase_fn = (lambda p=p, q=q, primary=primary,
                        flight=flight, label=label: _measure(
                board, p, q, steps, multi, depth, primary,
                watchdog=wd, channel=label, flight=flight))
            names = (p, "8b") if p == "llama3-8b" else (p,)
            ok = guarded(label, maybe_profiled(names, phase_fn))
            if not ok:
                board.annotate("partial_tokens", flight.total_tokens)
                pct = flight.percentiles()
                if pct["step_ms_p50"] is not None:
                    board.annotate("step_ms_p50", pct["step_ms_p50"])
                    board.annotate("step_ms_p99", pct["step_ms_p99"])
                # the phase skipped forward; ask the device whether there
                # is any point continuing (a recovered transient keeps the
                # remaining phases; a dead tunnel ends the run now)
                after = probe_device(timeout=min(probe_timeout, 15.0))
                board.annotate("device_health", after.to_dict())
                if not after.ok:
                    return
                continue
            if (p == "1b" and q == "int8" and has_8b and quant == "int8"
                    and phase_on("8b", "llama3-8b")):  # probe feeds the
                # 8B phase only — pointless when the selector skips it
                if not guarded("bench:w8probe", probe_w8_kernel):
                    # a stalled probe must not leave the unvalidated
                    # kernel force-enabled for the 8B phase, and a dead
                    # tunnel should end the run here, not one stall later
                    os.environ.pop("LOCALAI_W8_KERNEL", None)
                    after = probe_device(timeout=min(probe_timeout, 15.0))
                    board.annotate("device_health", after.to_dict())
                    if not after.ok:
                        return
        # meshed-paged phase (ISSUE 8 / ROADMAP item 3): the tensor-
        # parallel serving default over all visible chips, as its own
        # non-primary line (metric suffix _meshed, kv="paged+mesh") so
        # the single-device trend stays comparable across rounds. Skips
        # clean on single-device hosts; BENCH_MESHED=0 disables.
        import jax

        if (os.environ.get("BENCH_MESHED", "1") != "0"
                and phase_on("meshed")
                and len(jax.devices()) > 1
                and deadline - time.monotonic() > 120):
            mp, mq = ("1b", "int8") if has_8b else (preset, quant)
            mflight = FlightRecorder(512)
            guarded("bench:meshed", maybe_profiled(("meshed",), lambda:
                _measure(
                    board, mp, mq, steps, multi, depth, primary=False,
                    watchdog=wd, channel="bench:meshed", flight=mflight,
                    meshed=True)))
        # speculative phase (ISSUE 11): the paged+spec lane with the
        # n-gram self-drafter on repetitive prompts — its own output key
        # ("spec"), BENCH_SPEC=0 escape, never displaces the trend line
        if (os.environ.get("BENCH_SPEC", "1") != "0"
                and phase_on("spec")
                and deadline - time.monotonic() > 90):
            sp, sq = ("1b", "int8") if has_8b else (preset, quant)
            sflight = FlightRecorder(512)
            guarded("bench:spec", maybe_profiled(("spec",), lambda:
                _measure_spec(
                    board, sp, sq, steps, watchdog=wd,
                    channel="bench:spec", flight=sflight)))

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout=budget)
    board.flush()
    # hard-exit: a hung TPU tunnel must not keep the process (and the
    # driver's timeout clock) alive after the number is printed
    os._exit(0)


if __name__ == "__main__":
    main()
