"""CLI entry: ``python -m localai_tpu.cli.main <command>``.

Parity: the reference's kong command tree (/root/reference/core/cli/
cli.go:8-20 — run, models, tts, transcript, worker, util, federated,
explorer) with env-aliased flags (run.go:19-73). argparse instead of kong;
every flag also reads LOCALAI_<NAME> from the environment.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import Optional, Sequence


def _env_default(name: str, fallback):
    for key in (f"LOCALAI_{name.upper()}", name.upper()):
        if key in os.environ:
            return os.environ[key]
    return fallback


def _env_bool(name: str, fallback: bool = False) -> bool:
    """Boolean env flags parse like AppConfig.from_env — 'false'/'0' must
    mean False, not truthy-nonempty-string."""
    v = _env_default(name, None)
    if v is None:
        return fallback
    return str(v).lower() in ("1", "true", "yes", "on")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="localai-tpu",
        description="TPU-native LocalAI: OpenAI-compatible serving on JAX/XLA",
    )
    p.add_argument("--log-level", default=_env_default("log_level", "info"),
                   choices=["error", "warn", "info", "debug", "trace"])
    p.add_argument("--log-format",
                   default=_env_default("log_format", "text"),
                   choices=["text", "json"],
                   help="json = one structured object per line, with the "
                        "request trace id bound by the API middleware")
    sub = p.add_subparsers(dest="command")

    run = sub.add_parser("run", help="start the API server (default)")
    run.add_argument("models", nargs="*", help="model refs to preload")
    run.add_argument("--address", default=_env_default("address", "0.0.0.0"))
    run.add_argument("--port", type=int,
                     default=int(_env_default("port", 8080)))
    run.add_argument("--models-path",
                     default=_env_default("models_path", "models"))
    run.add_argument("--context-size", type=int,
                     default=int(_env_default("context_size", 4096)))
    run.add_argument("--api-keys", default=_env_default("api_keys", ""),
                     help="comma-separated bearer keys")
    run.add_argument("--cors", action="store_true", default=True)
    run.add_argument("--no-cors", dest="cors", action="store_false")
    run.add_argument("--opaque-errors", action="store_true",
                     default=bool(_env_default("opaque_errors", "")))
    run.add_argument("--single-active-backend", action="store_true")
    run.add_argument("--preload-models", default="",
                     help="comma-separated model names to load eagerly")
    run.add_argument("--enable-watchdog-idle", action="store_true")
    run.add_argument("--enable-watchdog-busy", action="store_true")
    run.add_argument("--watchdog-idle-timeout", type=float, default=15 * 60)
    run.add_argument("--watchdog-busy-timeout", type=float, default=5 * 60)
    run.add_argument("--mesh", default=_env_default("mesh", ""),
                     help="mesh shape, e.g. data=2,model=4 (default: auto)")
    run.add_argument("--platform", default=_env_default("platform", None),
                     help="force JAX platform (cpu for tests)")
    # SLO observatory targets (obs.slo): p95 latency bounds in ms; when
    # the error-budget burn rate exceeds --slo-burn-threshold on both the
    # 1m and 5m windows, new generation work is shed with 429+Retry-After
    run.add_argument("--slo-ttft-p95-ms", type=float, default=None,
                     help="p95 time-to-first-token target in ms "
                          "(0/unset = no target)")
    run.add_argument("--slo-tpot-p95-ms", type=float, default=None,
                     help="p95 per-output-token latency target in ms")
    run.add_argument("--slo-e2e-p95-ms", type=float, default=None,
                     help="p95 end-to-end request latency target in ms")
    run.add_argument("--slo-queue-p95-ms", type=float, default=None,
                     help="p95 queue-wait target in ms")
    run.add_argument("--slo-burn-threshold", type=float, default=None,
                     help="error-budget burn rate that triggers load "
                          "shedding (default 2.0)")
    run.add_argument("--request-deadline-s", type=float, default=None,
                     help="per-request generation deadline in seconds; "
                          "expiry cancels the generation and frees its "
                          "decode slot (default 600)")
    # offline batch subsystem (localai_tpu.batch): background-lane knobs
    run.add_argument("--batch-concurrency", type=int, default=None,
                     help="max in-flight batch lines on the scheduler's "
                          "background lane (default 2)")
    run.add_argument("--batch-expiry-h", type=float, default=None,
                     help="hours before a non-terminal batch job expires "
                          "(default 24)")
    # fleet router (localai_tpu.fleet): multi-replica data-parallel serving
    run.add_argument("--fleet-replicas", type=int, default=None,
                     help="serve each LLM from N engine replicas behind "
                          "one cache-aware router (0/1 = single engine)")
    run.add_argument("--fleet-prefill-replicas", type=int, default=None,
                     help="dedicated prefill replicas for disaggregated "
                          "serving: long prompts prefill here and hand "
                          "their KV prefix to a decode replica (default 0)")
    run.add_argument("--fleet-backend", default=None,
                     choices=["worker", "inprocess"],
                     help="replica shape: spawned gRPC worker processes "
                          "(default) or in-process engines")
    run.add_argument("--fleet-disagg-threshold", type=int, default=None,
                     help="prompt tokens at which a request takes the "
                          "disaggregated prefill path (default 512)")
    run.add_argument("--fleet-device-pinning", action="store_true",
                     default=_env_bool("fleet_device_pinning"),
                     help="auto-derive per-replica worker env (TPU "
                          "visible-device slices) so --fleet-replicas N "
                          "partitions the host's accelerators evenly")
    run.add_argument("--fleet-hosts", default=None,
                     help="comma-separated host:port remote workers to "
                          "adopt into every fleet pool (cross-host "
                          "serving; failed remotes are evicted and "
                          "redialed on backoff, never respawned)")
    run.add_argument("--fleet-rpc-timeout-s", type=float, default=None,
                     help="per-reply inactivity deadline on cross-"
                          "replica streams and control RPCs (default "
                          "120; 0 disables; size above worst-case "
                          "queue wait + TTFT)")
    # elastic capacity (localai_tpu.fleet.autoscale)
    run.add_argument("--autoscale", action="store_true",
                     default=_env_bool("autoscale"),
                     help="telemetry-driven fleet autoscaling: scale "
                          "decode replicas between --autoscale-min/max "
                          "off queue depth, SLO burn, and KV pressure; "
                          "drain-based scale-in loses zero requests")
    run.add_argument("--autoscale-min", type=int, default=None,
                     help="decode replica floor the autoscaler holds "
                          "(default 1)")
    run.add_argument("--autoscale-max", type=int, default=None,
                     help="decode replica ceiling for scale-out "
                          "(default 4)")
    run.add_argument("--autoscale-interval-s", type=float, default=None,
                     help="seconds between autoscale control-loop ticks "
                          "(default 5)")
    run.add_argument("--autoscale-in-idle-s", type=float, default=None,
                     help="a replica idle this long (fleet above the "
                          "floor) is drained and retired (default 120)")
    run.add_argument("--autoscale-zero-idle-s", type=float, default=None,
                     help="ALL replicas idle this long → scale the model "
                          "to zero; the next request cold-respawns one "
                          "and waits for it (0 = off, the default)")
    run.add_argument("--autoscale-standby-hosts", default=None,
                     help="comma-separated host:port standby workers "
                          "adopted (instant capacity) before spawning "
                          "when scaling out")

    models = sub.add_parser("models", help="model management")
    models_sub = models.add_subparsers(dest="models_command")
    mlist = models_sub.add_parser("list", help="list configured models")
    mlist.add_argument("--models-path", default="models")
    minstall = models_sub.add_parser(
        "install", help="install from gallery/embedded library/URL")
    minstall.add_argument("ref", help="name, gallery@name, or URL")
    minstall.add_argument("--models-path", default="models")
    minstall.add_argument("--name", default="", help="install under this name")
    minstall.add_argument("--galleries", default="",
                          help="JSON list of {name,url} galleries")
    mavail = models_sub.add_parser(
        "available", help="list models available to install")
    mavail.add_argument("--models-path", default="models")
    mavail.add_argument("--galleries", default="")

    tok = sub.add_parser("tokenize", help="tokenize text with a model")
    tok.add_argument("text")
    tok.add_argument("--model", required=True)
    tok.add_argument("--models-path", default="models")

    worker = sub.add_parser("worker", help="start a gRPC model worker")
    worker.add_argument("--addr", default="127.0.0.1:50051")

    fol = sub.add_parser(
        "follower",
        help="multi-host follower: replicate a leader's engine calls")
    fol.add_argument("--leader", required=True,
                     help="leader's mirror channel host:port")
    fol.add_argument("--model", required=True)
    fol.add_argument("--models-path",
                     default=_env_default("models_path", "models"))
    fol.add_argument("--coordinator",
                     default=_env_default("coordinator_address", ""),
                     help="jax.distributed coordinator host:port")
    fol.add_argument("--num-processes", type=int,
                     default=int(_env_default("num_processes", 1)))
    fol.add_argument("--process-id", type=int,
                     default=int(_env_default("process_id", 1)))
    fol.add_argument("--peer-token",
                     default=_env_default("peer_token", ""),
                     help="shared secret for the mirror channel")

    tts = sub.add_parser("tts", help="synthesize speech to a wav file")
    tts.add_argument("text", nargs="+")
    tts.add_argument("--model", "-m", default="")
    tts.add_argument("--voice", "-v", default="alloy")
    tts.add_argument("--language", "-l", default="")
    tts.add_argument("--output-file", "-o", default="tts.wav")
    tts.add_argument("--models-path", default=_env_default(
        "models_path", "models"))

    tr = sub.add_parser("transcript", help="transcribe a wav file")
    tr.add_argument("filename")
    tr.add_argument("--model", "-m", default="")
    tr.add_argument("--language", "-l", default="")
    tr.add_argument("--translate", action="store_true")
    tr.add_argument("--models-path", default=_env_default(
        "models_path", "models"))

    sg = sub.add_parser("sound-generation",
                        help="generate audio from a text description")
    sg.add_argument("text", nargs="+")
    sg.add_argument("--model", "-m", default="")
    sg.add_argument("--duration", "-d", type=float, default=3.0)
    sg.add_argument("--output-file", "-o", default="sound.wav")

    util = sub.add_parser("util", help="model utilities")
    util_sub = util.add_subparsers(dest="util_command")
    ci = util_sub.add_parser(
        "checkpoint-info",
        help="tensor names/shapes/dtypes of a safetensors checkpoint "
             "(the safetensors-era gguf-info)")
    ci.add_argument("path")
    ci.add_argument("--header", action="store_true",
                    help="also print config.json")
    scan = util_sub.add_parser(
        "scan", help="scan installed models for unsafe weight formats")
    scan.add_argument("--models-path", default=_env_default(
        "models_path", "models"))
    uh = util_sub.add_parser(
        "usecase-heuristic",
        help="print the usecases a model config will serve")
    uh.add_argument("name")
    uh.add_argument("--models-path", default=_env_default(
        "models_path", "models"))
    cv = util_sub.add_parser(
        "convert",
        help="convert a GGUF checkpoint (f32/f16/q8_0/q4_0/q4_1/q4_k/q6_k) "
             "to the native safetensors layout; serve the result with "
             "quantization: int4/int8 for q4/q8-class bandwidth")
    cv.add_argument("gguf", help="path to the .gguf file")
    cv.add_argument("out", nargs="?", default=None,
                    help="output dir (default: <gguf stem> next to it)")
    cv.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32", "float16"])

    exp = sub.add_parser(
        "explorer", help="multi-network discovery dashboard over "
                         "federation routers (dial-test + eviction)")
    exp.add_argument("--address", default="0.0.0.0")
    exp.add_argument("--port", type=int, default=8085)
    exp.add_argument("--router", required=True,
                     help="federation router base URL (more can be "
                          "registered at runtime via POST /api/networks)")
    exp.add_argument("--db", default="",
                     help="JSON file persisting the tracked-network list")
    exp.add_argument("--interval", type=float, default=50.0,
                     help="seconds between dial-test sweeps")
    exp.add_argument("--failure-threshold", type=int, default=3,
                     help="consecutive failures before a network is "
                          "evicted from the database")

    fed = sub.add_parser(
        "federated", help="run a federation router over instances")
    fed.add_argument("--address", default=_env_default("address", "0.0.0.0"))
    fed.add_argument("--port", type=int,
                     default=int(_env_default("port", 8080)))
    fed.add_argument("--peers", default=_env_default("peers", ""),
                     help="comma-separated instance addresses (host:port)")
    fed.add_argument("--random-worker", action="store_true",
                     default=_env_bool("random_worker"),
                     help="random selection instead of least-used")
    fed.add_argument("--target-worker",
                     default=_env_default("target_worker", ""),
                     help="pin all traffic to one instance")
    fed.add_argument("--peer-token",
                     default=_env_default("peer_token", ""),
                     help="shared secret for /federated/register")

    sub.add_parser("version", help="print version")
    return p


def _parse_mesh(spec: str) -> Optional[dict]:
    # the ONE mesh parser (parallel.mesh.parse_mesh_spec) — shared with
    # AppConfig.from_env's LOCALAI_MESH handling so flag and env agree
    from localai_tpu.parallel.mesh import parse_mesh_spec

    return parse_mesh_spec(spec)


def _run_util(args, parser) -> int:
    """`util` subcommands (parity: core/cli/util.go — gguf-info/hf-scan/
    usecase-heuristic, re-targeted at the safetensors ecosystem)."""
    if args.util_command == "checkpoint-info":
        from pathlib import Path

        p = Path(args.path)
        files = [p] if p.is_file() else sorted(p.glob("*.safetensors"))
        if not files:
            parser.error(f"no safetensors under {p}")
        cfg_dir = p.parent if p.is_file() else p
        if args.header and (cfg_dir / "config.json").exists():
            print((cfg_dir / "config.json").read_text())
        from safetensors import safe_open

        total = 0
        for fp in files:
            with safe_open(str(fp), framework="numpy") as h:
                for name in h.keys():
                    sl = h.get_slice(name)
                    shape, dtype = sl.get_shape(), sl.get_dtype()
                    n = 1
                    for d in shape:
                        n *= d
                    total += n
                    print(f"{name}\t{dtype}\t{list(shape)}")
        print(f"# total parameters: {total:,}")
        return 0

    if args.util_command == "scan":
        # safetensors-era hf-scan: weights must be safetensors; pickle
        # formats (.bin/.pt/.ckpt) execute arbitrary code at load
        from pathlib import Path

        bad = []
        for f in Path(args.models_path).rglob("*"):
            if f.suffix in (".bin", ".pt", ".pth", ".ckpt", ".pickle",
                            ".pkl"):
                bad.append(f)
        for f in bad:
            print(f"UNSAFE (pickle-format weights): {f}")
        print(f"{len(bad)} finding(s)")
        return 1 if bad else 0

    if args.util_command == "convert":
        from pathlib import Path

        from localai_tpu.utils.gguf import convert_gguf

        src = Path(args.gguf)
        if not src.is_file():
            parser.error(f"{src}: not a file")
        out = Path(args.out) if args.out else src.with_suffix("")
        convert_gguf(src, out, dtype=args.dtype)
        print(f"converted {src} -> {out}")
        return 0

    if args.util_command == "usecase-heuristic":
        from localai_tpu.config.loader import ConfigLoader
        from localai_tpu.config.model_config import Usecase

        loader = ConfigLoader(args.models_path)
        loader.load_from_path()
        mcfg = loader.get(args.name)
        if mcfg is None:
            parser.error(f"model {args.name!r} not found")
        for uc in Usecase:
            if mcfg.has_usecase(uc):
                print(uc.value)
        return 0

    parser.error("unknown util subcommand")
    return 2


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    level = {"error": logging.ERROR, "warn": logging.WARNING,
             "info": logging.INFO, "debug": logging.DEBUG,
             "trace": logging.DEBUG}[args.log_level]
    # obs.logging imports no jax — safe before the backend initializes
    from localai_tpu.obs import logging as obs_logging

    obs_logging.setup(args.log_format, level)

    cmd = args.command or "run"
    if cmd == "version":
        from localai_tpu.version import __version__

        print(__version__)
        return 0

    if cmd == "run":
        if args.platform:
            os.environ.setdefault("JAX_PLATFORMS", args.platform)
        from localai_tpu.api.server import serve
        from localai_tpu.config.app_config import AppConfig

        # env first (LOCALAI_* for every AppConfig field — parity with the
        # kong env tags), explicit CLI values override
        cfg = AppConfig.from_env(
            model_path=args.models_path,
            address=args.address,
            port=args.port,
            context_size=args.context_size,
            cors=args.cors,
            api_keys=[k for k in args.api_keys.split(",") if k],
            opaque_errors=args.opaque_errors,
            single_active_backend=args.single_active_backend,
            preload_models=[m for m in args.preload_models.split(",") if m]
            + list(args.models),
            watchdog_idle=args.enable_watchdog_idle,
            watchdog_busy=args.enable_watchdog_busy,
            watchdog_idle_timeout=args.watchdog_idle_timeout,
            watchdog_busy_timeout=args.watchdog_busy_timeout,
            mesh_shape=_parse_mesh(args.mesh),
            platform=args.platform,
            # None = flag not given → LOCALAI_SLO_* env (from_env) stands
            slo_ttft_p95_ms=args.slo_ttft_p95_ms,
            slo_tpot_p95_ms=args.slo_tpot_p95_ms,
            slo_e2e_p95_ms=args.slo_e2e_p95_ms,
            slo_queue_p95_ms=args.slo_queue_p95_ms,
            slo_burn_threshold=args.slo_burn_threshold,
            request_deadline_s=args.request_deadline_s,
            batch_concurrency=args.batch_concurrency,
            batch_expiry_h=args.batch_expiry_h,
            fleet_replicas=args.fleet_replicas,
            fleet_prefill_replicas=args.fleet_prefill_replicas,
            fleet_backend=args.fleet_backend,
            fleet_disagg_threshold=args.fleet_disagg_threshold,
            fleet_device_pinning=args.fleet_device_pinning or None,
            fleet_hosts=([h for h in args.fleet_hosts.split(",") if h]
                         if args.fleet_hosts is not None else None),
            fleet_rpc_timeout_s=args.fleet_rpc_timeout_s,
            autoscale=args.autoscale or None,
            autoscale_min=args.autoscale_min,
            autoscale_max=args.autoscale_max,
            autoscale_interval_s=args.autoscale_interval_s,
            autoscale_in_idle_s=args.autoscale_in_idle_s,
            autoscale_zero_idle_s=args.autoscale_zero_idle_s,
            autoscale_standby_hosts=(
                [h for h in args.autoscale_standby_hosts.split(",") if h]
                if args.autoscale_standby_hosts is not None else None),
        )
        serve(cfg)
        return 0

    if cmd == "models":
        if args.models_command == "list":
            from localai_tpu.config.loader import ConfigLoader

            loader = ConfigLoader(args.models_path)
            loader.load_from_path()
            for name in loader.names():
                print(name)
            return 0
        if args.models_command in ("install", "available"):
            import json as jsonlib

            from localai_tpu.gallery import (
                EMBEDDED_MODELS,
                Gallery,
                available_models,
                install_model,
                resolve_ref,
            )

            galleries = [
                Gallery(name=g["name"], url=g["url"])
                for g in (jsonlib.loads(args.galleries)
                          if args.galleries else [])
            ]
            if args.models_command == "available":
                for m in available_models(galleries, args.models_path):
                    mark = "*" if m.installed else " "
                    print(f"{mark} {m.id}\t{m.description}")
                for name, m in sorted(EMBEDDED_MODELS.items()):
                    print(f"  embedded@{name}\t{m.description}")
                return 0
            ref = args.ref
            model = resolve_ref(galleries, ref, name=args.name)
            if model is None:
                parser.error(f"model {ref!r} not found in embedded library "
                             "or configured galleries")

            def progress(fn, done, total):
                pct = f"{100.0 * done / total:5.1f}%" if total else "?"
                print(f"\r{fn}: {pct}", end="", flush=True)

            path = install_model(
                model, args.models_path,
                install_name=args.name or model.name, progress=progress,
            )
            print(f"\ninstalled → {path}")
            return 0
        parser.error("unknown models subcommand")

    if cmd == "tokenize":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from localai_tpu.config.loader import ConfigLoader
        from localai_tpu.models.registry import resolve_tokenizer

        loader = ConfigLoader(args.models_path)
        loader.load_from_path()
        mcfg = loader.get(args.model)
        if mcfg is None:
            parser.error(f"model {args.model!r} not found")
        # tokenizer-only: never pull weights/KV into RAM just to encode
        tok = resolve_tokenizer(mcfg.model, args.models_path)
        print(tok.encode(args.text))
        return 0

    if cmd == "worker":
        from localai_tpu.worker.server import serve_worker

        serve_worker(args.addr)
        return 0

    if cmd == "follower":
        from localai_tpu.config.app_config import AppConfig
        from localai_tpu.config.loader import ConfigLoader

        if args.coordinator:
            from localai_tpu.parallel.multihost import initialize

            initialize(args.coordinator, args.num_processes,
                       args.process_id)
        app_cfg = AppConfig.from_env(model_path=args.models_path)
        loader = ConfigLoader(args.models_path)
        loader.load_from_path(context_size=app_cfg.context_size)
        mcfg = loader.get(args.model)
        if mcfg is None:
            parser.error(f"model {args.model!r} not found")
        from localai_tpu.models.manager import build_runner
        from localai_tpu.parallel.multihost import CommandFollower

        _model, runner = build_runner(mcfg, app_cfg)
        print(f"follower replica of {args.model} ready; replaying from "
              f"{args.leader}", flush=True)
        CommandFollower(args.leader, {args.model: runner},
                        token=args.peer_token).run_forever()
        return 0

    if cmd == "tts":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from localai_tpu.audio import write_wav
        from localai_tpu.audio.tts import synthesize
        from localai_tpu.config.loader import ConfigLoader

        voice = args.voice
        if args.model:
            loader = ConfigLoader(args.models_path)
            loader.load_from_path()
            mcfg = loader.get(args.model)
            tcfg = getattr(mcfg, "tts", None) if mcfg else None
            if tcfg is not None and getattr(tcfg, "voice", ""):
                voice = tcfg.voice
        samples = synthesize(" ".join(args.text), voice=voice)
        with open(args.output_file, "wb") as f:
            f.write(write_wav(samples))
        print(args.output_file)
        return 0

    if cmd == "transcript":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from pathlib import Path

        from localai_tpu.audio import read_wav
        from localai_tpu.config.loader import ConfigLoader
        from localai_tpu.models import whisper as wh

        loader = ConfigLoader(args.models_path)
        loader.load_from_path()
        name = args.model
        if not name:
            from localai_tpu.config.model_config import Usecase

            for cfg in loader.all():
                if cfg.has_usecase(Usecase.TRANSCRIPT):
                    name = cfg.name
                    break
        mcfg = loader.get(name) if name else None
        ref = (mcfg.model if mcfg else name) or name
        if not ref:
            parser.error("no transcription model configured; pass --model")
        if ref.startswith("debug:"):
            model = wh.debug_model()
        else:
            for cand in (Path(ref), Path(args.models_path) / ref):
                if (cand / "config.json").exists():
                    model = wh.load_hf_whisper(cand)
                    break
            else:
                parser.error(f"whisper model {ref!r} not found")
        audio = read_wav(Path(args.filename).read_bytes())
        result = model.transcribe(
            audio, language=args.language or None,
            translate=args.translate,
        )
        for seg in result.get("segments", []):
            print(f"[{seg['start']:7.2f}s → {seg['end']:7.2f}s] "
                  f"{seg['text']}")
        print(result.get("text", ""))
        return 0

    if cmd == "sound-generation":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from localai_tpu.audio import write_wav
        from localai_tpu.audio.tts import generate_sound

        samples = generate_sound(" ".join(args.text),
                                 duration=args.duration)
        with open(args.output_file, "wb") as f:
            f.write(write_wav(samples))
        print(args.output_file)
        return 0

    if cmd == "util":
        return _run_util(args, parser)

    if cmd == "explorer":
        from localai_tpu.federation.explorer import serve_explorer

        serve_explorer(args.router, args.address, args.port,
                       db_path=args.db or None, interval=args.interval,
                       failure_threshold=args.failure_threshold)
        return 0

    if cmd == "federated":
        from localai_tpu.federation import FederatedServer

        fs = FederatedServer(
            [a.strip() for a in args.peers.split(",") if a.strip()],
            load_balanced=not args.random_worker,
            worker_target=args.target_worker,
            peer_token=args.peer_token,
        )
        fs.serve(args.address, args.port)
        return 0

    parser.error(f"unknown command {cmd!r}")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
