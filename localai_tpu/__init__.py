"""localai_tpu — a TPU-native, OpenAI-compatible model serving framework.

Brand-new design with the capabilities of the reference LocalAI
(see /root/reference, structural analysis in SURVEY.md): an OpenAI-compatible
HTTP surface, one narrow model-worker RPC protocol, and declarative per-model
YAML configs — but the compute layer is a single JAX/XLA engine with Pallas
kernels and pjit/ICI sharding instead of a zoo of per-format native engines.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

  L7 CLI                localai_tpu.cli
  L6 HTTP API           localai_tpu.api        (aiohttp, OpenAI/LocalAI/Jina surface)
  L5 Services           localai_tpu.gallery, localai_tpu.utils.metrics
  L4 Modality adapters  localai_tpu.worker.manager (request -> worker RPC)
  L3 Model lifecycle    localai_tpu.worker     (spawn/health/watchdog)
  L2 Compute            localai_tpu.engine, localai_tpu.models, localai_tpu.ops
  L1 Distributed        localai_tpu.parallel   (Mesh/pjit/ICI collectives)
  L0 Supporting libs    localai_tpu.{config,templates,functions,utils}
"""

from localai_tpu.version import __version__

__all__ = ["__version__"]
