"""Host-side block allocator for the paged KV cache.

vLLM-style PagedAttention bookkeeping (Kwon et al., SOSP 2023) adapted to
this engine's static-shape XLA model: HBM holds one block pool
``[L, num_blocks, Hkv, block_tokens, hd]`` (engine.kvcache.PagedKVCache;
int4 pools nibble-pack head_dim so their last dim is ``hd/2`` — the
allocator is deliberately dtype-blind, a block id maps the same rows
whatever the pool stores) and
every slot owns a *block table* — a [max_blocks] i32 row mapping logical
context blocks to physical pool blocks. All allocation state (free list,
refcounts, prefix-sharing pool) lives here on the host; the device only
ever sees the tables as a small [S, max_blocks] i32 array.

Design points:

  * **Reservation, not preemption.** A sequence is admitted only when the
    pool can cover its worst case (``min(prompt + max_new, max_ctx)``
    tokens), so a mid-decode dispatch can never run out of blocks — there
    is no preemption/recompute path to get wrong. Capacity overcommit
    comes from ``max_new_tokens`` being far below ``max_ctx`` for real
    traffic, and from prefix sharing.
  * **Whole-block prefix sharing.** When a finished admission's prompt is
    registered, each *full* block of the prompt is keyed by a running hash
    of the tokens it covers and kept in a pool (refcounted). A later
    prompt sharing the same leading blocks maps them into its table
    read-only and computes only the tail — chunked prefill then starts at
    a block boundary. Writes never touch a shared block: a sequence's
    write frontier always lies past its shared prefix.
  * **Block 0 is the trash block.** The decode program writes a KV row for
    every slot each step, active or not (static shapes). Released slots'
    device table rows are reset to all-zeros so those garbage writes land
    in a reserved scratch block that no table maps for real data.

All mutation happens on the scheduler's engine thread; the lock only
guards the read side (metrics scrapes from API threads).

Topology-blindness: under a device mesh the pool shards its kv-head
axis over 'model' (parallel.sharding.paged_kv_spec) while THIS allocator
stays host-side with its block ids global — every device walks any
slot's table against its own head shard, so admission, refcounts, and
prefix sharing are identical on one chip and on eight. Nothing in this
module may ever depend on the mesh.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from localai_tpu.faults import registry as _faults


def block_tokens_default() -> int:
    """Tokens per KV block (``LOCALAI_KV_BLOCK_TOKENS``, default 64)."""
    try:
        v = int(os.environ.get("LOCALAI_KV_BLOCK_TOKENS", "64"))
    except ValueError:
        return 64
    return max(8, v)


@dataclasses.dataclass
class BlockStats:
    total: int          # allocatable blocks (pool minus the trash block)
    free: int           # immediately free
    cached: int         # prefix-pool blocks reclaimable on demand
    used: int           # referenced by at least one live sequence
    high_watermark: int  # max concurrently-used blocks since init
    spec_reserved: int = 0  # blocks held purely for speculative lookahead

    @property
    def available(self) -> int:
        return self.free + self.cached

    @property
    def utilization(self) -> float:
        return self.used / self.total if self.total else 0.0


class BlockAllocator:
    """Free list + per-sequence block tables + refcounted prefix pool."""

    def __init__(self, num_blocks: int, block_tokens: int,
                 max_blocks_per_seq: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is the trash block)")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self.max_blocks_per_seq = max_blocks_per_seq
        self._lock = threading.Lock()
        # block 0 reserved: the garbage-write target for inactive slots
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._ref = np.zeros(num_blocks, np.int32)
        self._ref[0] = 1  # trash never allocated
        # seq (slot) -> list of physical block ids in logical order
        self.tables: dict[int, list[int]] = {}
        # how many leading blocks of each table are shared (read-only)
        self.shared_blocks: dict[int, int] = {}
        # speculation reservation: trailing blocks of a table held ONLY so
        # a draft window can overshoot the decode frontier (localai_tpu.
        # spec). Rollback is a runner-side position rollback — the blocks
        # stay reserved for the slot's lifetime and never enter the
        # prefix pool (register_prefix is prompt-keyed), so rejection
        # can't leak or share a speculation row.
        self.spec_blocks: dict[int, int] = {}
        # prefix pool: chain-hash of covered tokens -> block id, LRU order
        self._prefix: "OrderedDict[str, int]" = OrderedDict()
        self._block_key: dict[int, str] = {}
        self._watermark = 0
        # lifetime counters (telemetry)
        self.shared_tokens_total = 0
        self.evictions_total = 0
        # optional HBM→host spill tier under the prefix pool (fleet.
        # kveconomy.tiering.HostTier, attached by the runner): LRU pool
        # evictions pack their rows to host RAM instead of vanishing,
        # and a chain-walk miss re-onboards them. The allocator stays
        # device-blind — pack/load are runner callbacks.
        self._tier = None
        self._tier_pack = None
        self._tier_load = None
        self.spills_total = 0
        self.reloads_total = 0

    # -- sizing -----------------------------------------------------------

    def blocks_for(self, tokens: int) -> int:
        return max(1, -(-tokens // self.block_tokens))

    def _reclaimable(self) -> int:  # jaxlint: guarded-by(_lock)
        """Prefix-pool blocks held only by the pool (evictable). Caller
        holds the lock."""
        return sum(1 for b in self._prefix.values() if self._ref[b] == 1)

    # -- HBM→host tiering -------------------------------------------------

    def attach_tier(self, tier, *, pack, load) -> None:
        """Wire the host-RAM spill tier under the prefix pool.

        ``pack(bid) -> payload dict`` gathers one pool block's raw rows
        to host numpy; ``load(bid, payload)`` scatters them back —
        both are runner-owned so this module never touches the device.
        Call before serving starts (engine-thread mutation discipline
        applies once traffic flows)."""
        with self._lock:
            self._tier = tier
            self._tier_pack = pack
            self._tier_load = load

    def _spill(self, key: str, bid: int) -> None:  # jaxlint: guarded-by(_lock)
        """Best-effort park of an evicted pool block in the host tier.
        Caller holds the lock; the device gather is the price of not
        losing host-RAM-sized cache capacity — eviction is already the
        slow path."""
        try:
            payload = self._tier_pack(bid)  # jaxlint: disable=blocking-under-lock
            if payload is not None and self._tier.put(key, payload):
                self.spills_total += 1
        except Exception:  # noqa: BLE001 — a failed spill is a plain evict
            pass

    def _reload(self, key: str,
                exclude: list[int]) -> Optional[int]:  # jaxlint: guarded-by(_lock)
        """Re-onboard a spilled chain block into a free (or freshly
        evicted) pool block; returns its id as a pool-referenced prefix
        entry, or None. Caller holds the lock. ``exclude`` protects
        blocks already matched this walk from being picked as eviction
        victims (they carry only the pool reference until allocate()
        pins them)."""
        if not self._tier.contains(key):
            return None
        if self._free:
            bid = self._free.pop()
        else:
            bid = self._evict_one(exclude=exclude)
            if bid is None:
                return None
        payload = self._tier.take(key)
        if payload is None:  # raced away (budget churn)
            self._free.append(bid)
            return None
        try:
            self._tier_load(bid, payload)  # jaxlint: disable=blocking-under-lock
        except Exception:  # noqa: BLE001 — corrupt spill = miss, not error
            self._free.append(bid)
            return None
        self._prefix[key] = bid
        self._block_key[bid] = key
        self._ref[bid] = 1
        self.reloads_total += 1
        return bid

    def tier_stats(self) -> Optional[dict]:
        """The spill tier's accounting pane (None when tiering is off)."""
        with self._lock:
            tier = self._tier
            spills = self.spills_total
            reloads = self.reloads_total
        if tier is None:
            return None
        s = tier.stats()
        s["spills_total"] = spills
        s["reloads_total"] = reloads
        return s

    # -- prefix sharing ---------------------------------------------------

    @staticmethod
    def _chain(tokens: list[int], nb: int, bt: int) -> list[str]:
        """Running hash per full block: key i covers tokens[:(i+1)*bt]."""
        keys = []
        h = hashlib.sha1()
        for i in range(nb):
            # host token lists only — no device array ever enters here
            h.update(np.asarray(  # jaxlint: disable=host-sync-in-hot-path
                tokens[i * bt:(i + 1) * bt], np.int64).tobytes())
            keys.append(h.hexdigest())
        return keys

    def match_prefix(self, prompt: Optional[list[int]]) -> list[int]:
        """Physical block ids of the longest pool-cached full-block prefix
        of ``prompt``. Never covers the final prompt token (its logits must
        be recomputed to seed sampling), so at most (n-1)//bt blocks."""
        if not prompt:
            return []
        bt = self.block_tokens
        nb = (len(prompt) - 1) // bt
        if nb <= 0:
            return []
        out: list[int] = []
        with self._lock:
            for key in self._chain(prompt, nb, bt):
                bid = self._prefix.get(key)
                if bid is None and self._tier is not None:
                    # HBM miss, maybe a host-RAM hit: re-onboard the
                    # spilled block and keep walking the chain
                    bid = self._reload(key, exclude=out)
                if bid is None:
                    break
                out.append(bid)
        return out

    def register_prefix(self, seq: int, prompt: list[int]) -> int:
        """Insert ``seq``'s full prompt blocks into the prefix pool (each
        gains a pool reference). Call only after the blocks' contents have
        been dispatched to the device. Returns blocks registered."""
        if not prompt:
            return 0
        added = 0
        with self._lock:
            table = self.tables.get(seq)
            if table is None:
                return 0
            bt = self.block_tokens
            nb = min((len(prompt) - 1) // bt, len(table))
            for i, key in enumerate(self._chain(prompt, nb, bt)):
                if key in self._prefix:
                    self._prefix.move_to_end(key)
                    continue
                bid = table[i]
                if bid in self._block_key:  # already caches another chain
                    continue
                self._prefix[key] = bid
                self._block_key[bid] = key
                self._ref[bid] += 1
                added += 1
                if self._tier is not None:
                    # this chain just re-materialized in HBM from a fresh
                    # prefill — any spilled copy is now stale (a block is
                    # HBM-resident XOR spilled, audited by
                    # check_invariants)
                    self._tier.discard(key)
        return added

    def _evict_one(self, exclude: Optional[list[int]] = None,
                   ) -> Optional[int]:  # jaxlint: guarded-by(_lock)
        """Drop the LRU pool-only block; returns its id. Caller holds the
        lock. With a tier attached the victim's rows spill to host RAM
        first (best effort). ``exclude`` shields blocks a concurrent
        chain walk already claimed (pool-ref-only until allocate pins
        them) from victim selection."""
        shielded = set(exclude or ())
        victim = next((k for k, b in self._prefix.items()
                       if self._ref[b] == 1 and b not in shielded), None)
        if victim is None:
            return None
        bid = self._prefix.pop(victim)
        del self._block_key[bid]
        self._ref[bid] = 0
        self.evictions_total += 1
        if self._tier is not None:
            self._spill(victim, bid)
        return bid

    # -- allocate / release ----------------------------------------------

    def allocate(self, seq: int, tokens: int,
                 prompt: Optional[list[int]] = None,
                 spec_tokens: int = 0) -> Optional[int]:
        """Build ``seq``'s block table covering ``tokens + spec_tokens``
        rows, sharing pool-cached prompt prefix blocks where possible.
        ``spec_tokens`` extra rows are the slot's speculative-decoding
        lookahead (a draft window writes up to gamma rows past the decode
        frontier); the blocks they add beyond the base reservation are
        recorded as speculation blocks — pure reservation, audited by
        :meth:`check_invariants`, freed with the table at release.
        Returns the shared-token count, or None when the pool cannot
        cover the reservation (the caller queues the request). ``seq``
        must not already hold a table."""
        if _faults.ACTIVE and _faults.apply("paged.allocate",
                                            key=str(seq)) is not None:
            return None  # injected exhaustion: report the pool full
        nb = self.blocks_for(tokens + spec_tokens)
        nb_spec = nb - self.blocks_for(tokens)
        shared = self.match_prefix(prompt) if prompt else []
        shared = shared[: max(0, nb - 1)]  # at least one writable block
        with self._lock:
            assert seq not in self.tables, f"seq {seq} already has a table"
            # reference the shared blocks FIRST: a pool-only shared block
            # (ref==1) would otherwise be an eligible LRU eviction victim
            # in the fresh loop below and end up in the table twice —
            # once read-only, once writable
            for bid in shared:
                self._ref[bid] += 1
                key = self._block_key.get(bid)
                if key is not None:
                    self._prefix.move_to_end(key)
            need = nb - len(shared)
            if need > len(self._free) + self._reclaimable():
                for bid in shared:  # roll the reservation back
                    self._ref[bid] -= 1
                return None
            fresh: list[int] = []
            for _ in range(need):
                if not self._free:
                    evicted = self._evict_one()
                    assert evicted is not None
                    self._free.append(evicted)
                fresh.append(self._free.pop())
            for bid in fresh:
                self._ref[bid] = 1
            self.tables[seq] = shared + fresh
            self.shared_blocks[seq] = len(shared)
            if nb_spec:
                self.spec_blocks[seq] = nb_spec
            used = self.num_blocks - 1 - len(self._free) - self._reclaimable()
            self._watermark = max(self._watermark, used)
        n_shared = len(shared) * self.block_tokens
        self.shared_tokens_total += n_shared
        return n_shared

    def extend(self, seq: int, tokens: int, spec_tokens: int = 0) -> bool:
        """Grow ``seq``'s existing table to cover ``tokens + spec_tokens``
        rows (used when an admission resumes past disk-loaded rows);
        ``spec_tokens`` records the speculative lookahead exactly like
        :meth:`allocate`. False on exhaustion."""
        with self._lock:
            table = self.tables.get(seq)
            if table is None:
                return False
            nb = self.blocks_for(tokens + spec_tokens)
            nb_spec = nb - self.blocks_for(tokens)
            need = nb - len(table)
            if need <= 0:
                # the retained table already covers the reservation and
                # any lookahead: there is no distinct speculation tail to
                # account (recording one would make check_invariants
                # audit unrelated old tail blocks)
                self.spec_blocks.pop(seq, None)
                return True
            if need > len(self._free) + self._reclaimable():
                return False  # nothing recorded — nothing was reserved
            if nb_spec:
                self.spec_blocks[seq] = nb_spec
            else:
                self.spec_blocks.pop(seq, None)
            for _ in range(need):
                if not self._free:
                    evicted = self._evict_one()
                    assert evicted is not None
                    self._free.append(evicted)
                bid = self._free.pop()
                self._ref[bid] = 1
                table.append(bid)
            used = self.num_blocks - 1 - len(self._free) - self._reclaimable()
            self._watermark = max(self._watermark, used)
        return True

    def release(self, seq: int) -> None:
        with self._lock:
            table = self.tables.pop(seq, None)
            self.shared_blocks.pop(seq, None)
            self.spec_blocks.pop(seq, None)
            if table is None:
                return
            for bid in table:
                self._ref[bid] -= 1
                if self._ref[bid] == 0:
                    self._free.append(bid)

    # -- views ------------------------------------------------------------

    def table_row(self, seq: int) -> np.ndarray:
        """[max_blocks_per_seq] i32 device-shaped table row (trash-padded)."""
        row = np.zeros(self.max_blocks_per_seq, np.int32)
        with self._lock:
            t = list(self.tables.get(seq, []))
        row[: len(t)] = t[: self.max_blocks_per_seq]
        return row

    def tables_snapshot(self) -> dict[int, int]:
        """{seq: table length} under the lock — the /debug/kv view (the
        engine thread inserts/pops tables concurrently; iterating the
        live dict from an API thread would race the mutation)."""
        with self._lock:
            return {seq: len(t) for seq, t in self.tables.items()}

    def stats(self) -> BlockStats:
        with self._lock:
            free = len(self._free)
            cached = self._reclaimable()
            total = self.num_blocks - 1
            return BlockStats(
                total=total,
                free=free,
                cached=cached,
                used=total - free - cached,
                high_watermark=self._watermark,
                spec_reserved=sum(self.spec_blocks.values()),
            )

    def check_invariants(self) -> list[str]:
        """Block-conservation audit from refcount ground truth. Returns
        violation strings (empty = healthy). Invariants:

          * every allocatable block is exactly one of {free, referenced};
            free blocks carry refcount 0, referenced ones ≥ 1 — so
            ``free + used + cached == total`` by construction;
          * the free list holds no duplicates and never the trash block;
          * every table block id is a live (ref ≥ 1) non-trash block, and
            a table's shared leading blocks are also pool-referenced
            (ref ≥ 2);
          * every prefix-pool chain entry maps to a live block and the
            key↔block indices agree.

        O(blocks + table rows) under the lock — called from scheduler
        drains only behind ``LOCALAI_KV_CHECK`` and from every chaos
        scenario, surfaced at ``/debug/kv``."""
        problems: list[str] = []
        with self._lock:
            free_set = set(self._free)
            if len(free_set) != len(self._free):
                problems.append("free list holds duplicate block ids")
            if 0 in free_set:
                problems.append("trash block 0 is on the free list")
            if self._ref[0] < 1:
                problems.append("trash block 0 lost its standing reference")
            for bid in range(1, self.num_blocks):
                ref = int(self._ref[bid])
                if bid in free_set and ref != 0:
                    problems.append(
                        f"block {bid} is free but has refcount {ref}")
                if bid not in free_set and ref < 1:
                    problems.append(
                        f"block {bid} leaked: refcount {ref}, not free")
            for seq, table in self.tables.items():
                shared = self.shared_blocks.get(seq, 0)
                for i, bid in enumerate(table):
                    if bid == 0:
                        problems.append(f"seq {seq} table maps trash block")
                        continue
                    if bid in free_set:
                        problems.append(
                            f"seq {seq} table block {bid} is on the "
                            "free list")
                    want = 2 if i < shared else 1
                    if int(self._ref[bid]) < want:
                        problems.append(
                            f"seq {seq} {'shared ' if i < shared else ''}"
                            f"block {bid} refcount {int(self._ref[bid])} "
                            f"< {want}")
            for seq, nspec in self.spec_blocks.items():
                table = self.tables.get(seq)
                if table is None:
                    problems.append(
                        f"seq {seq} holds a speculation reservation "
                        f"({nspec} blocks) but no table")
                    continue
                if nspec < 0 or nspec > len(table):
                    problems.append(
                        f"seq {seq} speculation reservation {nspec} "
                        f"outside its table of {len(table)} blocks")
                    continue
                # speculation blocks are the table TAIL and must never be
                # shared through the prefix pool (a rejected draft row in
                # a shared block would poison every sharer)
                for bid in table[len(table) - nspec:]:
                    if bid in self._block_key:
                        problems.append(
                            f"seq {seq} speculation block {bid} leaked "
                            "into the prefix pool")
            for key, bid in self._prefix.items():
                if int(self._ref[bid]) < 1:
                    problems.append(
                        f"cached chain block {bid} refcount "
                        f"{int(self._ref[bid])} < 1")
                if self._block_key.get(bid) != key:
                    problems.append(
                        f"prefix pool and block-key index disagree on "
                        f"block {bid}")
            if len(self._block_key) != len(self._prefix):
                problems.append("block-key index size != prefix pool size")
            if self._tier is not None:
                # tier residency: a chain lives in the HBM pool XOR the
                # host tier — double residency means a reload forgot to
                # consume the spill (stale host rows would shadow newer
                # HBM contents on the next churn cycle)
                hbm_keys = set(self._prefix)
                for key in self._tier.keys():
                    if key in hbm_keys:
                        problems.append(
                            f"chain {key[:12]}… resident in the HBM pool "
                            "AND spilled to the host tier")
                # host-side accounting under the tier's own fine lock,
                # not a device/RPC round-trip
                ts = self._tier.stats()  # jaxlint: disable=blocking-under-lock
                if ts["bytes"] > ts["budget_bytes"]:
                    problems.append(
                        f"host tier over budget: {ts['bytes']} bytes "
                        f"held vs {ts['budget_bytes']} budgeted")
            # conservation, derived INDEPENDENTLY of stats() (whose
            # ``used`` is total - free - cached by construction): every
            # live block must be reachable from a table or the prefix
            # pool, and the reachable census must add up block by block
            table_ids = {bid for t in self.tables.values() for bid in t}
            pool_ids = set(self._prefix.values())
            live = {bid for bid in range(1, self.num_blocks)
                    if int(self._ref[bid]) > 0 and bid not in free_set}
            for bid in sorted(live - table_ids - pool_ids):
                problems.append(
                    f"block {bid} leaked: refcount {int(self._ref[bid])} "
                    "but referenced by no table or pool entry")
            free = len(self._free)
            cached = self._reclaimable()
            total = self.num_blocks - 1
            used = total - free - cached
            used_census = len(
                (table_ids | pool_ids)
                - {bid for bid in pool_ids if int(self._ref[bid]) == 1})
            if used_census != used:
                problems.append(
                    f"conservation broken: {used_census} blocks live in "
                    f"tables/pool vs used {used} "
                    f"(free {free}, cached {cached}, total {total})")
        return problems
