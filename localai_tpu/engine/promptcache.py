"""Disk-persistent prompt KV cache.

Parity: ``prompt_cache_path`` / ``prompt_cache_all`` / ``prompt_cache_ro``
(/root/reference/core/config/backend_config.go:120-122, proto
backend.proto:132-138) — llama.cpp persists a session's KV state to a file
and reloads it to skip recomputing a shared prompt prefix across restarts.

TPU redesign: instead of one mmap'd session file, a directory of npz blobs
keyed by the sha256 of the cached token sequence, plus an ``index.json``
mapping key → tokens. On admit, the scheduler looks up the entry with the
longest common prefix against the incoming prompt and loads its KV rows
straight into the slot cache (``ModelRunner.load_prefix``); the existing
suffix-prefill path then computes only the tail — the disk tier simply
feeds the same prefix-reuse machinery the in-memory resident records use
(engine/runner.py ``reusable_prefix``). Writes go through tmp+rename so a
crash never leaves a torn entry; the directory is LRU-capped by mtime.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
from pathlib import Path
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass
class CacheHit:
    tokens: list[int]       # the stored sequence (resident-record shaped)
    arrays: dict            # k/v (+ scales) rows for tokens[:n]
    n: int                  # cached KV rows
    # no lcp field: the scheduler re-scores the hit through
    # ModelRunner.reusable_prefix(valid_n=n) so one definition (with all
    # feasibility gates) decides both ranking and admit behavior


class PromptKVCache:
    """One directory of (index.json, <key>.npz) entries."""

    def __init__(self, path: str | os.PathLike, *, read_only: bool = False,
                 max_entries: int = 32, min_prefix: int = 16):
        self.dir = Path(path)
        self.read_only = read_only
        self.max_entries = max_entries
        self.min_prefix = min_prefix
        if not self.dir.exists() and not read_only:
            self.dir.mkdir(parents=True, exist_ok=True)
        # lookup() runs on the scheduler engine thread while store()/_evict()
        # run on the prompt-cache writer thread — every _index access (and
        # the index-file write) goes through this lock
        self._lock = threading.Lock()
        self._index: dict[str, list[int]] = {}
        self._load_index()
        # telemetry (scraped through Scheduler.metrics → /metrics)
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.hit_tokens = 0  # KV rows handed back by successful lookups

    # -- index ------------------------------------------------------------

    def _index_path(self) -> Path:
        return self.dir / "index.json"

    # __init__-only: runs before the cache object is shared across threads
    def _load_index(self) -> None:  # jaxlint: disable=lock-guarded-attr
        try:
            raw = json.loads(self._index_path().read_text())
            self._index = {k: list(map(int, v)) for k, v in raw.items()}
        except (OSError, ValueError):
            self._index = {}

    def _write_index(self) -> None:  # jaxlint: guarded-by(_lock)
        tmp = self._index_path().with_suffix(".tmp")
        tmp.write_text(json.dumps(self._index))
        tmp.replace(self._index_path())

    @staticmethod
    def _key(tokens: list[int]) -> str:
        return hashlib.sha256(
            np.asarray(tokens, np.int64).tobytes()
        ).hexdigest()[:32]

    # -- public -----------------------------------------------------------

    def lookup(self, prompt: list[int]) -> Optional[CacheHit]:
        """Entry with the longest common prefix ≥ min_prefix, or None."""
        best_key, best_tokens, best_lcp = None, None, 0
        with self._lock:
            items = list(self._index.items())
        for key, tokens in items:
            lcp = 0
            for a, b in zip(tokens, prompt):
                if a != b:
                    break
                lcp += 1
            if lcp > best_lcp:
                best_key, best_tokens, best_lcp = key, tokens, lcp
        # the last prompt token is always recomputed (its logits seed
        # sampling), so a full-prompt hit still leaves a 1-token tail
        best_lcp = min(best_lcp, len(prompt) - 1)
        if best_key is None or best_lcp < self.min_prefix:
            self.misses += 1
            return None
        path = self.dir / f"{best_key}.npz"
        try:
            with np.load(path) as z:
                arrays = {name: z[name] for name in z.files}
        except (OSError, ValueError) as e:
            log.warning("prompt cache entry %s unreadable: %s", best_key, e)
            with self._lock:
                self._index.pop(best_key, None)
            self.misses += 1
            return None
        n = int(arrays["k"].shape[2])
        try:  # LRU touch
            os.utime(path)
        except OSError:
            pass
        self.hits += 1
        self.hit_tokens += n
        return CacheHit(tokens=list(best_tokens), arrays=arrays, n=n)

    def store(self, tokens: list[int], arrays: dict) -> None:
        """Persist KV rows for ``tokens[:n]`` (n = arrays['k'].shape[2])."""
        if self.read_only:
            return
        n = int(arrays["k"].shape[2])
        if n < self.min_prefix:
            return
        key = self._key(tokens)
        with self._lock:
            if key in self._index:
                return
        self.dir.mkdir(parents=True, exist_ok=True)
        path = self.dir / f"{key}.npz"
        tmp = self.dir / f".{key}.tmp.npz"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        tmp.replace(path)
        with self._lock:
            self._index[key] = list(map(int, tokens))
            self._write_index()
        self.stores += 1
        self._evict()

    def _evict(self) -> None:
        with self._lock:
            if len(self._index) <= self.max_entries:
                return
            entries = []
            for key in list(self._index):
                p = self.dir / f"{key}.npz"
                try:
                    entries.append((p.stat().st_mtime, key))
                except OSError:
                    self._index.pop(key, None)
            entries.sort()
            for _, key in entries[: len(self._index) - self.max_entries]:
                (self.dir / f"{key}.npz").unlink(missing_ok=True)
                self._index.pop(key, None)
            self._write_index()

    def stats(self) -> dict:
        with self._lock:
            n_entries = len(self._index)
        return {
            "entries": n_entries,
            "hits": self.hits,
            "misses": self.misses,
            "stores": self.stores,
            "hit_tokens": self.hit_tokens,
        }
