"""Self-extend / group attention: serving beyond the trained context.

Parity: llama.cpp's ga_n/ga_w slot options (/root/reference/backend/cpp/
llama/grpc-server.cpp:210-211,528-539,1870-1895) — there implemented by
periodically REWRITING cached KV positions (seq_add/seq_div + K-shift
re-rotation). That design is hostile to XLA (in-place cache surgery,
data-dependent loop); the TPU redesign keeps the cache UNroped in
self-extend mode and computes BOTH attention score sets per step —
neighbor (exact relative positions) and grouped (positions floor-divided
by ga_n, the SelfExtend formulation, arXiv:2401.01325) — merging them by
relative distance inside one fused program. No cache rewrites, no extra
dispatches; the cost is a second QK^T over the same cache bytes already
in registers.

Positions: for query position p and key position j
  neighbor score  : rope(p) · rope(j)         used where  p - j <  ga_w
  grouped score   : rope(p//g + ga_w - ga_w//g) · rope(j//g)   otherwise
The +ga_w - ga_w//g query shift keeps the two branches continuous at the
window boundary (the paper's w_n - w_n//g offset).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from localai_tpu.models import llama as mdl
from localai_tpu.models.llama import LlamaConfig


def identity_rope(rope) -> tuple[jax.Array, jax.Array]:
    """A (cos=1, sin=0) table shaped like ``rope`` — models.llama.forward
    then leaves q/k UNrotated, and the self-extend attend applies all
    rotations itself."""
    cos, sin = rope
    return jnp.ones_like(cos), jnp.zeros_like(sin)


def build_attend(cfg: LlamaConfig, rope, ga_n: int, ga_w: int,
                 qpos: jax.Array, kpos: jax.Array):
    """attend(q, keys, values, mask) for the XLA engine paths.

    q [S, T, Hq, hd] and keys/values [S, Hkv, C, hd] arrive UNroped
    (identity_rope upstream). qpos [S, T] / kpos [C] are absolute
    positions; mask [S, T, C] bool is the normal causal/validity mask.
    """
    cos_t, sin_t = rope
    shift = ga_w - ga_w // ga_n

    def rope_q(x, pos):                       # x [S, T, Hq, hd], pos [S, T]
        return mdl.apply_rope(
            x, cos_t[pos][:, :, None, :], sin_t[pos][:, :, None, :])

    def rope_k(keys, pos):                    # keys [S, Hkv, C, hd], pos [C]
        return mdl.apply_rope(
            keys, cos_t[pos][None, None, :, :], sin_t[pos][None, None, :, :])

    def attend(q, keys, values, mask):
        S, T = q.shape[0], q.shape[1]
        Hkv, g, hd = cfg.num_kv_heads, cfg.q_per_kv, cfg.hd
        limit = cos_t.shape[0] - 1

        def scores(qr, kr):
            qg = qr.reshape(S, T, Hkv, g, hd)
            return jnp.einsum("stkgh,sklh->skgtl", qg, kr) / math.sqrt(hd)

        s_n = scores(rope_q(q, qpos), rope_k(keys, kpos))
        q_g = jnp.minimum(qpos // ga_n + shift, limit)
        s_g = scores(rope_q(q, q_g), rope_k(keys, kpos // ga_n))
        dist = qpos[:, :, None] - kpos[None, None, :]        # [S, T, C]
        s = jnp.where(dist[:, None, None] < ga_w, s_n, s_g)
        s = s.astype(jnp.float32)
        s = jnp.where(mask[:, None, None], s, -1e30)
        probs = jax.nn.softmax(s, axis=-1).astype(values.dtype)
        out = jnp.einsum("skgtl,sklh->stkgh", probs, values)
        return out.reshape(S, T, cfg.num_heads, hd)

    return attend
