"""Continuous-batching scheduler: the serving loop over ModelRunner.

TPU-era redesign of llama.cpp's slot engine (`update_slots`, task queue and
deferred-task handling — /root/reference/backend/cpp/llama/
grpc-server.cpp:1546-1990, utils.hpp:192-357):

  * requests queue on the host; a single engine thread admits them into free
    slots (prefill) and then advances ALL active slots with one compiled
    decode step per iteration — continuous batching is slot masking inside a
    static-shape program, not ragged batch rebuilds.
  * per-request streams: each request owns a thread-safe queue of text
    deltas; SSE writers drain it without touching the engine thread.
  * stop handling: EOS ids, stop strings (with split-across-tokens holdback),
    max_tokens, context exhaustion (slot released at n_ctx — parity with the
    reference's no-context-shift policy, grpc-server.cpp:1573-1592).
  * grammar constraints: an optional per-request TokenConstraint advances an
    FSM on the host and writes a -1e30 mask row into the device bias before
    the next step (see localai_tpu.functions for the FSM compiler).
  * metrics: per-slot prompt/generated token counts and tokens/sec — the
    GetMetrics surface (grpc-server.cpp:2434-2457).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Any, Optional, Protocol, Sequence

import numpy as np

from localai_tpu.engine.runner import NAN_TOKEN, ModelRunner
from localai_tpu.engine.stream import IncrementalDetokenizer, StopChecker
from localai_tpu.faults import registry as _faults
from localai_tpu.obs import anatomy as obs_anatomy
from localai_tpu.obs import compile as obs_compile
from localai_tpu.obs import flight as obs_flight
from localai_tpu.obs import ledger as obs_ledger
from localai_tpu.obs import profiler as obs_profiler
from localai_tpu.obs import watchdog as obs_watchdog
from localai_tpu.obs.engine import EngineTelemetry

log = logging.getLogger(__name__)


class _EngineAbandoned(Exception):
    """Raised inside a fenced-off engine thread (its epoch was bumped by
    a rebuild while it sat in a blocked round-trip): exit without
    touching the rebuilt engine's state."""


# admission lanes: interactive requests (API traffic with a client
# waiting) are admitted strictly before background batch work — a batch
# line only fills a slot when no interactive request is queued, so
# offline jobs soak idle capacity without touching interactive TTFT.
PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 1


class TokenConstraint(Protocol):
    """Grammar/JSON-schema constraint driven by the scheduler.

    ``allowed_mask`` returns a [V] f32 additive bias row (0 allowed, -1e30
    disallowed) or None for "anything"; ``advance`` consumes the sampled
    token; ``done`` means the constrained region is complete.
    """

    def allowed_mask(self) -> Optional[np.ndarray]: ...
    def advance(self, token_id: int) -> None: ...
    @property
    def done(self) -> bool: ...


@dataclasses.dataclass
class GenRequest:
    """One generation request (the scheduler-facing request schema)."""

    prompt: list[int]
    max_new_tokens: int = 256
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    min_p: Optional[float] = None
    repeat_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    seed: Optional[int] = None
    logit_bias: Optional[dict[int, float]] = None
    stop: Sequence[str] = ()
    ignore_eos: bool = False
    constraint: Optional[TokenConstraint] = None
    correlation_id: str = ""
    # tracing: groups this request's lifecycle spans with the HTTP span
    # that spawned it (obs subsystem); crosses the worker RPC boundary as
    # gRPC metadata (worker.rpc.trace_metadata)
    trace_id: str = ""
    # usage accounting (obs.ledger): the derive_tenant() bucket of the
    # request's API key — NEVER the raw key. Non-empty means "feed the
    # cost ledger at the terminal event"; crosses the worker RPC boundary
    # as gRPC metadata (worker.rpc.tenant_metadata)
    tenant: str = ""
    # an SSE client is attached: the scheduler bounds delivery lag by
    # shrinking the per-dispatch step count while this request is active
    stream: bool = False
    # multimodal injection: image-embedding rows [n_mm, D] scattered over
    # placeholder token positions [n_mm] during prefill (see ModelRunner)
    mm_embeds: Optional[Any] = None
    mm_positions: Optional[Any] = None
    # admission lane: PRIORITY_BATCH requests queue on the background lane
    # and are admitted only when the interactive lane is empty
    priority: int = PRIORITY_INTERACTIVE


class StreamItem:
    """Sentinel-free stream element: text delta or end-of-stream marker."""

    __slots__ = ("delta", "token_id", "finish_reason")

    def __init__(self, delta: str, token_id: Optional[int],
                 finish_reason: Optional[str]):
        self.delta = delta
        self.token_id = token_id
        self.finish_reason = finish_reason


class GenHandle:
    """Per-request handle: iterate deltas (streaming) or join for the full
    result. Filled by the engine thread."""

    def __init__(self, req: GenRequest, rid: int):
        self.request = req
        self.id = rid
        self._q: "queue.Queue[StreamItem]" = queue.Queue()
        self.text = ""
        self.token_ids: list[int] = []
        self.finish_reason: Optional[str] = None
        self.prompt_tokens = len(req.prompt)
        self._done = threading.Event()
        self.cancelled = False
        # perf (parity: per-slot timings grpc-server.cpp:1650,1661)
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        # lifecycle trace (obs.RequestTrace), attached by the scheduler
        self.trace = None
        # live-migration export flag (fleet.kveconomy): a migration
        # cancels the request but still needs its prompt+generation KV
        # snapshotted into the prompt cache at release — set by the
        # replica's migrate_out before cancel()
        self.migrate_export = False
        # NaN-guard receipt: set by Scheduler._poisoned just before the
        # error release, so the ledger classifies the waste as
        # nan_quarantine instead of a generic error
        self.nan_poisoned = False
        # global admission order (engine thread stamps it in _start):
        # lane-ordering tests and forensics read it; None until admitted
        self.admit_index: Optional[int] = None

    # engine-thread side -------------------------------------------------
    def _emit(self, delta: str, token_id: Optional[int]) -> None:
        if self.t_first_token is None:
            self.t_first_token = time.monotonic()
        if token_id is not None:
            self.token_ids.append(token_id)
        if delta:
            self.text += delta
        if delta or token_id is not None:
            self._q.put(StreamItem(delta, token_id, None))

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self.t_done = time.monotonic()
        self._q.put(StreamItem("", None, reason))
        self._done.set()

    # consumer side ------------------------------------------------------
    def cancel(self) -> None:
        """Request cancellation; the engine releases the slot on next step."""
        self.cancelled = True

    def __iter__(self):
        while True:
            item = self._q.get()
            yield item
            if item.finish_reason is not None:
                return

    def result(self, timeout: Optional[float] = None) -> "GenHandle":
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not finished")
        return self

    @property
    def completion_tokens(self) -> int:
        return len(self.token_ids)

    @property
    def tokens_per_second(self) -> float:
        if self.t_first_token is None:
            return 0.0
        end = self.t_done or time.monotonic()
        dt = end - self.t_first_token
        return (len(self.token_ids) - 1) / dt if dt > 0 else 0.0


@dataclasses.dataclass
class _PendingPrefill:
    """A chunked paged admission in flight: the engine loop dispatches one
    chunk per iteration (interleaved with decode dispatches) until the
    final chunk samples the first token and the slot goes live."""

    slot: int
    handle: GenHandle
    adm: Any                 # engine.runner.PagedAdmission
    base: Optional[np.ndarray]
    mask_set: bool


@dataclasses.dataclass
class _SlotCtx:
    """Host-side state for one occupied slot."""

    handle: GenHandle
    detok: IncrementalDetokenizer
    stopper: StopChecker
    generated: int = 0
    base_bias: Optional[np.ndarray] = None  # [V] row from logit_bias
    mask_set: bool = False                  # constraint mask currently on device
    admit_seq: int = 0                      # dispatch counter at admit time:
                                            # tokens from dispatches issued
                                            # before admission are not ours


class Scheduler:
    """Owns one ModelRunner + tokenizer; runs the engine thread."""

    def __init__(self, runner: ModelRunner, tokenizer: Any,
                 *, default_max_tokens: int = 2048, pipeline_depth: int = 2,
                 multi_step: int = 16, stream_latency_target: float = 0.1,
                 spec: Optional[Any] = None,
                 prompt_cache: Optional[Any] = None,
                 prompt_cache_all: bool = False,
                 telemetry: Optional[EngineTelemetry] = None,
                 watchdog: Optional[obs_watchdog.Watchdog] = None,
                 flight: Optional[obs_flight.FlightRecorder] = None):
        self.runner = runner
        self.tokenizer = tokenizer
        # request-lifecycle spans + engine histograms (obs subsystem); the
        # manager names it after the model, tests may inject their own
        self.telemetry = telemetry or EngineTelemetry()
        # the ledger's KV-block-seconds unit follows this runner's actual
        # paged block size (contiguous runners keep the 16-token default)
        self.telemetry.kv_block_tokens = getattr(runner, "block_tokens", 16)
        # stall watchdog: every blocking device round-trip this engine
        # makes (drain here, syncs inside the runner) is heartbeat-guarded;
        # no progress past the deadline → engine_stalled gauge + a
        # thread-stack forensic span (obs.watchdog). The runner shares the
        # instance so "device" and "engine" channels trip together.
        self.watchdog = watchdog or obs_watchdog.WATCHDOG
        runner.watchdog = self.watchdog
        self._wd_channel = (f"engine:{self.telemetry.model}"
                            if self.telemetry.model else "engine")
        self.watchdog.start()
        # flight recorder: one per-dispatch record from every drain, all
        # host mirrors this thread already holds (zero device syncs, no
        # per-record allocation — the ring is preallocated numpy columns).
        # Windowed step-time percentiles come from here; snapshots ride
        # every stall dump via the watchdog context provider below.
        self.flight = (flight if flight is not None
                       else obs_flight.FlightRecorder())
        self._tokens_emitted = 0      # host-side token counter (_consume)
        self._flight_mark = 0         # emitted count at the last record
        self.watchdog.add_context(
            f"flight:{self._wd_channel}", self._flight_forensics
        )
        # anomaly profiler: the ring is watched (weakly) for step-time
        # p99 regressions against its own trailing window — a no-op dict
        # insert unless LOCALAI_PROFILE_ON_ANOMALY armed the manager
        obs_profiler.PROFILER.watch_flight(
            self.telemetry.model or "engine", self.flight)
        # speculative decoding (localai_tpu.spec.SpecEngine): when set and
        # no grammar constraint is active, dispatches run draft+verify
        # windows instead of plain multi-step decode — on BOTH KV layouts
        # (the paged verify writes through the block-table mirror into
        # speculation blocks reserved at admission). Slot lifecycle ops
        # route through the spec engine so the drafter's state mirrors the
        # target's. After any non-speculative dispatch (or a chunked
        # admission, which bypasses spec.admit) the drafts are stale —
        # _spec_dirty forces a per-slot resync before the next window. A
        # drafter may decline a window (self-drafting with no lookup hit
        # anywhere): that dispatch falls back to plain multi-step decode.
        self.spec = spec
        self._spec_dirty = False
        # slots admitted through the chunked path whose drafter seeding
        # is pending — resynced individually (a full-batch resync per
        # admission would cost O(slots) draft prefills for model
        # drafters)
        self._spec_stale: set[int] = set()
        self._engine = spec if spec is not None else runner
        # disk prompt-KV persistence (engine.promptcache): looked up when the
        # in-memory resident record can't cover the prompt; finished slots
        # store their prefix back (prompt only, or prompt+generation with
        # prompt_cache_all). Parity: backend_config.go:120-122.
        self.prompt_cache = prompt_cache
        self.prompt_cache_all = prompt_cache_all
        # stores run off-thread: the engine thread only enqueues a device
        # snapshot (cheap slice dispatches); the writer does the blocking
        # D2H copy + npz write so completions never stall the decode loop
        self._pc_queue: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._pc_thread: Optional[threading.Thread] = None
        if prompt_cache is not None and not prompt_cache.read_only:
            self._pc_thread = threading.Thread(
                target=self._pc_writer, name="prompt-cache", daemon=True
            )
            self._pc_thread.start()
        self.default_max_tokens = default_max_tokens
        self.pipeline_depth = max(1, pipeline_depth)
        # tokens decoded per dispatch (lax.scan inside one program): amortizes
        # the host→device dispatch RTT that dominates single-step decode on a
        # tunneled chip. Delivery lag ≈ multi_step×pipeline_depth×step-time;
        # when any active request has an SSE stream attached, the dispatch
        # size adapts down (power-of-two steps, so at most log2(multi_step)
        # program variants ever compile) to keep that product under
        # stream_latency_target seconds. Batch requests keep the full size.
        self.multi_step = max(1, multi_step)
        self.stream_latency_target = stream_latency_target
        self._step_ema: Optional[float] = None   # seconds per decoded token
        self._last_drain_t: Optional[float] = None
        # dispatch-anatomy accumulators (obs.anatomy): measured host-phase
        # seconds since the LAST flight record, taken-and-reset by
        # _take_anat() at each record. Engine-thread-only scratch.
        self._anat_sched_s = 0.0    # admit/select/host-mirror spans
        self._anat_launch_s = 0.0   # async jit call-return spans
        self._anat_overlap_s = 0.0  # wall other records already account
        self.last_dispatch_steps = 0             # observability + tests
        # program shapes already dispatched once: the FIRST dispatch of a
        # new step count includes XLA trace+compile time, which must not be
        # folded into the per-token EMA (one multi-second compile sample
        # would pin the adaptive size at 1 for a long recovery)
        self._seen_shapes: set = set()
        # chunked prefill (paged runners): admissions queue their prompt
        # chunks here and the engine loop interleaves ONE chunk per
        # iteration with decode dispatches, so a long prompt never stalls
        # other slots' TPOT. Paged spec engines chunk too — the drafter
        # is seeded from the resident record once the final chunk lands.
        self._chunked = bool(getattr(runner, "paged", False))
        self._prefills: "deque[_PendingPrefill]" = deque()
        self.total_prefill_chunks = 0
        # a request the paged block pool couldn't cover yet: admission is
        # FIFO, so it parks here (not back in the queue) until blocks free
        self._held: Optional[GenHandle] = None
        # two-lane admission: interactive requests drain strictly before
        # the background batch lane (see _next_pending)
        self._pending: "queue.Queue[GenHandle]" = queue.Queue()
        self._pending_batch: "queue.Queue[GenHandle]" = queue.Queue()
        self._admit_seq = 0
        self._slots: dict[int, _SlotCtx] = {}
        self._ids = itertools.count()
        self._wake = threading.Event()
        self._stopping = False
        self._lock = threading.Lock()
        self._dispatch_seq = 0
        # self-healing (faults.supervisor): rebuild() bumps _epoch so a
        # wedged engine thread — parked inside a device round-trip that
        # may never return — is fenced off and exits harmlessly when (if)
        # it unblocks, while a fresh thread takes over the re-initialized
        # runner state. rebuild()/mark_failed() run ONLY on the
        # supervisor's single recovery thread (its _recovering flag is
        # the serialization point), which owns the engine structures
        # exactly while the fenced thread is parked — the same single-
        # owner-thread design the engine loop itself uses. failed latches
        # after the supervisor exhausts its bounded rebuild attempts:
        # submit() then fails fast and the manager's dead-engine reload
        # path owns further recovery.
        self._epoch = 0
        self.failed = False
        self.rebuilds = 0
        self.supervisor = None          # set by EngineSupervisor
        # NaN/inf decode guard: a slot whose logits row went non-finite
        # fails only its own request and is quarantined (kept out of
        # admission) for a fixed number of dispatches — a transient blip
        # returns the slot to service, a poisoned cache region keeps
        # erroring visibly instead of silently corrupting co-batched
        # streams. Counters feed localai_nan_rows_total.
        self._quarantined: dict[int, int] = {}  # slot -> release dispatch
        self.nan_rows = 0
        try:
            self._nan_quarantine = int(os.environ.get(
                "LOCALAI_NAN_QUARANTINE_DISPATCHES", "16") or 16)
        except ValueError:
            self._nan_quarantine = 16
        # block-leak invariant sweep (engine.paged.check_invariants) on
        # every drain — debug builds and the chaos harness only; the
        # O(blocks) walk is too hot for production dispatch cadence
        self._kv_check = os.environ.get("LOCALAI_KV_CHECK", "") == "1"
        self.kv_invariant_violations = 0
        # per-slot resident tokens (prompt + generated) for KV prefix reuse
        self._resident: dict[int, list[int]] = {}
        # lifetime metrics (GetMetrics parity)
        self.total_prompt_tokens = 0
        self.total_generated_tokens = 0
        self.total_preemptions = 0  # cancelled / engine-error slot exits
        # requests refused by SLO admission control (API-level 429s); a
        # mirror for the JSON metrics surface — the registry counter is
        # owned by obs.slo (single-writer rule, see update_engine_gauges)
        self.shed_total = 0
        self._thread = threading.Thread(
            target=self._run, args=(0,), name="engine", daemon=True
        )
        self._thread.start()

    # -- public API ------------------------------------------------------

    def submit(self, req: GenRequest) -> GenHandle:
        handle = GenHandle(req, next(self._ids))
        handle.trace = self.telemetry.queued(handle)
        # failed-check and enqueue are one atomic step vs mark_failed()'s
        # terminal queue drain (which flips the flag under the same lock
        # BEFORE draining): a submit can land in the queue only while the
        # drain is still obligated to pop it — no handle is ever parked
        # on a dead engine unresolved
        with self._lock:
            rejected = self.failed
            if not rejected:
                lane = (self._pending_batch
                        if req.priority >= PRIORITY_BATCH
                        else self._pending)
                lane.put(handle)
        if rejected:
            # the supervisor exhausted its rebuild budget: fail fast with
            # a clean error instead of queueing onto a dead engine
            self.telemetry.finished(handle.trace, handle, "error",
                                    preempted=False)
            handle._finish("error")
            return handle
        self._wake.set()
        return handle

    def generate(self, req: GenRequest, timeout: float = 600.0) -> GenHandle:
        return self.submit(req).result(timeout)

    def attach_prompt_cache(self, prompt_cache: Any,
                            *, layer: bool = False) -> None:
        """Attach a prompt-KV cache after construction (fleet replicas get
        an in-memory PrefixCache lazily, on first PrefillPrefix/
        TransferPrefix use — see localai_tpu.fleet.prefix). No-op when a
        cache is already wired — unless ``layer=True`` and the existing
        cache lacks the store-signalling surface the disaggregation
        export blocks on (``wait_for``): then the new cache FRONTS it
        (``fallthrough``), so a configured disk prompt cache keeps
        working while the fleet handoff gets its RAM tier. Starts the
        off-thread writer for writable caches, exactly as __init__ would
        have. Safe while the engine thread runs: its reads are a single
        attribute load, and the new cache only affects admissions/
        releases that start after the set."""
        if prompt_cache is None:
            return
        if self.prompt_cache is not None:
            if not layer or hasattr(self.prompt_cache, "wait_for"):
                return
            prompt_cache.fallthrough = self.prompt_cache
            self.prompt_cache = prompt_cache
        else:
            self.prompt_cache = prompt_cache
        if not self.prompt_cache.read_only and self._pc_thread is None:
            self._pc_thread = threading.Thread(
                target=self._pc_writer, name="prompt-cache", daemon=True
            )
            self._pc_thread.start()

    @property
    # lock-free liveness poll: every term is an atomic read of an engine-
    # thread-owned structure; worker Status tolerates a one-iteration lag
    def busy(self) -> bool:  # jaxlint: disable=lock-guarded-attr
        return (bool(self._slots) or bool(self._prefills)
                or self._held is not None
                or not self._pending.empty()
                or not self._pending_batch.empty())

    def note_shed(self) -> None:
        """Record one SLO admission-control rejection against this engine
        (called by the API tier when it 429s a request for this model)."""
        with self._lock:
            self.shed_total += 1

    def metrics(self) -> dict:
        """Live engine metrics (parity: GetMetrics RPC,
        grpc-server.cpp:2434-2457).

        ``step_time_ema`` is SECONDS PER DECODED TOKEN (per-token, not
        per-dispatch — a k-step dispatch contributes dt/k), the lifetime
        smoothed estimate that drives the adaptive streaming dispatch
        size. ``step_ms_p50``/``step_ms_p99`` are its windowed
        counterparts in milliseconds, computed from the flight ring's
        resident dispatches (compile-bearing first dispatches excluded);
        None until a post-compile dispatch lands."""
        num_slots = self.runner.num_slots
        pct = self.flight.percentiles()
        anat = obs_anatomy.summarize(self.flight)
        with self._lock:
            active = [
                {
                    "slot": s,
                    "prompt_tokens_processed": c.handle.prompt_tokens,
                    "tokens_generated": c.handle.completion_tokens,
                    "tokens_per_second": c.handle.tokens_per_second,
                    "correlation_id": c.handle.request.correlation_id,
                }
                for s, c in self._slots.items()
            ]
            # alloc.stats() inside is host-side allocator accounting,
            # not a worker RPC — the name-based heuristic misreads it
            kv_utilization = self._kv_utilization()  # jaxlint: disable=blocking-under-lock
            batch_slots = sum(
                1 for c in self._slots.values()
                if c.handle.request.priority >= PRIORITY_BATCH
            )
            # capture the lifetime counters under the same lock: a scrape
            # must not interleave half-updated totals from a mid-dispatch
            # engine iteration
            totals = {
                "prompt": self.total_prompt_tokens,
                "generated": self.total_generated_tokens,
                "preemptions": self.total_preemptions,
                "shed": self.shed_total,
                "failed": self.failed,
            }
        paged_stats = {}
        alloc = getattr(self.runner, "allocator", None)
        if alloc is not None:
            st = alloc.stats()
            paged_stats = {
                "kv_block_tokens": self.runner.block_tokens,
                # kernel-impl receipt ("pallas" | "lax"): feeds the
                # localai_paged_kernel_impl series so a silent fallback
                # off the flash kernel is dashboard-visible
                "paged_attn_impl": (
                    "pallas"
                    if getattr(self.runner, "paged_attn_impl", "") ==
                    "pallas" else "lax"),
                "kv_dtype": str(self.runner.kv_dtype),
                "kv_blocks_total": st.total,
                # free = immediately free + reclaimable prefix-pool cache
                "kv_blocks_free": st.free + st.cached,
                "kv_blocks_used": st.used,
                "kv_blocks_cached": st.cached,
                "kv_block_watermark": st.high_watermark,
                "kv_blocks_spec_reserved": st.spec_reserved,
                "kv_overcommit_ratio": getattr(
                    self.runner, "kv_overcommit", 1.0),
                "kv_shared_tokens": alloc.shared_tokens_total,
                "prefill_chunks": self.total_prefill_chunks,
                "prefill_chunk_queue_depth": sum(
                    p.adm.chunks_remaining for p in list(self._prefills)
                ),
            }
            ts = alloc.tier_stats()
            if ts is not None:
                paged_stats.update({
                    "kv_tier_blocks": ts["entries"],
                    "kv_tier_bytes": ts["bytes"],
                    "kv_tier_budget_bytes": ts["budget_bytes"],
                    "kv_tier_spills": ts["spills_total"],
                    "kv_tier_reloads": ts["reloads_total"],
                })
        return {
            "active_slots": active,
            "num_slots": num_slots,
            "occupancy": len(active) / num_slots if num_slots else 0.0,
            "kv_utilization": kv_utilization,
            **paged_stats,
            "queue_depth": self._pending.qsize(),
            "batch_queue_depth": self._pending_batch.qsize(),
            "batch_slots": batch_slots,
            "total_prompt_tokens": totals["prompt"],
            "total_generated_tokens": totals["generated"],
            "prefix_tokens_reused": self.runner.total_prefix_reused,
            "last_dispatch_steps": self.last_dispatch_steps,
            "dispatches": self._dispatch_seq,
            "preemptions": totals["preemptions"],
            "shed_total": totals["shed"],
            # self-healing + NaN-guard surface (faults subsystem)
            "engine_state": "failed" if totals["failed"] else "serving",
            "rebuilds": self.rebuilds,
            "nan_rows": self.nan_rows,
            "quarantined_slots": len(self._quarantined),
            "kv_invariant_violations": self.kv_invariant_violations,
            "step_time_ema": self._step_ema,  # seconds per decoded token
            "step_ms_p50": pct["step_ms_p50"],
            "step_ms_p99": pct["step_ms_p99"],
            # dispatch anatomy (obs.anatomy): windowed host/device
            # attribution over the same ring the step percentiles read
            "host_overhead_fraction": anat["host_overhead_fraction"],
            "device_bubble_fraction": anat["device_bubble_fraction"],
            "dispatch_phase_ms": obs_anatomy.phase_quantiles(anat),
            **(
                {"prompt_cache": self.prompt_cache.stats()}
                if self.prompt_cache is not None else {}
            ),
            **(
                {"spec_acceptance_rate": self.spec.acceptance_rate,
                 "spec_windows": self.spec.total_windows,
                 "spec_accept_rate": self.spec.accept_rate,
                 "spec_draft_tokens": self.spec.total_proposed,
                 "spec_accepted_tokens": self.spec.total_accepted,
                 "spec_tokens_per_dispatch": self.spec.tokens_per_dispatch,
                 "spec_suppressed": self.spec.total_suppressed,
                 "spec_drafter": self.spec.drafter.name,
                 "spec_gamma": self.spec.gamma}
                if self.spec is not None else {}
            ),
        }

    def _kv_utilization(self) -> float:  # jaxlint: disable=lock-guarded-attr
        """Fraction of KV capacity holding live context. Paged runners
        report block-pool utilization (used / allocatable blocks — the
        allocator's own accounting, reservation included); contiguous
        runners keep the row-level estimate from the host token record.
        Caller must own ``_slots`` — hold ``_lock`` or be the engine
        thread (the only mutator)."""
        alloc = getattr(self.runner, "allocator", None)
        if alloc is not None:
            return alloc.stats().utilization
        num_slots = self.runner.num_slots
        max_ctx = self.runner.max_ctx
        if not num_slots:
            return 0.0
        kv_rows = sum(
            min(c.handle.prompt_tokens + c.generated, max_ctx)
            for c in self._slots.values()
        )
        return kv_rows / (num_slots * max_ctx)

    def _pc_writer(self) -> None:
        """Writer loop: materialize KV snapshots and persist them."""
        while True:
            item = self._pc_queue.get()
            if item is None:
                return
            tokens, snapshot = item
            try:
                self.prompt_cache.store(
                    tokens, self.runner.pack_prefix(snapshot)
                )
            except Exception as e:  # noqa: BLE001 — cache ≠ serving
                log.warning("prompt-cache store failed: %s", e)

    def _take_anat(self, dt: float, sync_s: float,
                   ) -> dict:  # jaxlint: disable=lock-guarded-attr
        """Take-and-reset the anatomy accumulators into phase ms for a
        record accounting the wall interval ``dt`` (seconds).

        Clamp order is by trust: the measured ``sync`` block first, then
        the measured ``launch`` spans, then accumulated ``sched`` (which
        may predate a non-pipelined record's issue→drain interval and is
        crowded out rather than stealing from measured phases), then the
        wall other records already account (``overlap`` — prefill-chunk
        records inside this interval must not double count as gap).
        ``gap`` is the remainder, so gap+sched+launch+sync <= dispatch_ms
        holds structurally for every record. Engine thread only."""
        wall = max(0.0, dt)
        sync = min(max(0.0, sync_s), wall)
        launch = min(self._anat_launch_s, wall - sync)
        sched = min(self._anat_sched_s, wall - sync - launch)
        overlap = min(self._anat_overlap_s, wall - sync - launch - sched)
        gap = max(0.0, wall - sync - launch - sched - overlap)
        self._anat_sched_s = 0.0
        self._anat_launch_s = 0.0
        self._anat_overlap_s = 0.0
        return {"gap_ms": gap * 1e3, "sched_ms": sched * 1e3,
                "launch_ms": launch * 1e3, "sync_ms": sync * 1e3}

    def _flight_record(self, program: str, steps: int, dt: float,
                       fresh: bool, spec_proposed: int = 0,
                       spec_accepted: int = 0, sync_s: float = 0.0,
                       phases: Optional[dict] = None,
                       ) -> None:  # jaxlint: disable=lock-guarded-attr
        """One flight-ring record at a drain point. Everything here is a
        host mirror this (engine) thread already owns — ``_slots`` is only
        mutated on this thread, token counts come from ``_consume`` — so
        the cost is a handful of scalar reads plus one in-place ring row
        write. Called AFTER ``_process_rows`` so occupancy/tokens reflect
        end-of-dispatch state. ``spec_proposed``/``spec_accepted`` are
        THIS dispatch's draft counts (speculative windows only).
        ``sync_s`` is the measured result-fetch block for this drain;
        phase attribution comes from _take_anat unless the caller passes
        a pre-built ``phases`` dict (prefill chunks, whose span must not
        consume the accumulators owed to the next decode record)."""
        emitted = self._tokens_emitted
        num_slots = self.runner.num_slots
        batch_slots = sum(
            1 for c in self._slots.values()
            if c.handle.request.priority >= PRIORITY_BATCH
        )
        if phases is None:
            phases = self._take_anat(dt, sync_s)
        self.flight.record(
            program=program,
            steps=steps,
            dispatch_ms=dt * 1e3,
            occupancy=len(self._slots) / num_slots if num_slots else 0.0,
            batch_slots=batch_slots,
            queue_depth=self._pending.qsize(),
            kv_utilization=self._kv_utilization(),
            tokens=emitted - self._flight_mark,
            preemptions=self.total_preemptions,
            spec_accept=(self.spec.acceptance_rate
                         if self.spec is not None else None),
            spec_proposed=spec_proposed,
            spec_accepted=spec_accepted,
            gap_ms=phases["gap_ms"],
            sched_ms=phases["sched_ms"],
            launch_ms=phases["launch_ms"],
            sync_ms=phases["sync_ms"],
            compile=fresh,
        )
        self._flight_mark = emitted
        if spec_proposed > spec_accepted:
            # rejected draft tokens are device work the ring never counts
            # as emitted — the waste decomposition's spec_rejected class
            # (a short-lock dict update; safe at drain cadence)
            obs_ledger.LEDGER.note_waste(
                "spec_rejected", tokens=spec_proposed - spec_accepted,
                model=self.telemetry.model or "engine")
        if self._kv_check:
            self._check_kv_invariants()

    def _check_kv_invariants(self) -> None:
        """Debug-flag drain sweep: the block allocator must conserve its
        pool (free + used + cached == total, refcount sanity) after every
        dispatch. Violations log, count, and feed
        localai_kv_invariant_violations_total — they mean a leak."""
        alloc = getattr(self.runner, "allocator", None)
        if alloc is None:
            return
        problems = alloc.check_invariants()
        if problems:
            self.kv_invariant_violations += len(problems)
            self.telemetry.registry.kv_invariant_violations.inc(
                len(problems), model=self.telemetry.model or "engine")
            log.error("KV block invariants violated: %s", problems)

    def _flight_forensics(self) -> dict:
        """Watchdog context provider: the last-N engine timeline attached
        to every ``kind="stall"`` forensic trace (host-only, cheap)."""
        return {
            "channel": self._wd_channel,
            "records": self.flight.snapshot(limit=32),
            **self.flight.percentiles(),
        }

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stopping = True
        self._wake.set()
        if self.supervisor is not None:
            self.supervisor.detach()
        self.watchdog.remove_context(f"flight:{self._wd_channel}")
        obs_profiler.PROFILER.unwatch_flight(
            self.telemetry.model or "engine")
        self._thread.join(timeout)
        if self._pc_thread is not None:
            self._pc_queue.put(None)  # flush: writer drains FIFO first
            self._pc_thread.join(timeout)
            self._pc_thread = None

    # -- self-healing (faults.supervisor drives these) -------------------

    def _fail_handle(self, handle: GenHandle, reason: str = "error",
                     *, preempted: bool = True) -> None:
        self.telemetry.finished(handle.trace, handle, reason,
                                preempted=preempted)
        handle._finish(reason)

    def rebuild(self, probe_timeout: float = 30.0) -> None:
        """Tear down and re-initialize the engine after a suspected-wedged
        dispatch (called by the EngineSupervisor, off-thread, while the
        engine thread is presumed parked inside a device round-trip that
        may never return).

        Sequence: fence the old engine thread off (epoch bump — it exits
        whenever its blocked call returns, without touching the rebuilt
        state), fail every request holding engine state with a clean
        ``error`` (the API tier maps that to a 5xx), re-initialize the
        runner's device state (fresh KV pool / decode state / tables —
        compiled programs survive), verify the device answers with a
        probe dispatch in an abandonable thread, then start a fresh
        engine thread that resumes the still-queued requests. Raises if
        the probe fails or times out — the supervisor escalates.

        Runs ONLY on the supervisor's single recovery thread (its
        ``_recovering`` flag is the serialization point): while the
        fenced engine thread is parked, that thread is the sole owner of
        the engine structures — the same single-owner-thread design the
        engine loop itself uses (``_lock`` still guards the cross-thread
        ``_slots`` views)."""
        if self.spec is not None and not getattr(
                self.spec, "supports_rebuild", False):
            raise RuntimeError(
                "engine rebuild is not supported with this speculative "
                "engine")
        if self._stopping:
            raise RuntimeError("scheduler is shutting down")
        self._epoch += 1
        epoch = self._epoch
        with self._lock:
            failed = list(self._slots.items())
            self._slots.clear()
            self.total_preemptions += len(failed) + len(self._prefills)
        log.warning("engine rebuild: fencing old engine thread "
                    "(epoch %d), draining %d active slots",
                    epoch - 1, len(failed))
        for _slot, ctx in failed:
            self._fail_handle(ctx.handle)
        for pf in list(self._prefills):
            self._fail_handle(pf.handle)
        self._prefills.clear()
        # the held request has no engine state (its reservation is
        # only attempted at admit) — it survives the rebuild and is
        # retried against the fresh pool, like the queued requests
        self._resident.clear()
        self._quarantined.clear()
        self._spec_dirty = False
        self._spec_stale.clear()
        self._last_drain_t = None
        # the fenced thread never exits its wedged guard, so its arm()
        # has no disarm(): drop the channel or the leaked armed count
        # fires a spurious stall (and rebuild) every idle gap forever
        self.watchdog.reset(self._wd_channel)
        self.runner.reinit()
        if self.spec is not None:
            # the drafter's device/host state referenced the old pool —
            # reset it alongside (SpecEngine.reinit)
            self.spec.reinit()
        self._probe(probe_timeout)
        self.rebuilds += 1
        self._thread = threading.Thread(
            target=self._run, args=(epoch,), name="engine", daemon=True
        )
        self._thread.start()
        self._wake.set()

    def _probe(self, timeout: float) -> None:
        """One real admit+release against the rebuilt runner, in a side
        thread so a still-dead device costs ``timeout`` seconds (and an
        abandoned daemon) instead of wedging the supervisor forever."""
        done = threading.Event()
        err: list = []

        def probe() -> None:
            slot = None
            try:
                slot = self.runner.acquire_slot()
                if slot is None:
                    raise RuntimeError("no free slot after reinit")
                self.runner.admit(slot, [1, 2, 3], temperature=0.0)
                self.runner.release(slot)
            except Exception as e:  # noqa: BLE001 — reported to the waiter
                err.append(e)
                if slot is not None:
                    try:
                        self.runner.release(slot)
                    except Exception:  # noqa: BLE001
                        pass
            finally:
                done.set()

        t = threading.Thread(target=probe, name="engine-probe", daemon=True)
        t.start()
        if not done.wait(timeout):
            raise RuntimeError(
                f"probe dispatch made no progress in {timeout}s")
        if err:
            raise RuntimeError(f"probe dispatch failed: {err[0]}")

    def mark_failed(self) -> None:
        """Terminal state: the supervisor exhausted its rebuild budget.
        Every queued/held request resolves with a clean error, future
        submits fail fast, and the engine thread is fenced off; the
        manager's dead-engine reload path owns any further recovery."""
        self._epoch += 1  # fence whatever engine thread still exists
        with self._lock:
            # flag flip and slot collection share the lock submit()'s
            # check-and-enqueue holds: every handle that beat the flip is
            # already in a queue the drain below will pop
            self.failed = True
            failed = list(self._slots.items())
            self._slots.clear()
            self.total_preemptions += len(failed)
        for _slot, ctx in failed:
            self._fail_handle(ctx.handle)
        for pf in list(self._prefills):
            self._fail_handle(pf.handle)
        self._prefills.clear()
        if self._held is not None:
            self._fail_handle(self._held, preempted=False)
            self._held = None
        while True:
            handle = self._next_pending()
            if handle is None:
                break
            self._fail_handle(handle, preempted=False)

    # -- fault injection (chaos harness; no-ops unless armed) ------------

    def _inject_slot_faults(self) -> None:
        """decode.nan site: poison the bias row of the first active slot
        whose correlation/trace id matches an armed spec — its next
        logits row goes NaN on device and the per-row guard must catch
        it. Runs only when faults.ACTIVE (never in production)."""
        with self._lock:
            slots = {s: c.handle.request for s, c in self._slots.items()}
        for slot, req in slots.items():
            key = req.correlation_id or req.trace_id or str(slot)
            spec = _faults.fire("decode.nan", key=key)
            if spec is None:
                continue
            row = np.full(self.runner.cfg.vocab_size, np.nan, np.float32)
            self._engine.set_bias(slot, row)

    def _poisoned(self, slot: int, ctx: _SlotCtx) -> None:
        """The device-side per-row finite guard flagged this slot's
        logits (NAN_TOKEN sentinel in the sampled row): fail ONLY the
        affected request with ``error`` and quarantine the slot for
        ``LOCALAI_NAN_QUARANTINE_DISPATCHES`` dispatches — co-batched
        slots keep streaming untouched."""
        self.nan_rows += 1
        self.telemetry.registry.nan_rows.inc(
            model=self.telemetry.model or "engine")
        log.error(
            "non-finite logits for slot %d (request %s): failing the "
            "request, quarantining the slot for %d dispatches",
            slot, ctx.handle.request.correlation_id or ctx.handle.id,
            self._nan_quarantine)
        # the ledger's waste class for this failure is nan_quarantine,
        # not a generic error — stamp before the release feeds telemetry
        ctx.handle.nan_poisoned = True
        self._release(slot, ctx, "error")
        # _release returned the slot to the free list; pull it back out
        # until the quarantine window passes
        if self._engine.acquire_slot(slot) is not None:
            self._quarantined[slot] = (
                self._dispatch_seq + self._nan_quarantine)

    def _unquarantine(self) -> None:
        for slot, release_at in list(self._quarantined.items()):
            if self._dispatch_seq >= release_at:
                del self._quarantined[slot]
                self._engine.release(slot)
                log.info("slot %d leaves NaN quarantine", slot)

    # -- engine thread ---------------------------------------------------

    def _run(self, epoch: int) -> None:
        """Engine-thread entry: run the loop until shutdown — or until a
        rebuild fences this thread off (``_epoch`` moved past ours while
        we sat in a blocked round-trip), in which case exit silently:
        the replacement thread owns the state now."""
        try:
            self._run_loop(epoch)
        except _EngineAbandoned:
            log.warning("engine thread (epoch %d) abandoned after rebuild",
                        epoch)

    # the engine thread is the SOLE mutator of _slots/_prefills/etc.;
    # its own lock-free reads here are the single-owner-thread design the
    # class docstring documents (the lock exists for cross-thread viewers)
    def _run_loop(self, epoch: int) -> None:  # jaxlint: disable=lock-guarded-attr
        # Pipelined multi-step decode: each dispatch advances all slots
        # multi_step tokens inside ONE compiled program (lax.scan), up to
        # pipeline_depth dispatches stay in flight, and each result's D2H
        # copy starts immediately (copy_to_host_async). The device never
        # waits for the host round-trip and the dispatch overhead is
        # amortized over multi_step tokens (see bench.py). Grammar
        # constraints need the sampled token on the host before the next
        # dispatch (the FSM mask feeds the next step), so constrained slots
        # run synchronously one token per dispatch — but via the frozen-slot
        # program the UNconstrained slots still ride the same dispatch for
        # multi_step tokens (one tool-call request no longer de-pipelines
        # the whole batch).
        inflight: deque[tuple[Any, int, int, bool, float, bool]] = deque()

        def drain_one() -> None:
            toks, seq, k, pipelined, t_issue, fresh = inflight.popleft()
            # the designed drain point: copy_to_host_async started this
            # D2H at dispatch time, so materializing here overlaps with
            # the next dispatch already running on device. Watchdog-guarded:
            # a dead tunnel parks this exact line forever, and the stall
            # forensics must say so.
            t_sync = time.monotonic()  # anatomy: the result-fetch block
            with self.watchdog.guard(self._wd_channel):
                if _faults.ACTIVE:  # chaos: wedge/raise inside the guard
                    _faults.apply("engine.drain", key=self._wd_channel)
                rows = np.asarray(toks)  # jaxlint: disable=host-sync-in-hot-path
            if self._epoch != epoch:
                # a rebuild replaced this engine while we were parked in
                # the round-trip above — the state is no longer ours
                raise _EngineAbandoned
            now = time.monotonic()
            sync_s = now - t_sync
            window = None
            if k == 0 and self.spec is not None:  # speculative window
                window = self.spec.observe_window(rows)
            # per-token timing for the adaptive streaming dispatch size:
            # when this dispatch was issued while another was still on the
            # device, the interval between drains is pure device time for
            # its k tokens; otherwise (pipeline_depth=1, or a draining
            # pipeline) issue→drain wall time is the estimate. The first
            # dispatch of a new program shape is skipped — it pays compile.
            if pipelined and self._last_drain_t is not None:
                dt = now - self._last_drain_t
            else:
                dt = now - t_issue
            # a spec window's effective step count is its measured yield:
            # mean emitted tokens per active slot-window this dispatch.
            # With speculation the default lane, these dispatches feed
            # the step-time percentiles and the EMA like any other —
            # excluding them would blind the timeline to the hot path.
            k_eff = k
            if window is not None:
                k_eff = (max(1, round(window["emitted"]
                                      / window["windows"]))
                         if window["windows"] else 0)
            if not fresh and k_eff > 0:
                self._observe_step_time(dt / k_eff)
                # measured per-dispatch latency feeds the compiled-program
                # cost catalog (achieved-vs-roofline at /debug/programs)
                obs_compile.note_latency(
                    "verify" if k == 0
                    else "decode_n" if k > 1 else "decode",
                    dt, steps=k_eff)
            self._last_drain_t = now
            if rows.ndim == 1:
                rows = rows[None]
            self._process_rows(rows, seq)
            # flight ring: spec windows carry their yield as steps plus
            # per-dispatch proposed/accepted counts (ROADMAP item 3:
            # accept-rate in the flight ring); compile-bearing dispatches
            # are flagged
            self._flight_record(
                "spec" if k == 0 else ("decode_n" if k > 1 else "decode"),
                k_eff, dt, fresh,
                spec_proposed=window["proposed"] if window else 0,
                spec_accepted=window["accepted"] if window else 0,
                sync_s=sync_s,
            )

        while not self._stopping and self._epoch == epoch:
            if _faults.ACTIVE:
                # decode.nan chaos: poison a matching active slot's bias
                # row so its next logits go non-finite — exercising the
                # real device-side guard end to end
                self._inject_slot_faults()
            t_adm = time.monotonic()
            admitted = self._admit_pending()
            adm_s = time.monotonic() - t_adm
            if admitted and not self._chunked:
                # one-shot admissions dispatch AND sync a full prefill
                # inside _admit_pending — device compute, not host
                # scheduling; overlap keeps it out of the next record's gap
                self._anat_overlap_s += adm_s
            else:
                self._anat_sched_s += adm_s
            # chunked prefill: ONE chunk per loop iteration, so pending
            # chunks and decode dispatches alternate — a long prompt
            # spreads its prefill across the batch's decode cadence
            # instead of stalling it
            chunked = self._step_prefill_chunk()
            if not self._slots:
                self._last_drain_t = None  # idle gap would pollute the EMA
                if inflight:
                    drain_one()
                    continue
                if self._prefills:
                    continue  # no decode work yet — keep chunking
                if not admitted and not chunked:
                    # true idle: the poll spans accumulated above belong
                    # to no future record — drop them
                    self._anat_sched_s = 0.0
                    self._anat_launch_s = 0.0
                    self._anat_overlap_s = 0.0
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                continue
            try:
                if _faults.ACTIVE:  # chaos: a device dispatch that raises
                    _faults.apply("engine.dispatch", key="decode")

                def constrained_slots() -> set[int]:
                    return {
                        s for s, c in self._slots.items()
                        if c.handle.request.constraint is not None
                    }

                if constrained_slots():
                    # sync mode: drain the pipeline so set_bias updates from
                    # processed tokens apply to the very next dispatch
                    if self.spec is not None:
                        # plain dispatches leave the drafts without KV for
                        # the tokens they decode — resync before next window
                        self._spec_dirty = True
                    while inflight:
                        drain_one()
                    constrained = constrained_slots()
                    if not self._slots or not constrained:
                        continue
                    steps = self._effective_steps()
                    self._dispatch_seq += 1
                    if len(constrained) == len(self._slots) or steps == 1:
                        fresh = self._fresh_shape(1)
                        t0 = time.monotonic()
                        rows = self.runner.step()[None]
                        dt = time.monotonic() - t0
                        # anatomy: the runner split its own wall into
                        # enqueue vs result-fetch — harvest the scratch
                        self._anat_launch_s += (
                            self.runner.last_launch_ms * 1e-3)
                        if not fresh:
                            self._observe_step_time(dt)
                            obs_compile.note_latency("decode", dt, steps=1)
                        self.last_dispatch_steps = 1
                        self._process_rows(rows, self._dispatch_seq)
                        self._flight_record(
                            "decode", 1, dt, fresh,
                            sync_s=self.runner.last_sync_ms * 1e-3)
                    else:
                        freeze = np.zeros(self.runner.num_slots, bool)
                        freeze[list(constrained)] = True
                        fresh = self._fresh_shape(("frozen", steps))
                        t0 = time.monotonic()
                        rows = self.runner.step_frozen_n(freeze, steps)
                        dt = time.monotonic() - t0
                        self._anat_launch_s += (
                            self.runner.last_launch_ms * 1e-3)
                        if not fresh:
                            self._observe_step_time(dt / steps)
                            obs_compile.note_latency(
                                "decode_frozen_n", dt, steps=steps)
                        self.last_dispatch_steps = steps
                        self._process_rows(
                            rows, self._dispatch_seq, frozen=constrained
                        )
                        self._flight_record(
                            "decode_frozen_n", steps, dt, fresh,
                            sync_s=self.runner.last_sync_ms * 1e-3)
                    self._last_drain_t = None  # sync path: drain clock stale
                else:
                    # cheap speculation pre-gate, BEFORE any drain or
                    # resync: suppressed (acceptance backoff) or
                    # no-candidate (n-gram lookup misses everywhere)
                    # dispatches must cost exactly plain pipelined
                    # decode — the whole drain/resync/propose sequence
                    # is only worth paying when a window could land
                    spec_ready = self._spec_ready()
                    if spec_ready and self._spec_dirty and inflight:
                        # a resync must see the COMPLETE resident record
                        # — drain the in-flight plain dispatches before
                        # rebuilding drafts
                        drain_one()
                        continue
                    spec_rows = None
                    if spec_ready and self._spec_usable():
                        if not self.spec.pipeline_safe:
                            # host drafters (n-gram lookup) propose from
                            # drained history — the previous window must
                            # be observed before the next proposal, so
                            # spec dispatches serialize for them
                            while inflight:
                                drain_one()
                            if not self._slots:
                                continue
                        t_issue = time.monotonic()
                        # None = the drafter declined (no lookup hit
                        # anywhere) — fall through to plain decode
                        spec_rows = self.spec.step_spec_async()
                        # anatomy: proposal + verify enqueue span (host
                        # drafter work rides in launch — documented
                        # caveat); a declined proposal dispatched nothing,
                        # so its host work is scheduling, not launch
                        if spec_rows is not None:
                            self._anat_launch_s += (
                                time.monotonic() - t_issue)
                        else:
                            self._anat_sched_s += (
                                time.monotonic() - t_issue)
                    if spec_rows is not None:
                        self._dispatch_seq += 1
                        fresh = self._fresh_shape("spec")
                        self.last_dispatch_steps = self.spec.gamma + 1
                        try:
                            spec_rows.copy_to_host_async()
                        except AttributeError:
                            pass
                        # k=0 marks a spec window: rows carry SKIP
                        # sentinels; the drain folds the real token yield
                        # into the flight ring + step-time EMA
                        inflight.append((spec_rows, self._dispatch_seq, 0,
                                         bool(inflight), t_issue, fresh))
                        if len(inflight) >= self.pipeline_depth:
                            drain_one()
                        continue
                    if self.spec is not None:
                        self._spec_dirty = True
                    steps = self._effective_steps()
                    self._dispatch_seq += 1
                    fresh = self._fresh_shape(steps)
                    t_issue = time.monotonic()
                    if steps > 1:
                        tokens = self.runner.step_n_async(steps)
                    else:
                        tokens = self.runner.step_async()
                    self.last_dispatch_steps = steps
                    try:
                        tokens.copy_to_host_async()
                    except AttributeError:
                        pass
                    # anatomy: async enqueue span (jit call + D2H start)
                    self._anat_launch_s += time.monotonic() - t_issue
                    inflight.append((tokens, self._dispatch_seq, steps,
                                     bool(inflight), t_issue, fresh))
                    if len(inflight) >= self.pipeline_depth:
                        drain_one()
            except _EngineAbandoned:
                raise
            except Exception:  # noqa: BLE001 — engine must not die silently
                if self._epoch != epoch:
                    # a rebuild raced this dispatch; the new engine owns
                    # the slots — do not fail them from the fenced thread
                    raise _EngineAbandoned
                log.exception("decode step failed; failing active requests")
                inflight.clear()
                with self._lock:
                    failed = list(self._slots.items())
                    self._slots.clear()
                    self.total_preemptions += len(failed)
                for slot, ctx in failed:
                    self._engine.release(slot)
                    self.telemetry.finished(ctx.handle.trace, ctx.handle,
                                            "error")
                    ctx.handle._finish("error")

    def _spec_ready(self) -> bool:
        """The cheap speculation pre-gate, run BEFORE any pipeline drain
        or drafter resync: not backoff-suppressed, and the drafter has a
        proposal candidate for at least one active slot (checked against
        the live resident records — the same data a resync would seed).
        Keeping this ahead of _spec_usable means no-structure traffic
        keeps full plain-decode pipelining and suppressed cooldowns cost
        nothing."""
        if self.spec is None:
            return False
        if self.spec.suppressed_tick():
            return False
        with self._lock:
            residents = {s: self._resident.get(s) for s in self._slots}
        return self.spec.has_candidate(residents)

    def _spec_usable(self) -> bool:
        """Speculative windows require: a spec decoder, every active slot
        far enough from the context edge (a window writes gamma+1 KV rows),
        and fresh drafts (resynced if plain dispatches intervened)."""
        if self.spec is None:
            return False
        gamma = self.spec.gamma
        with self._lock:
            slots = {s: c.handle for s, c in self._slots.items()}
            gen = {s: c.generated for s, c in self._slots.items()}
        for s, h in slots.items():
            if (h.prompt_tokens + gen[s] + gamma + 2
                    >= self.runner.max_ctx):
                return False
        if self._spec_dirty:
            # draft KV is stale for every active slot; rebuild from the
            # resident token record (absent for multimodal slots — wait
            # until those finish)
            if any(self._resident.get(s) is None for s in slots):
                return False
            for s in slots:
                self.spec.resync_draft(s, self._resident[s])
            self._spec_dirty = False
            self._spec_stale.clear()
        elif self._spec_stale:
            # freshly admitted slots only — seed each one individually
            if any(self._resident.get(s) is None
                   for s in self._spec_stale if s in slots):
                return False  # multimodal slot: no token record to seed
            for s in list(self._spec_stale):
                if s in slots:
                    self.spec.resync_draft(s, self._resident[s])
                self._spec_stale.discard(s)
        return True

    def _fresh_shape(self, key) -> bool:
        """True exactly once per program shape — its first dispatch pays
        XLA compile and must not feed the timing EMA."""
        if key in self._seen_shapes:
            return False
        self._seen_shapes.add(key)
        return True

    def _observe_step_time(self, dt: float) -> None:
        """Fold one per-token timing sample into the EMA that drives the
        adaptive streaming dispatch size."""
        if dt <= 0:
            return
        self._step_ema = (
            dt if self._step_ema is None else 0.8 * self._step_ema + 0.2 * dt
        )

    def _effective_steps(self) -> int:
        """Tokens per dispatch for the next dispatch.

        Batch-only traffic takes the full multi_step (throughput). With any
        SSE stream attached, delivery lag ≈ steps×pipeline_depth×step_time
        must stay under stream_latency_target, so the step count shrinks to
        fit — quantized DOWN to a power of two, bounding the number of
        distinct compiled decode programs at log2(multi_step)+1. With no
        timing sample yet, streams get single-step dispatches (latency-safe;
        the EMA fills in from the first post-compile dispatch).
        """
        k = self.multi_step
        if k <= 1:
            return 1
        with self._lock:
            streaming = any(
                c.handle.request.stream for c in self._slots.values()
            )
        if not streaming:
            return k
        if self._step_ema is None:
            return 1
        budget = self.stream_latency_target / max(1, self.pipeline_depth)
        n = int(budget / self._step_ema) if self._step_ema > 0 else k
        p = 1
        while p * 2 <= min(n, k):
            p *= 2
        return p

    def _next_pending(self) -> Optional[GenHandle]:
        """Two-lane admission pop: the interactive lane drains strictly
        first; a batch request is handed out only when the interactive
        queue depth is zero at this instant — so background work is
        invisible to interactive queue wait by construction."""
        try:
            return self._pending.get_nowait()
        except queue.Empty:
            pass
        try:
            return self._pending_batch.get_nowait()
        except queue.Empty:
            return None

    def _admit_pending(self) -> bool:
        if self._quarantined:
            self._unquarantine()
        admitted = False
        while self._engine.free_slots():
            if self._held is not None:
                if (not self._held.cancelled
                        and not self._reservation_fits(self._held.request)):
                    # still no room — skip the (vocab-row + cache-scan +
                    # device-read) admission preamble entirely; this runs
                    # every engine iteration while parked, exactly under
                    # saturation. A cancelled parked request falls through
                    # to the cancelled check below and is dropped now —
                    # it must not keep head-of-line blocking admissions.
                    return admitted
                handle, self._held = self._held, None
            else:
                handle = self._next_pending()
            if handle is None:
                return admitted
            if handle.cancelled:
                # abandoned while still queued: not a slot exit, so it is
                # not a preemption — only requests_total records it
                self.telemetry.finished(handle.trace, handle, "cancelled",
                                        preempted=False)
                handle._finish("cancelled")
                continue
            if not self._reservation_fits(handle.request):
                # block pool can't cover the reservation yet: park the
                # request BEFORE the admission preamble (bias row, prompt
                # cache scan, slot_positions device read) so saturation
                # costs host arithmetic only. Interactive requests hold
                # their place (FIFO); a batch request goes back to its own
                # lane so it can never block interactive admissions.
                if handle.request.priority >= PRIORITY_BATCH:
                    self._pending_batch.put(handle)
                else:
                    self._held = handle
                return admitted
            # prefer the free slot whose resident tokens share the longest
            # prefix with this prompt (KV prefix-cache reuse); the loop
            # guard guarantees a free slot exists (slot lists are mutated
            # only on this thread). One batched [S] positions read serves
            # the whole ranking + admit — free slots' frontiers are frozen
            # until we prefill them, so the snapshot stays valid.
            positions = self._engine.slot_positions()
            slot = self._engine.acquire_slot(
                self._best_slot(handle.request.prompt, positions)
            )
            assert slot is not None
            try:
                if not self._start(slot, handle, positions):
                    # block pool can't cover the reservation yet: park the
                    # request and stop admitting — finishing slots free
                    # blocks and the loop retries. Interactive requests
                    # hold their place (FIFO); a batch request goes back
                    # to its own lane so it can never block interactive
                    # admissions behind a full pool.
                    self._engine.release(slot)
                    if handle.request.priority >= PRIORITY_BATCH:
                        self._pending_batch.put(handle)
                    else:
                        self._held = handle
                    return admitted
                admitted = True
            except Exception as e:  # noqa: BLE001 — bad request ≠ dead engine
                log.warning("admit failed: %s", e)
                self._engine.release(slot)
                with self._lock:
                    self.total_preemptions += 1
                self.telemetry.finished(handle.trace, handle, "error")
                handle._finish("error")

    def _start(self, slot: int, handle: GenHandle,
               positions: Optional[np.ndarray] = None) -> bool:
        """Admit ``handle`` into ``slot``. Returns False when a paged
        runner's block pool can't cover the reservation right now — the
        caller holds the request (nothing was dispatched or stamped)."""
        req = handle.request
        base = self._padded_vocab_ban()
        if req.logit_bias:
            if base is None:
                base = np.zeros(self.runner.cfg.vocab_size, np.float32)
            # bound by the tokenizer vocab, not the (possibly padded) model
            # vocab — a user bias must not resurrect banned padded ids
            limit = min(
                base.shape[0],
                getattr(self.tokenizer, "vocab_size", None) or base.shape[0],
            )
            for tid, b in req.logit_bias.items():
                if 0 <= int(tid) < limit:
                    base[int(tid)] = b
        mask = (
            req.constraint.allowed_mask() if req.constraint is not None else None
        )
        resident = self._resident.get(slot)
        if positions is None:
            positions = self._engine.slot_positions()
        valid_n = int(positions[slot])
        rows = getattr(self._engine, "resident_rows", None)
        if rows is not None:
            # paged runners free a slot's blocks at release — only rows
            # just loaded from the disk prompt cache stay reusable
            valid_n = rows(slot, valid_n)
        if self.prompt_cache is not None and req.mm_embeds is None:
            mem_lcp = (
                self._engine.reusable_prefix(slot, resident, req.prompt,
                                             valid_n=valid_n)
                if resident else 0
            )
            hit = self.prompt_cache.lookup(req.prompt)
            # score the disk hit through the same feasibility gates as the
            # in-memory resident (validity = its own row count): a hit whose
            # tail bucket can't fit would admit() as a full prefill, losing
            # in-memory reuse that was available (ADVICE r4)
            disk_lcp = (
                self._engine.reusable_prefix(
                    slot, hit.tokens, req.prompt, valid_n=hit.n)
                if hit is not None else 0
            )
            if (disk_lcp > mem_lcp
                    and self.runner.load_prefix(slot, hit.arrays, hit.n)):
                resident = hit.tokens
                valid_n = hit.n  # load_prefix moved the slot's frontier
        sampling = dict(
            resident=resident,
            valid_n=valid_n,
            temperature=req.temperature,
            top_k=req.top_k,
            top_p=req.top_p,
            min_p=req.min_p,
            repeat_penalty=req.repeat_penalty,
            presence_penalty=req.presence_penalty,
            frequency_penalty=req.frequency_penalty,
            seed=req.seed,
            bias_row=self._compose_bias(base, mask),
            mm_embeds=req.mm_embeds,
            mm_positions=req.mm_positions,
        )
        if self._chunked:
            # reserve the worst case so decode can never run out of blocks
            # mid-flight (preemption-free by construction)
            reserve = (len(req.prompt) + req.max_new_tokens + 1
                       if req.max_new_tokens
                       else len(req.prompt) + self.default_max_tokens + 1)
            adm = self._engine.begin_admit(
                slot, req.prompt, reserve_tokens=reserve, **sampling)
            if adm is None:
                return False
            handle.admit_index = self._admit_seq
            self._admit_seq += 1
            self.telemetry.admitted(
                handle.trace, slot=slot,
                queue_wait=time.monotonic() - handle.t_submit,
                background=req.priority >= PRIORITY_BATCH,
            )
            self._prefills.append(_PendingPrefill(
                slot=slot, handle=handle, adm=adm, base=base,
                mask_set=mask is not None,
            ))
            return True
        handle.admit_index = self._admit_seq  # engine thread is sole writer
        self._admit_seq += 1
        self.telemetry.admitted(
            handle.trace, slot=slot,
            queue_wait=time.monotonic() - handle.t_submit,
            background=req.priority >= PRIORITY_BATCH,
        )
        first = self._engine.admit(slot, req.prompt, **sampling)
        self.telemetry.prefill_done(
            handle.trace,
            path=self.runner.last_prefill_path,
            prefix_reused=self._engine.last_prefix_reused,
        )
        self._activate_slot(slot, handle, base, mask is not None, int(first))
        return True

    def _activate_slot(self, slot: int, handle: GenHandle,
                       base: Optional[np.ndarray], mask_set: bool,
                       first: int) -> None:
        """Prefill finished (one-shot or final chunk): record the resident
        tokens, install the live slot context, consume the first token."""
        req = handle.request
        # multimodal KV mixes injected embeddings with token ids, so the
        # token record alone can't prove prefix equality — never reuse it.
        # Mirror the runner's empty-prompt normalization ([0]) so the
        # record stays aligned with the cache rows.
        self._resident[slot] = (
            None if req.mm_embeds is not None
            else list(req.prompt) or [0]
        )
        ctx = _SlotCtx(
            handle=handle,
            detok=IncrementalDetokenizer(self.tokenizer.decode),
            stopper=StopChecker(req.stop),
            base_bias=base,
            mask_set=mask_set,
            admit_seq=self._dispatch_seq,
        )
        with self._lock:
            self._slots[slot] = ctx
            self.total_prompt_tokens += handle.prompt_tokens
        if self.spec is not None and self._chunked:
            # chunked paged admissions bypass spec.admit — mark THIS
            # slot's draft stale so the drafter is seeded from the
            # resident record before the next speculative window
            self._spec_stale.add(slot)
        self._consume(slot, ctx, first)

    def _reservation_fits(self, req: GenRequest) -> bool:
        """Host-arithmetic estimate of whether ``req``'s block reservation
        could be allocated right now (pool availability + pool-shareable
        prefix). Slightly optimistic — allocate() stays authoritative —
        so a True merely permits an admission attempt."""
        alloc = getattr(self.runner, "allocator", None)
        if alloc is None or not self._chunked:
            return True
        # spec engines reserve a gamma+1 speculation lookahead on top of
        # the decode worst case (begin_admit spec_tokens) — mirror it here
        # or a full pool would loop begin_admit→None on every iteration
        look = self.spec.gamma + 1 if self.spec is not None else 0
        reserve = min(
            self.runner.max_ctx,
            len(req.prompt) + (req.max_new_tokens
                               or self.default_max_tokens) + 1 + look,
        )
        need = alloc.blocks_for(reserve) - len(alloc.match_prefix(req.prompt))
        return alloc.stats().available >= need

    def _step_prefill_chunk(self) -> bool:
        """Dispatch ONE pending prefill chunk (FIFO across admissions) and
        finalize the admission on its final chunk. Returns True if a chunk
        was dispatched. The flight record tags these dispatches as
        ``prefill_chunk`` with steps=0, keeping them out of the decode
        step-time percentiles while /debug/flight still shows them."""
        if not self._prefills:
            return False
        pf = self._prefills[0]
        if pf.handle.cancelled:
            self._prefills.popleft()
            pf.adm.abort()   # frees the blocks, slot returns to free list
            with self._lock:
                self.total_preemptions += 1
            self.telemetry.finished(pf.handle.trace, pf.handle, "cancelled")
            pf.handle._finish("cancelled")
            return True
        t0 = time.monotonic()
        first = pf.adm.step_chunk()
        dt = time.monotonic() - t0
        self.total_prefill_chunks += 1
        # anatomy: the admission object split its own wall into enqueue
        # vs the final chunk's first-token fetch; the remainder of THIS
        # span is chunk staging (sched). Pre-built phases so the chunk
        # does not consume accumulators owed to the next decode record —
        # and its whole span becomes overlap there (no double count).
        wall_ms = max(0.0, dt) * 1e3
        sync_ms = min(getattr(pf.adm, "last_sync_ms", 0.0), wall_ms)
        launch_ms = min(getattr(pf.adm, "last_launch_ms", 0.0),
                        wall_ms - sync_ms)
        self._flight_record(
            "prefill_chunk", 0, dt, False,
            phases={"gap_ms": 0.0,
                    "sched_ms": wall_ms - sync_ms - launch_ms,
                    "launch_ms": launch_ms, "sync_ms": sync_ms})
        self._anat_overlap_s += dt
        if first is None:
            return True
        self._prefills.popleft()
        self.telemetry.prefill_done(
            pf.handle.trace,
            path=getattr(pf.adm, "path", "paged"),
            prefix_reused=pf.adm.prefix_reused,
        )
        self._activate_slot(pf.slot, pf.handle, pf.base, pf.mask_set, first)
        return True

    def _best_slot(self, prompt: list[int],
                   positions: Optional[np.ndarray] = None) -> Optional[int]:
        """Free slot with the longest reusable token prefix (None → FIFO).
        Uses the runner's own feasibility gates so the ranking can't pick a
        slot whose reuse collapses to zero at admit time. ``positions`` is
        the batched slot_positions() snapshot — passing valid_n explicitly
        keeps this loop free of per-candidate device syncs."""
        if positions is None:
            positions = self._engine.slot_positions()
        best, best_lcp = None, 0
        for s in self._engine.free_slots():
            r = self._resident.get(s)
            if not r:
                continue
            lcp = self._engine.reusable_prefix(
                s, r, prompt, valid_n=int(positions[s])
            )
            if lcp > best_lcp:
                best, best_lcp = s, lcp
        return best

    def _padded_vocab_ban(self) -> Optional[np.ndarray]:
        """Standing bias banning ids the tokenizer cannot produce or decode.

        Model vocabs are often padded wider than the tokenizer (mesh/MXU
        alignment — e.g. the debug presets pad the 258-id byte tokenizer to
        512); without the ban, sampling can land on a padded id and the
        stream silently emits empty deltas. Returns a fresh [V] row
        (callers mutate it) or None when vocabs already agree."""
        tok_v = getattr(self.tokenizer, "vocab_size", None)
        V = self.runner.cfg.vocab_size
        if not tok_v or tok_v >= V:
            return None
        row = np.zeros(V, np.float32)
        row[tok_v:] = -1e30
        return row

    def _compose_bias(
        self, base: Optional[np.ndarray], mask: Optional[np.ndarray]
    ) -> Optional[np.ndarray]:
        base = self._fit_vocab(base, 0.0)
        # a constraint mask covers the tokenizer's vocab; model vocab may be
        # padded wider (MXU/mesh-aligned) — padded ids are disallowed
        mask = self._fit_vocab(mask, -1e30)
        if base is None:
            return mask
        if mask is None:
            return base
        return base + mask

    def _fit_vocab(
        self, row: Optional[np.ndarray], fill: float
    ) -> Optional[np.ndarray]:
        if row is None:
            return None
        V = self.runner.cfg.vocab_size
        if len(row) == V:
            return row
        out = np.full(V, fill, np.float32)
        out[: min(len(row), V)] = row[:V]
        return out

    # engine-thread only (called from _run's drain path) — see _run
    def _process_rows(  # jaxlint: disable=lock-guarded-attr
        self, rows: np.ndarray, seq: int,
        frozen: Optional[set[int]] = None,
    ) -> None:
        # _slots is authoritative: the runner only deactivates slots when this
        # thread releases them, so no device round-trip for liveness. The seq
        # guard drops tokens from dispatches issued before a slot's admission
        # (pipelined mode re-admits slots while a read is still in flight);
        # it works at dispatch granularity because admissions only happen
        # between dispatches. Rows are consumed in temporal order, so a slot
        # that finishes at row i (removed from _slots) ignores rows i+1..;
        # ``frozen`` slots only advanced on the first step of the dispatch,
        # so only row 0 is theirs.
        for i in range(rows.shape[0]):
            for slot, ctx in list(self._slots.items()):
                if seq <= ctx.admit_seq:
                    continue
                if i > 0 and frozen is not None and slot in frozen:
                    continue
                tok = int(rows[i, slot])
                if tok == NAN_TOKEN:
                    # per-row NaN/inf guard sentinel: fail THIS request,
                    # quarantine the slot, keep the rest of the batch
                    self._poisoned(slot, ctx)
                    continue
                if tok < 0:  # SKIP sentinel: speculative window ended early
                    continue
                self._consume(slot, ctx, tok)

    def _consume(self, slot: int, ctx: _SlotCtx, token_id: int) -> None:
        """Handle one sampled token for one slot: stream, stop, constrain."""
        r = self._resident.get(slot)
        if r is not None:
            r.append(token_id)
        handle = ctx.handle
        req = handle.request
        if handle.cancelled:
            self._release(slot, ctx, "cancelled")
            return

        is_eos = (not req.ignore_eos) and token_id in getattr(
            self.tokenizer, "eos_ids", set()
        )
        if is_eos:
            handle._emit(ctx.stopper.flush(), None)
            self._release(slot, ctx, "stop")
            return

        ctx.generated += 1
        self._tokens_emitted += 1  # flight-ring per-dispatch token delta
        delta = ctx.detok.push(token_id)
        safe = ctx.stopper.push(delta)
        handle._emit(safe, token_id)

        if ctx.stopper.stopped is not None:
            self._release(slot, ctx, "stop")
            return

        if req.constraint is not None:
            req.constraint.advance(token_id)
            if req.constraint.done:
                handle._emit(ctx.stopper.flush(), None)
                self._release(slot, ctx, "stop")
                return
            mask = req.constraint.allowed_mask()
            if mask is not None or ctx.mask_set:
                # always refresh when a mask was ever set, so an FSM entering
                # a free-text region (mask=None) clears the stale device mask
                self._engine.set_bias(slot, self._compose_bias(ctx.base_bias, mask))
                ctx.mask_set = mask is not None

        limit = req.max_new_tokens or self.default_max_tokens
        if ctx.generated >= limit:
            handle._emit(ctx.stopper.flush(), None)
            self._release(slot, ctx, "length")
            return
        if handle.prompt_tokens + ctx.generated >= self.runner.max_ctx - 1:
            # context exhausted: finish (no silent context shifting — parity
            # with grpc-server.cpp:1573-1592)
            handle._emit(ctx.stopper.flush(), None)
            self._release(slot, ctx, "length")

    def _release(self, slot: int, ctx: _SlotCtx, reason: str) -> None:
        with self._lock:
            self._slots.pop(slot, None)
            self.total_generated_tokens += ctx.handle.completion_tokens
            if reason in ("cancelled", "error"):
                self.total_preemptions += 1
        migrating = (reason == "cancelled"
                     and getattr(ctx.handle, "migrate_export", False))
        if (self.prompt_cache is not None
                and not self.prompt_cache.read_only
                and (reason in ("stop", "length") or migrating)):
            r = self._resident.get(slot)
            if r:
                # prompt_cache_all keeps generation too; otherwise prompt
                # only. Generated length comes from the host record — no
                # device sync on the engine thread. A migration export
                # always keeps the generation: the destination replica
                # resumes from the full token record's frontier.
                pos = min(len(r) - 1, self.runner.max_ctx - 1)
                keep = (pos if (self.prompt_cache_all or migrating)
                        else min(ctx.handle.prompt_tokens, pos))
                if keep >= self.prompt_cache.min_prefix:
                    try:
                        self._pc_queue.put((
                            list(r[:keep]),
                            self.runner.snapshot_prefix(slot, keep),
                        ))
                    except Exception as e:  # noqa: BLE001 — cache ≠ serving
                        log.warning("prompt-cache snapshot failed: %s", e)
        self._engine.release(slot)
        # retire the trace BEFORE _finish unblocks the client: a traces
        # query racing the response must not see a half-annotated trace
        self.telemetry.finished(ctx.handle.trace, ctx.handle, reason)
        ctx.handle._finish(reason)
        if reason in ("stop", "length") and self.supervisor is not None:
            # a natural completion closes any open incident: the
            # supervisor's bounded rebuild budget refills
            self.supervisor.note_healthy()
