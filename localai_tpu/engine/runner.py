"""ModelRunner: the jitted prefill/decode executor for one loaded LLM.

This is the TPU-era replacement for llama.cpp's slot engine hot loop
(update_slots + llama_decode + per-slot sampling,
/root/reference/backend/cpp/llama/grpc-server.cpp:1546-1990), redesigned for
XLA's compile-once/static-shape model:

  * ONE compiled decode step serves all slots every iteration (continuous
    batching = slot masking, not ragged batch rebuilds).
  * Prefill lengths are bucketed (powers of a small set) so at most
    len(buckets) prefill programs are ever compiled — no recompilation
    storms from arbitrary prompt lengths.
  * KV cache and decode state are donated on every dispatch → in-place HBM
    updates, zero copies.
  * Sampling runs on device in the same program as the forward pass; the
    only per-step host traffic is the [S] sampled-token vector.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.engine import kvcache as kvc
from localai_tpu.engine import paged as pgd
from localai_tpu.engine import sampling as smp
from localai_tpu.engine.kvcache import KVCache
from localai_tpu.models import llama as mdl
from localai_tpu.models.llama import LlamaConfig
from localai_tpu.obs import compile as obs_compile
from localai_tpu.obs import watchdog as obs_watchdog
from localai_tpu.utils.jaxcompat import shard_map

log = logging.getLogger(__name__)

# sampled-row sentinel for the per-row NaN/inf logits guard: a slot whose
# (biased) logits row went non-finite reports this instead of a token id,
# riding the [S] token transfer the host already pays for — zero extra
# device syncs. Distinct from the speculative SKIP sentinel (-1); the
# scheduler fails the affected request and quarantines the slot.
NAN_TOKEN = -2

# speculative-window sentinel in emitted [T, S] rows: no token for this
# (step, slot) — the slot's window ended at an earlier rejection (or the
# slot is inactive). Consumers (scheduler._process_rows) skip it.
SKIP = -1


def _prompt_counts_row(vocab_size: int, prompt) -> np.ndarray:
    """[V] i32 bincount of the FULL prompt for resume-style prefills (the
    in-program count would only see the tail chunk)."""
    crow = np.zeros(vocab_size, np.int32)
    ids = np.asarray(prompt, np.int64)
    np.add.at(crow, ids[(ids >= 0) & (ids < vocab_size)], 1)
    return crow


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """All per-slot mutable serving state, device-resident."""

    tokens: jax.Array      # [S] i32 — next token to feed per slot
    positions: jax.Array   # [S] i32 — next KV write position per slot
    active: jax.Array      # [S] bool
    keys: jax.Array        # [S] PRNG keys
    counts: jax.Array      # [S, V] i32 — token occurrence counts (penalties)
    bias: jax.Array        # [S, V] f32 — additive logit bias (logit_bias API
                           #              + grammar/FSM masks as -1e30)
    params: smp.SamplingParams

    @staticmethod
    def init(num_slots: int, vocab_size: int, seed: int = 0) -> "DecodeState":
        return DecodeState(
            tokens=jnp.zeros(num_slots, jnp.int32),
            positions=jnp.zeros(num_slots, jnp.int32),
            active=jnp.zeros(num_slots, jnp.bool_),
            keys=jax.random.split(jax.random.key(seed), num_slots),
            counts=jnp.zeros((num_slots, vocab_size), jnp.int32),
            bias=jnp.zeros((num_slots, vocab_size), jnp.float32),
            params=smp.SamplingParams.init(num_slots),
        )


class ModelRunner:
    """Owns params + KV cache + decode state for one model; exposes
    admit/step/release to the scheduler."""

    def __init__(
        self,
        cfg: LlamaConfig,
        params: Any,
        *,
        num_slots: int = 8,
        max_ctx: Optional[int] = None,
        prefill_buckets: Optional[list[int]] = None,
        kv_dtype: str = "bfloat16",
        rope_freq_base: Optional[float] = None,
        rope_freq_scale: Optional[float] = None,
        seed: int = 0,
        mesh: Optional[jax.sharding.Mesh] = None,
        attn_impl: str = "auto",
        sp_threshold: int = 1024,
        ga_n: int = 1,
        ga_w: int = 512,
        paged: Any = "auto",
        kv_block_tokens: Optional[int] = None,
        kv_num_blocks: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
    ):
        from localai_tpu import ops

        self.cfg = cfg
        self.params = params
        # stall watchdog guarding this runner's blocking device round-trips
        # (the scheduler rebinds it to its own instance when injected); the
        # process-wide default is unstarted until a Scheduler starts it
        self.watchdog = obs_watchdog.WATCHDOG
        # dispatch-anatomy scratch (obs.anatomy): the sync-by-contract
        # entry points (step / step_n / step_frozen_n) split their wall
        # time into call-return (async enqueue) vs result-fetch (device
        # block) and leave it here for the caller to harvest. Engine-
        # thread-only, overwritten every call — an attribution side
        # channel, not state.
        self.last_launch_ms = 0.0
        self.last_sync_ms = 0.0
        # self-extend / group attention (parity: llama.cpp ga_n/ga_w slot
        # options — see engine.selfextend). ga_n>1 serves past the trained
        # context by merging neighbor + grouped attention scores; the KV
        # cache stays UNroped in this mode, so it forces the XLA attend
        # (the Pallas kernels assume pre-roped K).
        if ga_n > 1 and ga_w % ga_n:
            raise ValueError(f"ga_w ({ga_w}) must be a multiple of "
                             f"ga_n ({ga_n})")
        self.ga_n, self.ga_w = ga_n, ga_w
        if ga_n > 1:
            attn_impl = "xla"
            log.info("self-extend active (ga_n=%d ga_w=%d): XLA attention, "
                     "unroped KV cache", ga_n, ga_w)
        # pipeline (layer-sharded) parallelism: HBM capacity scaling over
        # the 'pipe' axis (parallel.pipeline — llama.cpp layer-split-mode
        # parity). v1 runs pipe alone and keeps the XLA attend.
        self.pp_enabled = (mesh is not None
                           and mesh.shape.get("pipe", 1) > 1)
        if self.pp_enabled:
            n_pipe = mesh.shape["pipe"]
            busy = [ax for ax in ("data", "model", "seq", "expert")
                    if mesh.shape.get(ax, 1) > 1]
            if busy:
                raise ValueError(
                    f"pipeline parallelism composes with no other axis "
                    f"yet; mesh also shards {busy}")
            if cfg.num_layers % n_pipe:
                raise ValueError(
                    f"num_layers {cfg.num_layers} not divisible by "
                    f"pipe={n_pipe}")
            if ga_n > 1:
                raise ValueError(
                    "self-extend is not supported with pipeline "
                    "parallelism")
            attn_impl = "xla"
            log.info("pipeline parallelism: %d stages x %d layers",
                     n_pipe, cfg.num_layers // n_pipe)
        # the full decision (auto-resolve + every fallback gate) lives in
        # ops.select_attn_impl so tests can assert which path a given
        # (model, mesh) lands on at hardware shapes
        self.attn_impl, self._attn_interpret, why = ops.select_attn_impl(
            attn_impl,
            num_heads=cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.hd,
            max_ctx=max_ctx or cfg.max_position_embeddings,
            tp=mesh.shape["model"] if mesh is not None else 1,
        )
        if why:
            log.info("attention: %s; using XLA", why)
        # int8 KV rides the same flash decode kernel: per-position scales
        # fuse into the online-softmax loop (ops.attention), so the default
        # quantized config is both length-aware (block-skip past each slot's
        # frontier) and half-bandwidth — no XLA fallback, no bf16 cache copy.
        self.decode_attn_impl = self.attn_impl
        self.num_slots = num_slots
        self.max_ctx = max_ctx or cfg.max_position_embeddings
        self.mesh = mesh
        buckets = sorted(prefill_buckets or [128, 512, 2048, 8192])
        self.buckets = [b for b in buckets if b < self.max_ctx]
        self.buckets.append(self.max_ctx)  # any admissible prompt has a bucket
        self.rope = mdl.rope_table(
            cfg, self.max_ctx, freq_base=rope_freq_base, freq_scale=rope_freq_scale
        )
        if self.ga_n > 1:
            from localai_tpu.engine import selfextend as se

            # forward() sees an identity table (q/k written unroped); the
            # self-extend attend applies the real rotations per score set
            self._se_rope = self.rope
            self.rope = se.identity_rope(self.rope)
        # paged KV cache (vLLM-style block pool + tables, engine.paged).
        # A plain dp×tp(×seq) mesh composes: the pool shards its kv-head
        # axis over 'model' (parallel.sharding.paged_kv_spec), the [S, MB]
        # table mirror shards slots over 'data', and the block allocator
        # stays host-side and replicated — admission, refcounts, and
        # prefix sharing are topology-blind. Incompatible modes keep the
        # slot-contiguous layout: pipeline parallelism (pp_forward's stage
        # chain assumes layer-sharded slot rows) and self-extend (unroped
        # cache + grouped rescoring assume row slices).
        incompat = []
        if self.pp_enabled:
            incompat.append("pipeline parallelism")
        if ga_n > 1:
            incompat.append("self-extend")
        if paged in ("auto", None):
            # bare runners (tests, tools) default contiguous; the serving
            # manager and bench enable paged whenever compatible — flip
            # globally with LOCALAI_KV_PAGED=1
            want_paged = os.environ.get("LOCALAI_KV_PAGED", "0") == "1"
            self.paged = want_paged and not incompat
        else:
            self.paged = bool(paged)
            if self.paged and incompat:
                raise ValueError(
                    f"paged KV cache is incompatible with {incompat}")
        if kv_dtype == "int4" and not self.paged:
            raise ValueError(
                "kv_dtype=int4 requires the paged KV layout (the nibble-"
                "packed pool scatter only exists for block pools); use "
                "int8 for contiguous caches")
        tp_width = mesh.shape["model"] if mesh is not None else 1
        if self.paged:
            # per-shape tuned defaults (ops.tuning, written by
            # tools/autotune.py): explicit kwargs and env knobs win,
            # then the tuning table, then the built-in defaults
            from localai_tpu.ops import tuning as ops_tuning

            tuned = ops_tuning.lookup(
                cfg.hd, cfg.num_kv_heads, kv_dtype, tp_width)
            try:
                env_bt = int(
                    os.environ.get("LOCALAI_KV_BLOCK_TOKENS", "") or 0)
            except ValueError:
                env_bt = 0
            self.block_tokens = max(8, int(
                kv_block_tokens or env_bt
                or (tuned.block_tokens if tuned else 0)
                or pgd.block_tokens_default()))
            try:
                env_buf = int(
                    os.environ.get("LOCALAI_PAGED_NUM_BUFFERS", "") or 0)
            except ValueError:
                env_buf = 0
            self.paged_num_buffers = max(2, int(
                env_buf or (tuned.num_buffers if tuned else 0) or 2))
            self.max_blocks = -(-self.max_ctx // self.block_tokens)
            self.ctx_pad = self.max_blocks * self.block_tokens
            # default pool = the contiguous layout's HBM footprint (every
            # slot can still reach max_ctx) scaled by LOCALAI_KV_OVERCOMMIT
            # (ratio, default 1.0 — <1 shrinks for true overcommit, >1
            # grows past the contiguous footprint), plus the trash block;
            # LOCALAI_KV_BLOCKS / kv_num_blocks set an absolute count and
            # win over the ratio
            try:
                self.kv_overcommit = max(0.01, float(
                    os.environ.get("LOCALAI_KV_OVERCOMMIT", "1.0") or 1.0))
            except ValueError:
                self.kv_overcommit = 1.0
            default_blocks = max(
                self.max_blocks,
                int(num_slots * self.max_blocks * self.kv_overcommit)) + 1
            env_blocks = os.environ.get("LOCALAI_KV_BLOCKS", "")
            num_blocks = int(kv_num_blocks or env_blocks or default_blocks)
            self.allocator = pgd.BlockAllocator(
                num_blocks, self.block_tokens, self.max_blocks)
            chunk_env = os.environ.get("LOCALAI_PREFILL_CHUNK_TOKENS", "512")
            self.prefill_chunk = max(
                self.block_tokens,
                int(prefill_chunk or chunk_env or 512))
            (self.paged_attn_impl, self._paged_attn_interpret,
             paged_why) = ops.select_paged_attn_impl(
                attn_impl,
                num_heads=cfg.num_heads,
                num_kv_heads=cfg.num_kv_heads,
                head_dim=cfg.hd,
                block_tokens=self.block_tokens,
                tp=tp_width,
                kv_dtype=kv_dtype,
                # reuse the entry fetched above — an empty TuneEntry
                # means "already looked up, no preference", so one
                # construction emits exactly one lookup receipt
                tuned=tuned or ops_tuning.TuneEntry(),
            )
            if paged_why:
                log.info("paged attention: %s; using gather+XLA", paged_why)
            # collective/compute overlap (parallel.overlap): meshed decode
            # runs the trunk as a manual-TP shard_map with the per-layer
            # psums decomposed into chunked psum_scatter+all_gather so ICI
            # latency hides behind the matmuls. LOCALAI_MESH_OVERLAP =
            # auto(default)/psum/0; resolve_mode gates unsupported meshes
            # back to GSPMD.
            self.overlap_mode = ""
            self.overlap_chunks = 4
            if mesh is not None:
                from localai_tpu.parallel import overlap as ovl

                self.overlap_mode, ovl_why = ovl.resolve_mode(
                    cfg, mesh,
                    os.environ.get("LOCALAI_MESH_OVERLAP", "auto"))
                try:
                    self.overlap_chunks = max(1, int(os.environ.get(
                        "LOCALAI_MESH_OVERLAP_CHUNKS", "") or 4))
                except ValueError:
                    pass
                if self.overlap_mode:
                    log.info(
                        "meshed decode: manual-TP %s reductions "
                        "(chunks=%d)", self.overlap_mode,
                        self.overlap_chunks)
                elif ovl_why:
                    log.info("meshed decode overlap unavailable: %s "
                             "(GSPMD psum path)", ovl_why)
            # one device-resident zeros row reused by every non-final
            # chunk dispatch (whose sample=False program ignores counts —
            # no per-chunk [V] host alloc + H2D copy)
            self._zero_counts = jnp.zeros(cfg.vocab_size, jnp.int32)
        else:
            self.allocator = None
            self.overlap_mode = ""
            self.overlap_chunks = 4
            self.paged_num_buffers = 2
        # shardings are kept so reinit() (self-healing engine rebuild)
        # can rebuild the device state into the exact same layout
        self._kv_sharding = None
        self._paged_sharding = None
        self._table_sharding = None
        self._seed = seed
        self.kv_dtype = kv_dtype
        if mesh is not None:
            from jax.sharding import NamedSharding

            from localai_tpu.models import quant as qnt
            from localai_tpu.parallel import sharding as shd

            # the Pallas w8 matmul has no partitioning rule — GSPMD would
            # all-gather sharded weights into it every step. The block is
            # carried by THIS runner's tensors (kernel_ok metadata), so a
            # single-device runner built later keeps the kernel opt-in.
            self.params = params = qnt.block_w8_kernel_params(
                params, "runner built over a device mesh")
            shd.slots_per_data_shard(num_slots, mesh)  # divisibility check
            if self.paged:
                # pool kv-heads on 'model' (paged_kv_spec); the [S, MB]
                # table mirror carries the 'data' sharding instead — the
                # pool has no slot axis to put it on
                self._paged_sharding = NamedSharding(
                    mesh, shd.paged_kv_spec(cfg, mesh))
                self._table_sharding = NamedSharding(
                    mesh, shd.block_table_spec())
            else:
                self._kv_sharding = NamedSharding(
                    mesh, shd.kv_spec(cfg, mesh))
        self._init_device_state()
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self.rope = jax.device_put(
                self.rope, NamedSharding(mesh, P())
            )
        # every jit entry point is wrapped by obs.compile.watch: the first
        # dispatch of each program shape compiles synchronously, so its
        # wall time lands in the localai_xla_compile_* series (the
        # jax.monitoring listener supplements this where available)
        obs_compile.install()
        self._decode = obs_compile.watch(
            jax.jit(self._decode_fn, donate_argnums=(1, 2)), "decode"
        )
        self._decode_n = obs_compile.watch(jax.jit(
            self._decode_n_fn, static_argnames=("n",), donate_argnums=(1, 2)
        ), "decode_n")
        self._decode_frozen_n = obs_compile.watch(jax.jit(
            self._decode_frozen_n_fn, static_argnames=("n",),
            donate_argnums=(1, 2),
        ), "decode_frozen_n")
        # speculative verify (localai_tpu.spec): one batched T-wide target
        # forward scores a whole draft window per dispatch. One program per
        # gamma (the window width is baked into the proposals shape).
        self._verify = obs_compile.watch(
            jax.jit(self._verify_fn, donate_argnums=(1, 2)), "verify"
        )
        self._prefill = obs_compile.watch(jax.jit(
            self._prefill_fn, static_argnames=("bucket",), donate_argnums=(1, 2)
        ), "prefill")
        self._prefill_mm = obs_compile.watch(jax.jit(
            self._prefill_mm_fn, static_argnames=("bucket",),
            donate_argnums=(1, 2),
        ), "prefill_mm")
        self._prefill_resume = obs_compile.watch(jax.jit(
            self._prefill_resume_fn, static_argnames=("bucket",),
            donate_argnums=(1, 2),
        ), "prefill_resume")
        if self.paged:
            # paged variants keep the contiguous programs' obs labels so
            # the cost observatory's per-program series stay comparable
            # across layouts; the chunked prefill gets its own label.
            self._decode_paged = obs_compile.watch(
                jax.jit(self._decode_paged_fn, donate_argnums=(1, 2)),
                "decode")
            self._decode_paged_n = obs_compile.watch(jax.jit(
                self._decode_paged_n_fn, static_argnames=("n",),
                donate_argnums=(1, 2),
            ), "decode_n")
            self._decode_paged_frozen_n = obs_compile.watch(jax.jit(
                self._decode_paged_frozen_n_fn, static_argnames=("n",),
                donate_argnums=(1, 2),
            ), "decode_frozen_n")
            self._verify_paged = obs_compile.watch(
                jax.jit(self._verify_paged_fn, donate_argnums=(1, 2)),
                "verify")
            self._prefill_paged = obs_compile.watch(jax.jit(
                self._prefill_paged_fn,
                static_argnames=("bucket", "sample"),
                donate_argnums=(1, 2),
            ), "prefill_chunk")
            self._prefill_paged_mm = obs_compile.watch(jax.jit(
                self._prefill_paged_mm_fn, static_argnames=("bucket",),
                donate_argnums=(1, 2),
            ), "prefill_mm")
        # sequence-parallel prefill: long prompts chunk over the 'seq' mesh
        # axis and run ring attention (parallel.ring) straight into the
        # slot cache. Composes with TP: weights stay 'model'-sharded
        # (Megatron layout + per-layer psums) while activations shard over
        # 'seq' — requires the head groups to split evenly so each device's
        # ring carries a consistent Hkv/tp head shard.
        sp_tp = mesh.shape.get("model", 1) if mesh is not None else 1
        self.sp_enabled = (
            mesh is not None
            and mesh.shape.get("seq", 1) > 1
            and (sp_tp == 1
                 or (cfg.num_heads % sp_tp == 0
                     and cfg.num_kv_heads % sp_tp == 0
                     and cfg.intermediate_size % sp_tp == 0))
            # expert-parallel MoE prefill stays on the GSPMD path — the
            # manual ring shard_map doesn't slice router weights per shard
            and (cfg.num_experts == 0 or mesh.shape.get("expert", 1) == 1)
            # self-extend keeps the cache unroped; the ring prefill writes
            # roped K, so the two modes are mutually exclusive
            and ga_n == 1
        )
        self.sp_threshold = sp_threshold
        self.last_prefill_path = ""
        self._prefill_sp = obs_compile.watch(jax.jit(
            self._prefill_sp_fn, static_argnames=("bucket",),
            donate_argnums=(1, 2),
        ), "prefill_sp")
        if self.paged:
            # ring-attention prefill straight into the sharded block pool
            # (one long prompt uses every chip without stalling decode —
            # chosen by begin_admit when the mesh has a 'seq' axis)
            self._prefill_paged_sp = obs_compile.watch(jax.jit(
                self._prefill_paged_sp_fn, static_argnames=("bucket",),
                donate_argnums=(1, 2),
            ), "prefill_sp")
        self._embed = obs_compile.watch(
            jax.jit(self._embed_fn, static_argnames=("bucket",)), "embed"
        )
        # KV prefix reuse (parity: common_part, grpc-server.cpp:67-74):
        # suffix prefill only pays off past a minimum shared prefix
        self.prefix_reuse_min = 16
        self.last_prefix_reused = 0       # tokens reused by the last admit
        self.total_prefix_reused = 0

    # -- device-state lifecycle (construction + self-healing rebuild) ----

    def _init_device_state(self) -> None:
        """(Re)build everything device-resident and per-slot: KV pool,
        decode state, block tables, allocator bookkeeping, free-slot
        list. Called once at construction and again by :meth:`reinit`
        after a suspected device wedge — params, compiled programs, and
        shardings are untouched, so no retrace/recompile happens."""
        cfg = self.cfg
        if self.paged:
            self.allocator = pgd.BlockAllocator(
                self.allocator.num_blocks, self.block_tokens,
                self.max_blocks)
            # disk prompt-cache rows loaded into a slot's fresh blocks
            # (the only slot-resident reuse that survives release)
            self._loaded_rows: dict[int, int] = {}
            tables = jnp.zeros((self.num_slots, self.max_blocks), jnp.int32)
            if self._table_sharding is not None:
                tables = jax.device_put(tables, self._table_sharding)
            self.block_tables = tables
            self.kv = kvc.init_paged_cache(
                cfg, self.allocator.num_blocks, self.block_tokens,
                self.kv_dtype, sharding=self._paged_sharding,
            )
            # HBM→host prefix-pool tiering (LOCALAI_KV_TIER_MB, off by
            # default): LRU pool evictions spill their raw block rows to
            # host RAM and re-onboard on a later chain hit. Rebuilt with
            # the allocator on every reinit — a rebuilt pool starts cold,
            # and stale spills from the pre-wedge cache must not shadow
            # it (lazy import: fleet.kveconomy is runtime-only here).
            from localai_tpu.fleet.kveconomy.tiering import tier_from_env

            tier = tier_from_env()
            if tier is not None:
                self.allocator.attach_tier(
                    tier, pack=self.pack_block, load=self.load_block)
        else:
            self.kv = kvc.init_cache(
                cfg, self.num_slots, self.max_ctx, self.kv_dtype,
                sharding=self._kv_sharding,
            )
        state = DecodeState.init(self.num_slots, cfg.vocab_size, self._seed)
        if self.mesh is not None:
            state = self._place_state(state)
        self.state = state
        self._free_slots = list(range(self.num_slots))
        # host mirror of which slots are serving: admit()/release() are the
        # only transitions, so liveness queries never touch the device
        self._active_slots: set[int] = set()

    def _place_state(self, state: DecodeState) -> DecodeState:
        """Shard a fresh DecodeState over the mesh (the construction-time
        layout, reapplied verbatim on rebuild)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from localai_tpu.parallel import sharding as shd

        mesh = self.mesh
        specs = shd.state_specs(mesh)

        def place(name: str, leaf):
            spec = shd._sanitize(specs[name], leaf.shape, mesh)
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        return DecodeState(
            tokens=place("tokens", state.tokens),
            positions=place("positions", state.positions),
            active=place("active", state.active),
            keys=place("keys", state.keys),
            counts=place("counts", state.counts),
            bias=place("bias", state.bias),
            params=jax.tree.map(
                lambda a: jax.device_put(
                    a, NamedSharding(mesh, P("data"))
                ),
                state.params,
            ),
        )

    def reinit(self) -> None:
        """Self-healing engine rebuild (faults.supervisor): drop the
        possibly-corrupt device state and allocate a fresh KV pool /
        decode state / block tables in the original layout. The old
        arrays may still be referenced by an abandoned dispatch thread
        parked in a dead round-trip; they are released here and freed
        whenever that thread exits. Callers own slot bookkeeping — every
        previously admitted request must already be failed."""
        self._init_device_state()
        self.last_prefill_path = ""
        self.last_prefix_reused = 0

    # -- jitted programs -------------------------------------------------

    def _decode_fn(self, params, kv: KVCache, state: DecodeState):
        cfg = self.cfg
        pos = state.positions
        attn = None
        raw_kv = self.decode_attn_impl == "pallas" and kv.quantized
        if self.decode_attn_impl == "pallas":
            from localai_tpu import ops

            kernel = partial(
                ops.decode_attention,
                sliding_window=cfg.sliding_window,
                interpret=self._attn_interpret,
            )
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P

                # per-device kernel over (slots/'data', heads/'model'):
                # decode attention is independent across slots and head
                # groups, so the shard_map body is the single-device kernel
                in_specs = [P("data", "model", None),
                            P("data", "model", None, None),
                            P("data", "model", None, None),
                            P("data")]
                if raw_kv:
                    in_specs += [P("data", "model", None),
                                 P("data", "model", None)]
                kernel = shard_map(
                    kernel,
                    mesh=self.mesh,
                    in_specs=tuple(in_specs),
                    out_specs=P("data", "model", None),
                    check_vma=False,
                )

            def attn(q, keys, values, _mask):  # q [S,1,Hq,hd], keys [S,Hkv,C,hd]
                if raw_kv:  # (int8 cache, f32 scales) — fused dequant
                    out = kernel(q[:, 0], keys[0], values[0], pos,
                                 keys[1], values[1])
                else:
                    out = kernel(q[:, 0], keys, values, pos)
                return out[:, None]

        if attn is None:
            attn = self._se_attn(
                pos[:, None], jnp.arange(self.max_ctx, dtype=jnp.int32))
        mask = kvc.decode_mask(cfg, pos, self.max_ctx)
        write = kvc.decode_write(pos, raw=raw_kv)
        hidden, new_stack = self._forward(
            params, state.tokens[:, None], pos[:, None],
            write, kv.stacked(), mask, attn=attn,
        )
        new_state, tokens = self._decode_tail(params, state, hidden)
        return KVCache.from_stacked(new_stack), new_state, tokens

    def _decode_tail(self, params, state: DecodeState, hidden):
        """Sampling + per-slot state advance shared by the contiguous and
        paged decode programs (KV-layout-independent)."""
        pos = state.positions
        logits = mdl.logits_from_hidden(self.cfg, params, hidden[:, 0])
        tokens, keys = smp.sample(
            logits, state.params, state.counts, state.keys, state.bias
        )
        # inactive/frozen slots keep their key: a seeded request's stream must
        # not depend on batch composition (key advances == tokens sampled)
        keys = jnp.where(state.active, keys, state.keys)
        tokens = jnp.where(state.active, tokens, state.tokens)
        # per-row NaN/inf guard on the effective (biased) logits: one bad
        # row must fail only its own slot, never silently poison the
        # co-batched streams. The verdict rides the sampled-token row as
        # the NAN_TOKEN sentinel — no extra transfer, no host branch.
        row_ok = jnp.all(
            jnp.isfinite(logits.astype(jnp.float32) + state.bias), axis=-1)
        tokens = jnp.where(state.active & ~row_ok, NAN_TOKEN, tokens)
        # clamp the sentinel out of the scatter index (the slot is dead
        # either way; a wrapped negative index would dirty a real count)
        counts = smp.update_counts(
            state.counts, jnp.maximum(tokens, 0), state.active)
        positions = jnp.where(
            state.active, jnp.minimum(pos + 1, self.max_ctx - 1), pos
        )
        new_state = dataclasses.replace(
            state, tokens=tokens, positions=positions, keys=keys, counts=counts
        )
        return new_state, tokens

    # -- speculative verify programs (localai_tpu.spec drives these) -----

    def _accept_scan(self, state: DecodeState, logits, proposals):
        """Accept/sample scan over a speculative window: the full sampler
        chain per position with sequentially-updated counts, so emitted
        tokens follow the exact non-speculative sampling distribution
        (naive-match acceptance: a draft token is accepted iff it equals
        the token the target itself sampled; on mismatch the target's
        sample is the correction and the window ends). PRNG keys advance
        once per EMITTED token, preserving the seeded-stream contract.

        logits [S, T, V], proposals [S, T-1]. Returns (new_state,
        emitted [T, S]) where SKIP marks positions past a slot's
        accepted window; positions roll forward by exactly the emitted
        count — the rejected tail is rolled back for every slot
        independently."""
        S = self.num_slots
        G = proposals.shape[1]
        T = G + 1

        def acc_body(carry, xs):
            counts, keys, still, n_emit, final_tok = carry
            logits_t, draft_t, t = xs  # [S, V], [S], scalar
            tok, new_keys = smp.sample(
                logits_t, state.params, counts, keys, state.bias
            )
            # per-row NaN/inf guard, same contract as _decode_tail: a
            # non-finite effective logits row reports the NAN_TOKEN
            # sentinel instead of a sample (and ends the slot's window —
            # the sentinel can never equal a draft token), so the
            # scheduler fails ONLY that request. Speculation is the
            # default lane; skipping the guard here would reopen the
            # silent-poison class the plain path closed.
            row_ok = jnp.all(
                jnp.isfinite(logits_t.astype(jnp.float32) + state.bias),
                axis=-1)
            tok = jnp.where(row_ok, tok, NAN_TOKEN)
            emit_now = still & state.active
            keys = jnp.where(emit_now, new_keys, keys)
            # clamp the sentinel out of the scatter index (the slot is
            # dead either way; a wrapped negative index would dirty a
            # real count) — mirrors _decode_tail
            counts = counts.at[jnp.arange(S), jnp.maximum(tok, 0)].add(
                emit_now.astype(counts.dtype)
            )
            final_tok = jnp.where(emit_now, tok, final_tok)
            n_emit = n_emit + emit_now.astype(jnp.int32)
            is_match = emit_now & (t < G) & (tok == draft_t)
            emitted_t = jnp.where(emit_now, tok, SKIP)
            return (counts, keys, is_match, n_emit, final_tok), emitted_t

        init = (
            state.counts,
            state.keys,
            jnp.ones(S, jnp.bool_),
            jnp.zeros(S, jnp.int32),
            state.tokens,
        )
        draft_padded = jnp.concatenate(
            [proposals, jnp.full((S, 1), SKIP, jnp.int32)], axis=1
        )
        (counts, keys, _, n_emit, final_tok), emitted = jax.lax.scan(
            acc_body, init,
            (logits.transpose(1, 0, 2), draft_padded.T, jnp.arange(T)),
        )  # emitted [T, S]
        new_pos = jnp.minimum(state.positions + n_emit, self.max_ctx - 1)
        new_state = dataclasses.replace(
            state, tokens=final_tok, positions=new_pos, keys=keys,
            counts=counts,
        )
        return new_state, emitted

    def _verify_fn(self, params, kv: KVCache, state: DecodeState,
                   proposals):
        """One speculative verify dispatch over the contiguous cache: a
        T=gamma+1-wide batched forward scores every draft position at each
        slot's frontier (positions offset per slot — decode generalized to
        T tokens), then the accept/sample scan emits the accepted prefix +
        correction. proposals [S, gamma] i32; returns emitted [T, S]."""
        cfg = self.cfg
        T = proposals.shape[1] + 1
        p0 = state.positions
        positions = p0[:, None] + jnp.arange(T)[None, :]     # [S, T]
        tokens = jnp.concatenate(
            [state.tokens[:, None], proposals], axis=1)      # [S, T]
        mask = kvc.verify_mask(cfg, p0, T, self.max_ctx)
        write = kvc.verify_write(p0)
        hidden, new_stack = self._forward(
            params, tokens, positions, write, kv.stacked(), mask,
        )
        logits = mdl.logits_from_hidden(cfg, params, hidden)  # [S, T, V]
        new_state, emitted = self._accept_scan(state, logits, proposals)
        return KVCache.from_stacked(new_stack), new_state, emitted

    def _verify_paged_fn(self, params, kv: kvc.PagedKVCache,
                         state: DecodeState, tables, proposals):
        """Paged twin of _verify_fn: draft rows scatter through the block
        tables into each slot's reserved speculation blocks, window tokens
        attend resume-style over the gathered prefix + window, and the
        accept scan rolls every slot's frontier back independently — the
        rejected tail is a per-slot position rollback, never a table
        mutation (co-batched slots are untouched by construction)."""
        cfg = self.cfg
        T = proposals.shape[1] + 1
        p0 = state.positions
        positions = p0[:, None] + jnp.arange(T)[None, :]     # [S, T]
        tokens = jnp.concatenate(
            [state.tokens[:, None], proposals], axis=1)      # [S, T]
        mask = kvc.verify_mask(cfg, p0, T, self.ctx_pad)
        write = kvc.paged_verify_write(tables, p0, self.max_ctx)
        hidden, new_stack = self._forward(
            params, tokens, positions, write, kv.stacked(), mask,
        )
        logits = mdl.logits_from_hidden(cfg, params, hidden)  # [S, T, V]
        new_state, emitted = self._accept_scan(state, logits, proposals)
        return kvc.PagedKVCache.from_stacked(new_stack), new_state, emitted

    def _decode_n_fn(self, params, kv: KVCache, state: DecodeState, *, n: int):
        """n decode steps in ONE dispatch via lax.scan — amortizes host→device
        dispatch latency (the tunnel RTT dominates single-step decode; see
        bench.py). Returns tokens [n, S]."""

        def body(carry, _):
            kv, state = carry
            kv, state, tokens = self._decode_fn(params, kv, state)
            return (kv, state), tokens

        (kv, state), tokens = jax.lax.scan(
            body, (kv, state), None, length=n
        )
        return kv, state, tokens

    def _decode_frozen_n_fn(self, params, kv: KVCache, state: DecodeState,
                            freeze, *, n: int):
        """n decode steps in one dispatch where slots in ``freeze`` advance
        only on the FIRST step — the per-slot constraint gating path: a
        grammar-constrained slot needs its logit mask refreshed by the host
        between tokens (so it gets one token per dispatch), while the
        unconstrained slots ride the same dispatch for n tokens. Replaces the
        whole-batch synchronous fallback (one constrained request no longer
        de-pipelines the batch). Returns tokens [n, S]; rows 1..n-1 are only
        meaningful for non-frozen slots."""
        full_active = state.active

        def body(carry, i):
            kv, st = carry
            eff = jnp.where(i == 0, full_active, full_active & ~freeze)
            kv, st, tokens = self._decode_fn(
                params, kv, dataclasses.replace(st, active=eff)
            )
            st = dataclasses.replace(st, active=full_active)
            return (kv, st), tokens

        (kv, state), tokens = jax.lax.scan(
            body, (kv, state), jnp.arange(n), length=n
        )
        return kv, state, tokens

    def _prefill_fn(self, params, kv: KVCache, state: DecodeState,
                    tokens, length, slot, *, bucket: int, embeds=None):
        cfg = self.cfg
        positions = jnp.arange(bucket, dtype=jnp.int32)[None, :]
        attn = self._prefill_attn(length) or self._se_attn(
            positions, positions[0])
        mask = kvc.prefill_mask(cfg, bucket, length)
        write = kvc.prefill_write(slot, jnp.zeros((), jnp.int32))
        hidden, new_stack = self._forward(
            params, tokens, positions, write, kv.stacked(), mask,
            attn=attn, embeds=embeds,
        )
        last_h = jax.lax.dynamic_index_in_dim(hidden[0], length - 1, keepdims=True)
        logits = mdl.logits_from_hidden(cfg, params, last_h)  # [1, V]
        counts = smp.count_prompt_tokens(state.counts, slot, tokens[0], length)
        slot_params = jax.tree.map(lambda a: a[slot][None], state.params)
        tok, new_key = smp.sample(
            logits, slot_params, counts[slot][None], state.keys[slot][None],
            state.bias[slot][None],
        )
        new_state = dataclasses.replace(
            state,
            tokens=state.tokens.at[slot].set(tok[0]),
            positions=state.positions.at[slot].set(length),
            active=state.active.at[slot].set(True),
            keys=state.keys.at[slot].set(new_key[0]),
            counts=counts,
        )
        return KVCache.from_stacked(new_stack), new_state, tok[0]

    def _prefill_mm_fn(self, params, kv: KVCache, state: DecodeState,
                       tokens, length, slot, mm_embeds, mm_positions,
                       *, bucket: int):
        """Multimodal prefill: token embeddings with image-embedding blocks
        scattered over the placeholder positions (parity: llama.cpp's
        image-embedding batch injection, grpc-server.cpp:1397-1424 — but as
        one fused program instead of interleaved decode batches).

        mm_embeds [n_mm, D] float32, mm_positions [n_mm] i32 (positions are
        < length by construction in the scheduler)."""
        from localai_tpu.models import quant as qnt

        dtype = jnp.dtype(self.cfg.dtype)
        x = qnt.embed_rows(params["embed"], tokens, dtype)  # [1, bucket, D]
        x = x.at[0, mm_positions].set(mm_embeds.astype(dtype))
        return self._prefill_fn(
            params, kv, state, tokens, length, slot, bucket=bucket, embeds=x
        )

    def _prefill_resume_fn(self, params, kv: KVCache, state: DecodeState,
                           tokens, length, offset, slot, counts_row,
                           *, bucket: int):
        """Suffix prefill: the slot keeps ``offset`` tokens of reused prefix
        KV; only the tail chunk is computed, attending over prefix + chunk
        (XLA path — keys span the full cache row, which the fresh-chunk
        Pallas prefill kernel does not model). ``counts_row`` [V] i32 is the
        host-side bincount of the FULL prompt (the in-program count would
        only see the tail); it rides this dispatch so resume stays a single
        program launch."""
        cfg = self.cfg
        positions = offset + jnp.arange(bucket, dtype=jnp.int32)[None, :]
        attn = self._se_attn(
            positions, jnp.arange(self.max_ctx, dtype=jnp.int32))
        mask = kvc.resume_mask(cfg, bucket, offset, self.max_ctx)
        write = kvc.resume_write(slot, offset)
        hidden, new_stack = self._forward(
            params, tokens, positions, write, kv.stacked(), mask, attn=attn,
        )
        last_h = jax.lax.dynamic_index_in_dim(hidden[0], length - 1,
                                              keepdims=True)
        logits = mdl.logits_from_hidden(cfg, params, last_h)  # [1, V]
        counts = state.counts.at[slot].set(counts_row)
        slot_params = jax.tree.map(lambda a: a[slot][None], state.params)
        tok, new_key = smp.sample(
            logits, slot_params, counts[slot][None],
            state.keys[slot][None], state.bias[slot][None],
        )
        new_state = dataclasses.replace(
            state,
            tokens=state.tokens.at[slot].set(tok[0]),
            positions=state.positions.at[slot].set(offset + length),
            active=state.active.at[slot].set(True),
            keys=state.keys.at[slot].set(new_key[0]),
            counts=counts,
        )
        return KVCache.from_stacked(new_stack), new_state, tok[0]

    def _prefill_sp_fn(self, params, kv: KVCache, state: DecodeState,
                       tokens, length, slot, *, bucket: int):
        """Sequence-parallel prefill: the prompt chunks over the 'seq' mesh
        axis, each device runs blockwise ring attention (KV chunks rotating
        over ICI via ppermute — parallel.ring), and the resulting per-layer
        K/V lands in the slot cache. tokens: [bucket] i32 (1-D)."""
        from localai_tpu.parallel import ring

        cfg = self.cfg
        hidden, (ks, vs) = ring.sp_prefill_forward(
            cfg, params, tokens, length, self.mesh, self.rope
        )
        # [L, T, Hkv, hd] → cache layout [L, 1, Hkv, T, hd]
        k_hm = ks.transpose(0, 2, 1, 3)[:, None]
        v_hm = vs.transpose(0, 2, 1, 3)[:, None]
        zero = jnp.zeros((), jnp.int32)
        idx = (zero, slot, zero, zero, zero)
        if kv.quantized:
            kq, kscale = kvc._quant_chunk(k_hm)
            vq, vscale = kvc._quant_chunk(v_hm)
            new_kv = KVCache(
                k=jax.lax.dynamic_update_slice(kv.k, kq, idx),
                v=jax.lax.dynamic_update_slice(kv.v, vq, idx),
                k_scale=jax.lax.dynamic_update_slice(
                    kv.k_scale, kscale, idx[:4]),
                v_scale=jax.lax.dynamic_update_slice(
                    kv.v_scale, vscale, idx[:4]),
            )
        else:
            kdt = kv.k.dtype
            new_kv = KVCache(
                k=jax.lax.dynamic_update_slice(kv.k, k_hm.astype(kdt), idx),
                v=jax.lax.dynamic_update_slice(kv.v, v_hm.astype(kdt), idx),
            )
        last_h = jax.lax.dynamic_index_in_dim(hidden[0], length - 1,
                                              keepdims=True)
        logits = mdl.logits_from_hidden(cfg, params, last_h)  # [1, V]
        counts = smp.count_prompt_tokens(state.counts, slot, tokens, length)
        slot_params = jax.tree.map(lambda a: a[slot][None], state.params)
        tok, new_key = smp.sample(
            logits, slot_params, counts[slot][None], state.keys[slot][None],
            state.bias[slot][None],
        )
        new_state = dataclasses.replace(
            state,
            tokens=state.tokens.at[slot].set(tok[0]),
            positions=state.positions.at[slot].set(length),
            active=state.active.at[slot].set(True),
            keys=state.keys.at[slot].set(new_key[0]),
            counts=counts,
        )
        return new_kv, new_state, tok[0]

    # -- paged programs (block-pool KV; engine.paged / kvcache.Paged*) ---

    def _decode_paged_fn(self, params, kv: kvc.PagedKVCache,
                         state: DecodeState, tables):
        """Batched single-token decode over the block pool. ``tables``
        [S, MB] i32 is the device mirror of the allocator's block tables
        (not donated — it changes only at admit/release)."""
        cfg = self.cfg
        pos = state.positions
        if self.overlap_mode:
            # manual-TP trunk with decomposed per-layer reductions
            # (parallel.overlap); sampling/logits keep the GSPMD tail
            from localai_tpu.parallel import overlap as ovl

            trunk = {k: params[k] for k in ovl.TRUNK_KEYS}
            hidden, new_stack = ovl.paged_decode_trunk(
                cfg, trunk, self.mesh, state.tokens, pos,
                kv.stacked(), tables, self.rope,
                ctx_pad=self.ctx_pad,
                mode=self.overlap_mode,
                chunks=self.overlap_chunks,
                use_pallas=self.paged_attn_impl == "pallas",
                interpret=self._paged_attn_interpret,
                num_buffers=self.paged_num_buffers,
            )
            new_state, tokens = self._decode_tail(params, state, hidden)
            return (kvc.PagedKVCache.from_stacked(new_stack), new_state,
                    tokens)
        raw = self.paged_attn_impl == "pallas"
        attn = None
        if raw:
            from localai_tpu import ops

            kernel = partial(
                ops.paged_decode_attention,
                sliding_window=cfg.sliding_window,
                interpret=self._paged_attn_interpret,
                num_buffers=self.paged_num_buffers,
            )
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P

                # per-device kernel over (slots/'data', heads/'model'):
                # the pool's block axis stays whole on every device (table
                # values are global block ids), its kv-head axis shards on
                # 'model', and each data shard walks its own slots' SMEM
                # table mirror — the shard_map body is the single-device
                # kernel (select_paged_attn_impl gates Pallas off when the
                # head groups don't split over tp)
                in_specs = [P("data", "model", None),
                            P(None, "model", None, None),
                            P(None, "model", None, None),
                            P("data", None),
                            P("data")]
                if kv.quantized:
                    in_specs += [P(None, "model", None),
                                 P(None, "model", None)]
                kernel = shard_map(
                    kernel,
                    mesh=self.mesh,
                    in_specs=tuple(in_specs),
                    out_specs=P("data", "model", None),
                    check_vma=False,
                )

            def attn(q, keys, values, _mask):  # q [S,1,Hq,hd]; keys = pool
                if kv.quantized:  # (int8 pool, f32 scales) — fused dequant
                    out = kernel(q[:, 0], keys[0], values[0], tables, pos,
                                 keys[1], values[1])
                else:
                    out = kernel(q[:, 0], keys, values, tables, pos)
                return out[:, None]

        mask = kvc.decode_mask(cfg, pos, self.ctx_pad)
        write = kvc.paged_decode_write(tables, pos, raw=raw)
        hidden, new_stack = self._forward(
            params, state.tokens[:, None], pos[:, None],
            write, kv.stacked(), mask, attn=attn,
        )
        new_state, tokens = self._decode_tail(params, state, hidden)
        return kvc.PagedKVCache.from_stacked(new_stack), new_state, tokens

    def _decode_paged_n_fn(self, params, kv, state, tables, *, n: int):
        """n paged decode steps in one dispatch (lax.scan) — the paged
        twin of _decode_n_fn. The block tables are loop-invariant: every
        admitted slot's table already covers its full reservation."""

        def body(carry, _):
            kv, state = carry
            kv, state, tokens = self._decode_paged_fn(
                params, kv, state, tables)
            return (kv, state), tokens

        (kv, state), tokens = jax.lax.scan(body, (kv, state), None, length=n)
        return kv, state, tokens

    def _decode_paged_frozen_n_fn(self, params, kv, state, tables, freeze,
                                  *, n: int):
        """Paged twin of _decode_frozen_n_fn (see its docstring)."""
        full_active = state.active

        def body(carry, i):
            kv, st = carry
            eff = jnp.where(i == 0, full_active, full_active & ~freeze)
            kv, st, tokens = self._decode_paged_fn(
                params, kv, dataclasses.replace(st, active=eff), tables
            )
            st = dataclasses.replace(st, active=full_active)
            return (kv, st), tokens

        (kv, state), tokens = jax.lax.scan(
            body, (kv, state), jnp.arange(n), length=n
        )
        return kv, state, tokens

    def _prefill_paged_fn(self, params, kv, state, tokens, length, offset,
                          table_row, slot, counts_row, *, bucket: int,
                          sample: bool, embeds=None):
        """One chunked-prefill dispatch: write ``length`` real tokens of the
        chunk at absolute positions [offset, offset+length) through the
        slot's block table, attending resume-style over the gathered prefix
        + chunk. Non-final chunks (``sample=False``) leave the decode state
        untouched; the final chunk samples the first token and arms the
        slot exactly like the contiguous prefill paths."""
        cfg = self.cfg
        positions = offset + jnp.arange(bucket, dtype=jnp.int32)[None, :]
        mask = kvc.resume_mask(cfg, bucket, offset, self.ctx_pad)
        write = kvc.paged_prefill_write(table_row, offset, length)
        hidden, new_stack = self._forward(
            params, tokens, positions, write, kv.stacked(), mask,
            embeds=embeds,
        )
        new_kv = kvc.PagedKVCache.from_stacked(new_stack)
        if not sample:
            return new_kv, state, jnp.zeros((), jnp.int32)
        last_h = jax.lax.dynamic_index_in_dim(hidden[0], length - 1,
                                              keepdims=True)
        logits = mdl.logits_from_hidden(cfg, params, last_h)  # [1, V]
        counts = state.counts.at[slot].set(counts_row)
        slot_params = jax.tree.map(lambda a: a[slot][None], state.params)
        tok, new_key = smp.sample(
            logits, slot_params, counts[slot][None],
            state.keys[slot][None], state.bias[slot][None],
        )
        new_state = dataclasses.replace(
            state,
            tokens=state.tokens.at[slot].set(tok[0]),
            positions=state.positions.at[slot].set(offset + length),
            active=state.active.at[slot].set(True),
            keys=state.keys.at[slot].set(new_key[0]),
            counts=counts,
        )
        return new_kv, new_state, tok[0]

    def _prefill_paged_mm_fn(self, params, kv, state, tokens, length,
                             table_row, slot, mm_embeds, mm_positions,
                             counts_row, *, bucket: int):
        """Multimodal paged prefill: single-dispatch (never chunked — the
        scattered image embeddings must ride one program, mirroring
        _prefill_mm_fn), offset 0, always samples."""
        from localai_tpu.models import quant as qnt

        dtype = jnp.dtype(self.cfg.dtype)
        x = qnt.embed_rows(params["embed"], tokens, dtype)  # [1, bucket, D]
        x = x.at[0, mm_positions].set(mm_embeds.astype(dtype))
        return self._prefill_paged_fn(
            params, kv, state, tokens, length, jnp.zeros((), jnp.int32),
            table_row, slot, counts_row, bucket=bucket, sample=True,
            embeds=x,
        )

    def _prefill_paged_sp_fn(self, params, kv, state, tokens, length,
                             table_row, slot, counts_row, *, bucket: int):
        """Sequence-parallel paged prefill: the prompt chunks over the
        'seq' mesh axis, each device runs blockwise ring attention
        (parallel.ring — composes with 'model'-sharded weights), and the
        resulting per-layer K/V scatters straight into the slot's reserved
        blocks through its table row. One dispatch, all chips, no gathered
        context. Always the FINAL (only) dispatch of its admission —
        samples and arms the slot exactly like the final chunk of
        _prefill_paged_fn. tokens: [bucket] i32 (1-D, like _prefill_sp_fn);
        only fresh admissions route here (offset 0 — shared/loaded prefix
        rows fall back to the chunked path)."""
        from localai_tpu.parallel import ring

        cfg = self.cfg
        hidden, (ks, vs) = ring.sp_prefill_forward(
            cfg, params, tokens, length, self.mesh, self.rope
        )
        # ks/vs [L, T, Hkv, hd] → scatter through the table row; padding
        # rows (t >= length) redirect to the trash block exactly like
        # kvcache.paged_prefill_write
        bt = self.block_tokens
        MB = table_row.shape[0]
        T = tokens.shape[0]
        t = jnp.arange(T)
        valid = t < length
        blk = jnp.where(valid, table_row[jnp.minimum(t // bt, MB - 1)], 0)
        off = t % bt
        # advanced indices (blk, off) around the head slice broadcast to
        # the FRONT: the set value is row-major [T, L, H, ...]
        if kv.quantized:
            # int4 pools (packed hd/2 last dim) take the nibble packer
            quant = (kvc._quant_chunk4
                     if kv.k.shape[-1] * 2 == ks.shape[-1]
                     else kvc._quant_chunk)
            kq, kscale = quant(ks)   # [L,T,H,hd or hd/2], [L,T,H]
            vq, vscale = quant(vs)
            new_kv = kvc.PagedKVCache(
                k=kv.k.at[:, blk, :, off].set(kq.transpose(1, 0, 2, 3)),
                v=kv.v.at[:, blk, :, off].set(vq.transpose(1, 0, 2, 3)),
                k_scale=kv.k_scale.at[:, blk, :, off].set(
                    kscale.transpose(1, 0, 2)),
                v_scale=kv.v_scale.at[:, blk, :, off].set(
                    vscale.transpose(1, 0, 2)),
            )
        else:
            kdt = kv.k.dtype
            new_kv = kvc.PagedKVCache(
                k=kv.k.at[:, blk, :, off].set(
                    ks.transpose(1, 0, 2, 3).astype(kdt)),
                v=kv.v.at[:, blk, :, off].set(
                    vs.transpose(1, 0, 2, 3).astype(kdt)),
            )
        last_h = jax.lax.dynamic_index_in_dim(hidden[0], length - 1,
                                              keepdims=True)
        logits = mdl.logits_from_hidden(cfg, params, last_h)  # [1, V]
        counts = state.counts.at[slot].set(counts_row)
        slot_params = jax.tree.map(lambda a: a[slot][None], state.params)
        tok, new_key = smp.sample(
            logits, slot_params, counts[slot][None],
            state.keys[slot][None], state.bias[slot][None],
        )
        new_state = dataclasses.replace(
            state,
            tokens=state.tokens.at[slot].set(tok[0]),
            positions=state.positions.at[slot].set(length),
            active=state.active.at[slot].set(True),
            keys=state.keys.at[slot].set(new_key[0]),
            counts=counts,
        )
        return new_kv, new_state, tok[0]

    def _embed_fn(self, params, tokens, length, *, bucket: int):
        """Mean-pooled final hidden state over the real tokens — the LLM
        embeddings path (parity: llama.cpp embeddings mode behind the
        Embedding RPC, backend.proto:16; reference core/backend/
        embeddings.go:13). Uses a throwaway single-sequence KV so it never
        touches serving slots."""
        cfg = self.cfg
        # throwaway scratch cache stays in the compute dtype even when the
        # serving cache is int8 — it is read back within the same program
        kv_shape = (cfg.num_layers, 1, cfg.num_kv_heads, bucket, cfg.hd)
        kv = (jnp.zeros(kv_shape, jnp.dtype(cfg.dtype)),
              jnp.zeros(kv_shape, jnp.dtype(cfg.dtype)))
        positions = jnp.arange(bucket, dtype=jnp.int32)[None, :]
        mask = kvc.prefill_mask(cfg, bucket, length)
        write = kvc.prefill_write(jnp.int32(0), jnp.zeros((), jnp.int32))
        attn = self._prefill_attn(length) or self._se_attn(
            positions, positions[0])
        hidden, _ = self._forward(
            params, tokens, positions, write, kv, mask, attn=attn,
        )
        valid = (jnp.arange(bucket) < length)[None, :, None]
        # pool in f32: a bf16 sum over thousands of positions loses the
        # precision the embeddings exist to provide
        summed = jnp.sum((hidden * valid).astype(jnp.float32), axis=1)
        pooled = summed / jnp.maximum(length, 1).astype(jnp.float32)
        return pooled[0]

    def _se_attn(self, qpos, kpos):
        """Self-extend attend for the XLA paths (None when ga_n == 1) —
        the single construction point for all four call sites."""
        if self.ga_n <= 1:
            return None
        from localai_tpu.engine import selfextend as se

        return se.build_attend(
            self.cfg, self._se_rope, self.ga_n, self.ga_w,
            qpos=qpos, kpos=kpos,
        )

    def _forward(self, params, tokens, positions, write, stack, mask,
                 attn=None, embeds=None):
        """models.llama.forward, or the pipeline-parallel stage chain
        when the mesh has a 'pipe' axis (layer-sharded capacity scaling —
        parallel.pipeline; attn overrides don't apply there: pp forces the
        XLA attend and gates self-extend/Pallas off at init)."""
        if self.pp_enabled:
            from localai_tpu.parallel import pipeline as pp

            return pp.pp_forward(
                self.cfg, params, tokens, positions, write, stack, mask,
                self.rope, self.mesh, embeds=embeds,
            )
        return mdl.forward(
            self.cfg, params, tokens, positions, write, stack, mask,
            self.rope, attn=attn, embeds=embeds,
        )

    def _prefill_attn(self, length):
        """Pallas flash attention for the prefill/embed paths (None = XLA)."""
        if self.attn_impl != "pallas":
            return None
        from localai_tpu import ops

        cfg = self.cfg
        kernel = partial(
            ops.prefill_attention,
            sliding_window=cfg.sliding_window,
            interpret=self._attn_interpret,
        )
        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            # single-sequence prefill: only the head dim shards ('model');
            # each device runs flash attention over its head group
            kernel = shard_map(
                kernel,
                mesh=self.mesh,
                in_specs=(P(None, "model", None), P("model", None, None),
                          P("model", None, None), P()),
                out_specs=P(None, "model", None),
                check_vma=False,
            )

        def attn(q, keys, values, _mask):  # q [1,T,Hq,hd], keys [1,Hkv,T,hd]
            out = kernel(q[0], keys[0], values[0], length)
            return out[None]

        return attn

    # -- host API --------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds max prefill bucket {self.buckets[-1]}"
        )

    def acquire_slot(self, slot: Optional[int] = None) -> Optional[int]:
        """Claim a free slot — FIFO by default, or a specific free slot
        (the scheduler targets the slot with the longest reusable prefix)."""
        if not self._free_slots:
            return None
        if slot is not None and slot in self._free_slots:
            self._free_slots.remove(slot)
            return slot
        return self._free_slots.pop(0)

    def free_slots(self) -> list[int]:
        return list(self._free_slots)

    def admit(
        self,
        slot: int,
        prompt: list[int],
        *,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repeat_penalty: Optional[float] = None,
        presence_penalty: Optional[float] = None,
        frequency_penalty: Optional[float] = None,
        seed: Optional[int] = None,
        logit_bias: Optional[dict[int, float]] = None,
        bias_row: Optional[np.ndarray] = None,
        mm_embeds: Optional[np.ndarray] = None,    # [n_mm, D] image embeds
        mm_positions: Optional[np.ndarray] = None,  # [n_mm] prompt positions
        resident: Optional[list[int]] = None,       # slot's previous tokens
                                                    # (enables prefix reuse)
        valid_n: Optional[int] = None,              # slot's KV frontier, from
                                                    # a batched slot_positions()
                                                    # read (None → read it here)
        reserve_tokens: Optional[int] = None,       # paged mode: worst-case
                                                    # rows (prompt + max_new)
                                                    # to reserve; None → max_ctx
        spec_tokens: int = 0,                       # paged mode: extra
                                                    # speculation-lookahead rows
                                                    # (localai_tpu.spec);
                                                    # ignored contiguous
    ) -> int:
        """Prefill a prompt into a slot; returns the first sampled token.

        When ``resident`` is given and shares a long-enough prefix with the
        prompt, the prefix KV is kept and only the tail is prefilled
        (parity: llama.cpp common_part slot reuse, grpc-server.cpp:67-74).
        Callers that already hold a slot_positions() snapshot pass
        ``valid_n`` so admission stays a single device sync."""
        if not prompt:
            prompt = [0]
        n = len(prompt)
        if n > self.max_ctx - 1:
            # context-exhaustion policy parity (grpc-server.cpp:1573-1592):
            # reject rather than silently shift context.
            raise ValueError(f"prompt ({n} tokens) exceeds context {self.max_ctx}")
        if self.paged:
            adm = self.begin_admit(
                slot, prompt,
                reserve_tokens=reserve_tokens,
                spec_tokens=spec_tokens,
                resident=resident, valid_n=valid_n,
                mm_embeds=mm_embeds, mm_positions=mm_positions,
                temperature=temperature, top_k=top_k, top_p=top_p,
                min_p=min_p, repeat_penalty=repeat_penalty,
                presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty,
                seed=seed, logit_bias=logit_bias, bias_row=bias_row,
            )
            if adm is None:
                raise RuntimeError(
                    "KV block pool exhausted: cannot reserve "
                    f"{len(prompt)} prompt tokens (direct admit has no "
                    "queue; size the pool via LOCALAI_KV_BLOCKS or admit "
                    "through the scheduler)")
            while True:
                tok = adm.step_chunk()
                if tok is not None:
                    return tok
        lcp = 0
        if resident and mm_embeds is None:
            lcp = self.reusable_prefix(slot, resident, prompt, valid_n)
        self.last_prefix_reused = lcp
        self.total_prefix_reused += lcp
        tail = prompt[lcp:]
        bucket = (self._resume_bucket(len(tail), lcp) if lcp
                  else self.bucket_for(n))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(tail)] = tail
        self._prepare_slot(
            slot, temperature=temperature, top_k=top_k, top_p=top_p,
            min_p=min_p, repeat_penalty=repeat_penalty,
            presence_penalty=presence_penalty,
            frequency_penalty=frequency_penalty,
            seed=seed, logit_bias=logit_bias, bias_row=bias_row,
        )
        n_seq = self.mesh.shape.get("seq", 1) if self.mesh is not None else 1
        use_sp = (
            self.sp_enabled and not lcp and mm_embeds is None
            and n >= self.sp_threshold and bucket % n_seq == 0
        )
        if use_sp:
            self.last_prefill_path = "sp"
            self.kv, self.state, tok = self._prefill_sp(
                self.params, self.kv, self.state,
                jnp.asarray(padded[0]), jnp.int32(n), jnp.int32(slot),
                bucket=bucket,
            )
        elif lcp:
            self.last_prefill_path = "resume"
            crow = _prompt_counts_row(self.cfg.vocab_size, prompt)
            self.kv, self.state, tok = self._prefill_resume(
                self.params, self.kv, self.state,
                jnp.asarray(padded), jnp.int32(len(tail)), jnp.int32(lcp),
                jnp.int32(slot), jnp.asarray(crow), bucket=bucket,
            )
        elif mm_embeds is not None and len(mm_embeds):
            self.last_prefill_path = "mm"
            self.kv, self.state, tok = self._prefill_mm(
                self.params, self.kv, self.state,
                jnp.asarray(padded), jnp.int32(n), jnp.int32(slot),
                jnp.asarray(mm_embeds, jnp.float32),
                jnp.asarray(mm_positions, jnp.int32),
                bucket=bucket,
            )
        else:
            self.last_prefill_path = "full"
            self.kv, self.state, tok = self._prefill(
                self.params, self.kv, self.state,
                jnp.asarray(padded), jnp.int32(n), jnp.int32(slot),
                bucket=bucket,
            )
        self._active_slots.add(slot)
        # the first sampled token seeds the host-side stream state; this
        # one admit-time sync is the prefill/decode handoff point (guarded:
        # a dead tunnel would otherwise hang here silently forever)
        with self.watchdog.guard("device"):
            return int(tok)  # jaxlint: disable=host-sync-in-hot-path

    def _prepare_slot(self, slot: int, *, temperature=None, top_k=None,
                      top_p=None, min_p=None, repeat_penalty=None,
                      presence_penalty=None, frequency_penalty=None,
                      seed=None, logit_bias=None, bias_row=None) -> None:
        """Per-slot sampling params + PRNG seed + logit-bias row — the
        admission preamble shared by the contiguous and paged paths."""
        self.state = dataclasses.replace(
            self.state,
            params=self.state.params.with_slot(
                slot,
                temperature=temperature,
                top_k=top_k,
                top_p=top_p,
                min_p=min_p,
                repeat_penalty=repeat_penalty,
                presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty,
            ),
        )
        if seed is not None:
            self.state = dataclasses.replace(
                self.state,
                keys=self.state.keys.at[slot].set(jax.random.key(seed)),
            )
        if bias_row is not None:
            row = np.asarray(bias_row, np.float32).copy()
        else:
            row = np.zeros(self.cfg.vocab_size, np.float32)
        if logit_bias:
            for tid, b in logit_bias.items():
                if 0 <= int(tid) < self.cfg.vocab_size:
                    row[int(tid)] += b
        self.set_bias(slot, row)

    # -- paged admission (chunked prefill; engine.paged) -----------------

    def begin_admit(
        self, slot: int, prompt: list[int], *,
        reserve_tokens: Optional[int] = None,
        spec_tokens: int = 0,
        resident: Optional[list[int]] = None,
        valid_n: Optional[int] = None,
        mm_embeds=None, mm_positions=None,
        **sampling,
    ) -> Optional["PagedAdmission"]:
        """Start a chunked paged admission: reserve blocks (sharing pooled
        prefix blocks where the prompt allows), arm the slot's sampling
        state, and return a PagedAdmission whose ``step_chunk()`` the
        caller drives — interleaving chunk dispatches with decode
        dispatches so one long prompt never stalls other slots' TPOT.
        ``spec_tokens`` reserves extra speculation rows past the decode
        worst case (a draft window writes up to gamma rows beyond the
        frontier; see localai_tpu.spec) — recorded separately by the
        allocator so rollback accounting is auditable. Returns None when
        the pool cannot cover the reservation (the scheduler keeps the
        request queued)."""
        assert self.paged, "begin_admit requires a paged runner"
        if not prompt:
            prompt = [0]
        n = len(prompt)
        if n > self.max_ctx - 1:
            raise ValueError(
                f"prompt ({n} tokens) exceeds context {self.max_ctx}")
        reserve = min(self.max_ctx, max(n + 1, reserve_tokens
                                        or self.max_ctx))
        # the speculation lookahead never needs rows past max_ctx (the
        # write policy trash-redirects there and the scheduler gates
        # windows off near the edge)
        spec_tokens = max(0, min(int(spec_tokens), self.max_ctx - reserve))
        if self.allocator.blocks_for(
                reserve + spec_tokens) > self.allocator.num_blocks - 1:
            # can NEVER fit, even with an empty pool (overcommitted
            # LOCALAI_KV_BLOCKS): reject like the prompt-exceeds-context
            # check — holding it would head-of-line block admission forever
            raise ValueError(
                f"reservation of {reserve + spec_tokens} tokens "
                f"({self.allocator.blocks_for(reserve + spec_tokens)} "
                f"blocks) exceeds the block pool "
                f"({self.allocator.num_blocks - 1} blocks); "
                "lower max_new_tokens or raise LOCALAI_KV_BLOCKS")
        mm = mm_embeds is not None and len(mm_embeds) > 0
        lcp = 0
        if resident and not mm and self._loaded_rows.get(slot):
            # rows just loaded from the disk prompt cache (load_prefix) —
            # the only slot-resident reuse paged mode has; pool sharing
            # covers everything else
            lcp = self.reusable_prefix(slot, resident, prompt, valid_n)
        if lcp:
            if not self.allocator.extend(slot, reserve,
                                         spec_tokens=spec_tokens):
                self.allocator.release(slot)
                self._loaded_rows.pop(slot, None)
                return None
            self.last_prefill_path = "paged_resume"
        else:
            if slot in self.allocator.tables:  # stale loaded rows
                self.allocator.release(slot)
            self._loaded_rows.pop(slot, None)
            shared = self.allocator.allocate(
                slot, reserve, prompt=None if mm else prompt,
                spec_tokens=spec_tokens)
            if shared is None:
                return None
            lcp = shared
            self.last_prefill_path = ("paged_mm" if mm
                                      else "paged_shared" if shared
                                      else "paged")
        self.last_prefix_reused = lcp
        self.total_prefix_reused += lcp
        # long fresh prompts on a 'seq' mesh take the ring-attention path:
        # one dispatch over all chips writing straight into the reserved
        # blocks (shared/loaded prefix rows need the resume-style chunk
        # attention, so any lcp keeps the chunked path)
        n_seq = self.mesh.shape.get("seq", 1) if self.mesh is not None else 1
        use_sp = (self.sp_enabled and not mm and lcp == 0
                  and n >= self.sp_threshold
                  and self.bucket_for(n) % n_seq == 0)
        if use_sp:
            self.last_prefill_path = "paged_sp"
        self._prepare_slot(slot, **sampling)
        return PagedAdmission(self, slot, list(prompt), lcp,
                              mm_embeds=mm_embeds,
                              mm_positions=mm_positions, sp=use_sp)

    def _install_table_row(self, slot: int) -> None:
        self.block_tables = self.block_tables.at[slot].set(
            jnp.asarray(self.allocator.table_row(slot)))

    def _finish_paged_admit(self, slot: int, prompt: list[int],
                            mm: bool) -> None:
        """Final-chunk bookkeeping: expose the block table to the decode
        programs, publish the prompt's full blocks to the prefix pool
        (their contents are dispatched by now; token-keyed sharing is
        meaningless for multimodal prompts), mark the slot live."""
        self._install_table_row(slot)
        if not mm:
            self.allocator.register_prefix(slot, prompt)
        self._loaded_rows.pop(slot, None)
        self._active_slots.add(slot)

    def reusable_prefix(self, slot: int, resident: Optional[list[int]],
                        prompt: list[int],
                        valid_n: Optional[int] = None) -> int:
        """Tokens of ``resident`` (the slot's previous prompt+generation)
        that admit() would actually reuse for ``prompt`` — all feasibility
        gates applied: KV-validity clipping (the last sampled token's KV is
        never written), last-token recompute, minimum worthwhile length,
        and the tail bucket fitting inside the context. The scheduler ranks
        candidate slots with this same function so its choice can't
        collapse to zero at admit time. ``valid_n`` overrides the KV
        validity frontier (disk prompt-cache hits score their own row count
        instead of the slot's current position)."""
        if not resident or not prompt:
            return 0
        if valid_n is None:
            valid_n = (self._loaded_rows.get(slot, 0) if self.paged
                       else self.slot_position(slot))
        valid = resident[:valid_n]
        lcp = 0
        for a, b in zip(valid, prompt):
            if a != b:
                break
            lcp += 1
        # always recompute at least the last token (its logits seed sampling)
        lcp = min(lcp, len(prompt) - 1)
        if lcp < self.prefix_reuse_min:
            return 0
        if self.paged:
            # chunked writes redirect bucket overshoot to the trash block,
            # so any in-context tail is feasible — no bucket-fit gate
            return lcp
        if self._resume_bucket(len(prompt) - lcp, lcp) is None:
            return 0
        return lcp

    def resident_rows(self, slot: int, default: int) -> int:
        """KV rows of ``slot`` that are actually resident for prefix reuse.
        Contiguous mode: the device frontier the caller already read
        (``default``). Paged mode: blocks are freed at release, so only
        rows just loaded from the disk prompt cache count."""
        if not self.paged:
            return default
        return min(default, self._loaded_rows.get(slot, 0))

    def _resume_bucket(self, tail_len: int, offset: int) -> Optional[int]:
        """Smallest prefill bucket holding the tail that also fits in the
        cache past the kept prefix (dynamic_update_slice clamps start
        indices, so an overhanging bucket would silently shift the write)."""
        for b in self.buckets:
            if tail_len <= b and offset + b <= self.max_ctx:
                return b
        return None

    def step(self) -> np.ndarray:
        """One decode iteration over all slots; returns sampled tokens [S].

        Synchronous by contract — the blocking host read IS the API
        (constraint gating needs the token before the next dispatch);
        pipelined callers use step_async()."""
        t0 = time.perf_counter()
        tokens = self.step_async()
        t1 = time.perf_counter()
        with self.watchdog.guard("device"):
            out = np.asarray(tokens)  # jaxlint: disable=host-sync-in-hot-path
        self.last_launch_ms = (t1 - t0) * 1e3
        self.last_sync_ms = (time.perf_counter() - t1) * 1e3
        return out

    def step_async(self) -> jax.Array:
        """Like step() but returns the device array without synchronizing —
        callers overlap the host read with the next dispatch."""
        if self.paged:
            self.kv, self.state, tokens = self._decode_paged(
                self.params, self.kv, self.state, self.block_tables
            )
            return tokens
        self.kv, self.state, tokens = self._decode(
            self.params, self.kv, self.state
        )
        return tokens

    def verify_async(self, proposals) -> jax.Array:
        """One speculative verify dispatch over all slots: score the
        [S, gamma] draft ``proposals`` with a single gamma+1-wide target
        forward, accept/sample on device, and return the [gamma+1, S]
        emitted-token device array (SKIP = nothing for that step/slot).
        Works on both KV layouts; the paged variant writes the window
        through the block-table mirror and rolls rejected tails back
        per slot. No host sync — callers overlap the read."""
        proposals = jnp.asarray(proposals, jnp.int32)
        if self.paged:
            self.kv, self.state, emitted = self._verify_paged(
                self.params, self.kv, self.state, self.block_tables,
                proposals,
            )
            return emitted
        self.kv, self.state, emitted = self._verify(
            self.params, self.kv, self.state, proposals
        )
        return emitted

    def step_n(self, n: int) -> np.ndarray:
        """n decode iterations in one dispatch; returns tokens [n, S].
        Synchronous by contract — see step(); hot callers use
        step_n_async()."""
        t0 = time.perf_counter()
        tokens = self.step_n_async(n)
        t1 = time.perf_counter()
        with self.watchdog.guard("device"):
            out = np.asarray(tokens)  # jaxlint: disable=host-sync-in-hot-path
        self.last_launch_ms = (t1 - t0) * 1e3
        self.last_sync_ms = (time.perf_counter() - t1) * 1e3
        return out

    def step_n_async(self, n: int) -> jax.Array:
        """Like step_n() but returns the [n, S] device array without
        synchronizing — callers overlap the host read with later dispatches."""
        if self.paged:
            self.kv, self.state, tokens = self._decode_paged_n(
                self.params, self.kv, self.state, self.block_tables, n=n
            )
            return tokens
        self.kv, self.state, tokens = self._decode_n(
            self.params, self.kv, self.state, n=n
        )
        return tokens

    def step_frozen_n(self, freeze: np.ndarray, n: int) -> np.ndarray:
        """n decode iterations where ``freeze``-masked slots advance only on
        the first; returns tokens [n, S] (rows 1+ stale for frozen slots)."""
        t0 = time.perf_counter()
        if self.paged:
            self.kv, self.state, tokens = self._decode_paged_frozen_n(
                self.params, self.kv, self.state, self.block_tables,
                jnp.asarray(freeze, jnp.bool_), n=n,
            )
        else:
            self.kv, self.state, tokens = self._decode_frozen_n(
                self.params, self.kv, self.state,
                jnp.asarray(freeze, jnp.bool_), n=n,
            )
        # synchronous by contract: the frozen slots' constraint masks need
        # the sampled token on the host before the next dispatch
        t1 = time.perf_counter()
        with self.watchdog.guard("device"):
            out = np.asarray(tokens)  # jaxlint: disable=host-sync-in-hot-path
        self.last_launch_ms = (t1 - t0) * 1e3
        self.last_sync_ms = (time.perf_counter() - t1) * 1e3
        return out

    def embed(self, prompt: list[int]) -> np.ndarray:
        """[D] float32 embedding of a token sequence (bucketed like prefill)."""
        if not prompt:
            prompt = [0]
        n = len(prompt)
        if n > self.max_ctx:
            raise ValueError(f"input ({n} tokens) exceeds context {self.max_ctx}")
        bucket = self.bucket_for(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = prompt
        out = self._embed(
            self.params, jnp.asarray(padded), jnp.int32(n), bucket=bucket
        )
        return np.asarray(out, dtype=np.float32)

    def set_bias(self, slot: int, bias_row: Optional[np.ndarray]) -> None:
        """Replace one slot's [V] additive logit-bias row (grammar masks write
        -1e30 at disallowed ids; None clears)."""
        if bias_row is None:
            row = jnp.zeros(self.cfg.vocab_size, jnp.float32)
        else:
            row = jnp.asarray(bias_row, jnp.float32)
        self.state = dataclasses.replace(
            self.state, bias=self.state.bias.at[slot].set(row)
        )

    def release(self, slot: int) -> None:
        self.state = dataclasses.replace(
            self.state, active=self.state.active.at[slot].set(False)
        )
        if self.paged:
            # free the slot's blocks (prompt blocks registered in the
            # prefix pool survive as reclaimable cache) and point the
            # device table row at the trash block so the decode programs'
            # static-shape garbage writes can't touch reallocated blocks
            self.allocator.release(slot)
            self._loaded_rows.pop(slot, None)
            self.block_tables = self.block_tables.at[slot].set(
                jnp.zeros(self.max_blocks, jnp.int32))
            self.state = dataclasses.replace(
                self.state,
                positions=self.state.positions.at[slot].set(0),
            )
        self._active_slots.discard(slot)
        if slot not in self._free_slots:
            self._free_slots.append(slot)

    @property
    def any_active(self) -> bool:
        # host mirror — admit()/release() are the only transitions, so no
        # device round-trip (and no stall behind in-flight decodes)
        return bool(self._active_slots)

    def slot_positions(self) -> np.ndarray:
        """Every slot's KV frontier in ONE [S] transfer. The scheduler's
        admit path ranks ALL free slots by reusable prefix; per-slot
        int() reads would multiply the device sync by the candidate
        count."""
        # single batched admit-time read — the one deliberate sync here
        with self.watchdog.guard("device"):
            return np.asarray(  # jaxlint: disable=host-sync-in-hot-path
                self.state.positions
            )

    def slot_position(self, slot: int) -> int:
        return int(self.slot_positions()[slot])

    # -- prompt-cache persistence (engine.promptcache) -------------------

    def pack_block(self, bid: int) -> Optional[dict]:
        """One pool block's raw rows as host numpy — the HBM→host spill
        payload (BlockAllocator tiering). Rows keep the pool dtype
        byte-exact: bf16 stays bf16, int4 stays nibble-packed (half the
        f32 bytes), so spill→reload is an identity round-trip."""
        if not self.paged:
            return None
        kv = self.kv
        out = {"k": np.asarray(kv.k[:, bid]), "v": np.asarray(kv.v[:, bid])}
        if kv.quantized:
            out["k_scale"] = np.asarray(kv.k_scale[:, bid])
            out["v_scale"] = np.asarray(kv.v_scale[:, bid])
        return out

    def load_block(self, bid: int, payload: dict) -> None:
        """Scatter a spilled block's rows back into pool block ``bid``
        (tier re-onboarding; inverse of :meth:`pack_block`)."""
        kv = self.kv
        new = {
            "k": kv.k.at[:, bid].set(jnp.asarray(payload["k"], kv.k.dtype)),
            "v": kv.v.at[:, bid].set(jnp.asarray(payload["v"], kv.v.dtype)),
        }
        if kv.quantized:
            new["k_scale"] = kv.k_scale.at[:, bid].set(
                jnp.asarray(payload["k_scale"], jnp.float32))
            new["v_scale"] = kv.v_scale.at[:, bid].set(
                jnp.asarray(payload["v_scale"], jnp.float32))
        self.kv = kvc.PagedKVCache(**new)

    def snapshot_prefix(self, slot: int, n: Optional[int] = None) -> dict:
        """Device-array snapshot of one slot's first ``n`` KV rows.

        The slices are NEW device buffers enqueued in program order, so the
        snapshot is consistent even though later dispatches donate and
        overwrite the cache — callers may hand it to another thread and
        materialize it there (pack_prefix) without stalling the engine."""
        p = n if n is not None else self.slot_position(slot)
        out: dict = {"kv_dtype": str(self.kv_dtype),
                     # self-extend caches store UNroped K — a roped-cache
                     # runner must never load these rows (and vice versa)
                     "kv_rope": "raw" if self.ga_n > 1 else "roped"}
        if self.paged:
            # gather the slot's blocks back into contiguous [L, H, p, ...]
            # rows — the export format is layout-independent, so paged and
            # contiguous runners can share one disk prompt cache
            bt = self.block_tokens
            table = self.allocator.tables.get(slot, [])
            nb = min(max(1, -(-p // bt)), len(table)) if table else 0
            if nb == 0:
                p = 0
                blocks = np.zeros(1, np.int64)
            else:
                p = min(p, nb * bt)
                blocks = np.asarray(table[:nb], np.int64)

            def rows(cache):  # [L, N, H, bt, hd] -> [L, H, p, hd]
                g = cache[:, blocks]
                L, _, H = g.shape[0], g.shape[1], g.shape[2]
                return g.transpose(0, 2, 1, 3, 4).reshape(
                    L, H, len(blocks) * bt, cache.shape[-1])[:, :, :p]

            def srows(sc):    # [L, N, H, bt] -> [L, H, p]
                g = sc[:, blocks]
                L, H = g.shape[0], g.shape[2]
                return g.transpose(0, 2, 1, 3).reshape(
                    L, H, len(blocks) * bt)[:, :, :p]

            out["k"] = rows(self.kv.k)
            out["v"] = rows(self.kv.v)
            if self.kv.quantized:
                out["k_scale"] = srows(self.kv.k_scale)
                out["v_scale"] = srows(self.kv.v_scale)
            return out
        out["k"] = self.kv.k[:, slot, :, :p]
        out["v"] = self.kv.v[:, slot, :, :p]
        if self.kv.quantized:
            out["k_scale"] = self.kv.k_scale[:, slot, :, :p]
            out["v_scale"] = self.kv.v_scale[:, slot, :, :p]
        return out

    @staticmethod
    def pack_prefix(snapshot: dict) -> dict:
        """Materialize a snapshot_prefix result as npz-serializable numpy.
        bfloat16 rows are stored as uint16 bit-views (numpy's npz format
        has no native bfloat16); scaled-int8 caches keep their scales."""
        out: dict = {"kv_dtype": np.asarray(snapshot["kv_dtype"]),
                     "kv_rope": np.asarray(snapshot.get("kv_rope", "roped"))}
        for name in ("k", "v", "k_scale", "v_scale"):
            if name not in snapshot:
                continue
            host = np.asarray(snapshot[name])
            if host.dtype.name == "bfloat16":
                out[name] = host.view(np.uint16)
                out[f"{name}_bf16"] = _ONE
            else:
                out[name] = host
        return out

    def export_prefix(self, slot: int, n: Optional[int] = None) -> dict:
        """snapshot_prefix + pack_prefix in one (synchronous) call."""
        return self.pack_prefix(self.snapshot_prefix(slot, n))

    def load_prefix(self, slot: int, arrays: dict, n: int) -> bool:
        """Write exported KV rows into a slot and set its frontier to ``n``
        (admit() then reuses them via the resident/resume path). Returns
        False on any mismatch (dtype, shape, context) — callers fall back
        to a full prefill."""
        if str(arrays.get("kv_dtype")) != str(self.kv_dtype):
            return False
        want_rope = "raw" if self.ga_n > 1 else "roped"
        if str(arrays.get("kv_rope", "roped")) != want_rope:
            return False
        if n > self.max_ctx - 1:
            return False

        def unpack(name):
            host = arrays[name]
            if f"{name}_bf16" in arrays:
                import ml_dtypes

                host = host.view(ml_dtypes.bfloat16)
            return host

        k, v = unpack("k"), unpack("v")
        L, H, hd = self.cfg.num_layers, self.cfg.num_kv_heads, self.cfg.hd
        if str(self.kv_dtype) == "int4":
            hd //= 2  # int4 exports stay nibble-packed along head_dim
        if k.shape != (L, H, n, hd) or v.shape != (L, H, n, hd):
            return False
        if self.paged:
            return self._load_prefix_paged(slot, arrays, n, k, v)
        kv = self.kv
        new = {
            "k": kv.k.at[:, slot, :, :n].set(jnp.asarray(k, kv.k.dtype)),
            "v": kv.v.at[:, slot, :, :n].set(jnp.asarray(v, kv.v.dtype)),
        }
        if kv.quantized:
            if "k_scale" not in arrays or "v_scale" not in arrays:
                return False
            new["k_scale"] = kv.k_scale.at[:, slot, :, :n].set(
                jnp.asarray(arrays["k_scale"], jnp.float32))
            new["v_scale"] = kv.v_scale.at[:, slot, :, :n].set(
                jnp.asarray(arrays["v_scale"], jnp.float32))
        self.kv = KVCache(**new)
        self.state = dataclasses.replace(
            self.state,
            positions=self.state.positions.at[slot].set(n),
            active=self.state.active.at[slot].set(False),
        )
        self._active_slots.discard(slot)
        return True

    def _load_prefix_paged(self, slot: int, arrays: dict, n: int,
                           k: np.ndarray, v: np.ndarray) -> bool:
        """Paged load_prefix tail: scatter the exported contiguous rows
        into freshly allocated blocks and mark them slot-resident
        (``_loaded_rows``) so begin_admit can resume past them."""
        kv = self.kv
        if kv.quantized and ("k_scale" not in arrays
                             or "v_scale" not in arrays):
            return False
        if slot in self.allocator.tables:
            self.allocator.release(slot)
        self._loaded_rows.pop(slot, None)
        if self.allocator.allocate(slot, n) is None:
            return False
        bt = self.block_tokens
        table = np.asarray(self.allocator.tables[slot], np.int64)
        pos = np.arange(n)
        blk = jnp.asarray(table[pos // bt], jnp.int32)
        off = jnp.asarray(pos % bt, jnp.int32)
        # advanced indices (blk, off) around the head slice broadcast to
        # the FRONT: the set value is row-major [n, L, H, ...]
        new = {
            "k": kv.k.at[:, blk, :, off].set(
                jnp.asarray(k, kv.k.dtype).transpose(2, 0, 1, 3)),
            "v": kv.v.at[:, blk, :, off].set(
                jnp.asarray(v, kv.v.dtype).transpose(2, 0, 1, 3)),
        }
        if kv.quantized:
            new["k_scale"] = kv.k_scale.at[:, blk, :, off].set(
                jnp.asarray(arrays["k_scale"],
                            jnp.float32).transpose(2, 0, 1))
            new["v_scale"] = kv.v_scale.at[:, blk, :, off].set(
                jnp.asarray(arrays["v_scale"],
                            jnp.float32).transpose(2, 0, 1))
        self.kv = kvc.PagedKVCache(**new)
        self._install_table_row(slot)
        self._loaded_rows[slot] = n
        self.state = dataclasses.replace(
            self.state,
            positions=self.state.positions.at[slot].set(n),
            active=self.state.active.at[slot].set(False),
        )
        self._active_slots.discard(slot)
        return True


class PagedAdmission:
    """One in-flight chunked paged admission (ModelRunner.begin_admit).

    The scheduler drives ``step_chunk()`` from its engine loop,
    interleaving chunk dispatches with decode dispatches; direct callers
    (bench, tests) just loop it. Only the FINAL chunk samples — it
    installs the slot's device block-table row, publishes prompt blocks
    to the prefix pool, arms the slot, and returns the first token."""

    def __init__(self, runner: ModelRunner, slot: int, prompt: list[int],
                 start: int, mm_embeds=None, mm_positions=None,
                 sp: bool = False):
        self.runner = runner
        self.slot = slot
        self.prompt = prompt
        self.pos = start                     # next position to prefill
        self.prefix_reused = start           # shared/loaded rows (telemetry)
        self.path = runner.last_prefill_path
        self.mm = mm_embeds is not None and len(mm_embeds) > 0
        self.mm_embeds = mm_embeds
        self.mm_positions = mm_positions
        self.sp = sp                         # ring-attention one-shot path
        self.first_token: Optional[int] = None
        self.done = False
        # dispatch-anatomy scratch for the last step_chunk() call: enqueue
        # span vs the final chunk's first-token fetch (obs.anatomy)
        self.last_launch_ms = 0.0
        self.last_sync_ms = 0.0

    @property
    def chunks_remaining(self) -> int:
        if self.done:
            return 0
        if self.mm or self.sp:
            return 1
        return max(1, -(-(len(self.prompt) - self.pos)
                        // self.runner.prefill_chunk))

    def _counts_row(self) -> np.ndarray:
        return _prompt_counts_row(self.runner.cfg.vocab_size, self.prompt)

    def step_chunk(self) -> Optional[int]:
        """Dispatch the next prefill chunk; returns the first sampled
        token once the admission is complete, else None."""
        assert not self.done
        r = self.runner
        slot = self.slot
        n = len(self.prompt)
        t0 = time.perf_counter()
        table_row = jnp.asarray(r.allocator.table_row(slot))
        if self.sp:
            # ring attention over the 'seq' mesh axis, scattered straight
            # into the reserved blocks — the whole prompt in one dispatch
            bucket = r.bucket_for(n)
            padded = np.zeros(bucket, np.int32)
            padded[:n] = self.prompt
            r.kv, r.state, tok = r._prefill_paged_sp(
                r.params, r.kv, r.state, jnp.asarray(padded), jnp.int32(n),
                table_row, jnp.int32(slot),
                jnp.asarray(self._counts_row()), bucket=bucket,
            )
            self.pos = n
            last = True
        elif self.mm:
            bucket = r.bucket_for(n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = self.prompt
            r.kv, r.state, tok = r._prefill_paged_mm(
                r.params, r.kv, r.state, jnp.asarray(padded), jnp.int32(n),
                table_row, jnp.int32(slot),
                jnp.asarray(self.mm_embeds, jnp.float32),
                jnp.asarray(self.mm_positions, jnp.int32),
                jnp.asarray(self._counts_row()), bucket=bucket,
            )
            self.pos = n
            last = True
        else:
            rem = n - self.pos
            take = min(rem, r.prefill_chunk)
            last = take == rem
            bucket = r.bucket_for(take)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :take] = self.prompt[self.pos:self.pos + take]
            crow = (jnp.asarray(self._counts_row()) if last
                    else r._zero_counts)  # sample=False ignores counts
            r.kv, r.state, tok = r._prefill_paged(
                r.params, r.kv, r.state, jnp.asarray(padded),
                jnp.int32(take), jnp.int32(self.pos), table_row,
                jnp.int32(slot), crow, bucket=bucket,
                sample=last,
            )
            self.pos += take
        if not last:
            # pure async enqueue — no sync on intermediate chunks
            self.last_launch_ms = (time.perf_counter() - t0) * 1e3
            self.last_sync_ms = 0.0
            return None
        self.done = True
        r._finish_paged_admit(slot, self.prompt, mm=self.mm)
        # the admit-time prefill/decode handoff sync, same as admit()
        t1 = time.perf_counter()
        with r.watchdog.guard("device"):
            self.first_token = int(tok)  # jaxlint: disable=host-sync-in-hot-path
        self.last_launch_ms = (t1 - t0) * 1e3
        self.last_sync_ms = (time.perf_counter() - t1) * 1e3
        return self.first_token

    def abort(self) -> None:
        """Abandon a part-way admission (client cancelled while chunks
        were queued): frees the blocks and leaves the slot inactive."""
        self.done = True
        self.runner.release(self.slot)


_ONE = np.asarray(1)
