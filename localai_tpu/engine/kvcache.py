"""Slot-resident KV cache in HBM.

Replaces llama.cpp's per-slot KV management (kv_cache_clear / cache_tokens /
n_ctx-per-slot partitioning, /root/reference/backend/cpp/llama/
grpc-server.cpp:176,906,1546-1990) with a TPU-native layout: one statically
shaped tensor pair per model, stacked over layers so the layer loop can
``lax.scan`` it, sliced per slot by masking — never by ragged mutation.

Layout: k,v each [num_layers, num_slots, num_kv_heads, max_ctx, head_dim].
Heads lead the context dim so the last two axes are (context, head_dim) —
the (sublane, lane) tiling Mosaic requires for the flash kernels' per-head
HBM→VMEM DMA slices (ops.attention), and a contiguous stream per head.
All updates are functional; jit donation makes them in-place in HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from localai_tpu.models.llama import LlamaConfig
from localai_tpu.models.quant import (
    quantize_lastdim as _quant_chunk,
    quantize_lastdim4 as _quant_chunk4,
    unpack_int4_lastdim as _unpack4,
)
from localai_tpu.ops.attention import gather_block_scales, gather_blocks


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """k, v: [L, S, Hkv, C, hd]. When the cache dtype is int8, k/v hold
    symmetric per-(slot, head, position) quantized values and
    k_scale/v_scale hold the f32 scales [L, S, Hkv, C] — honest scaled
    int8, not a raw dtype cast (the scale adds hd⁻¹·4 bytes/elem ≈ 1.5%
    overhead against a 2× KV memory saving)."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_ctx(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def stacked(self):
        """The pytree scanned alongside layers in models.llama.forward."""
        if self.k_scale is None:
            return (self.k, self.v)
        return (self.k, self.v, self.k_scale, self.v_scale)

    @staticmethod
    def from_stacked(t) -> "KVCache":
        return KVCache(*t)


def init_cache(
    cfg: LlamaConfig,
    num_slots: int,
    max_ctx: int,
    dtype: str = "bfloat16",
    sharding: Optional[jax.sharding.Sharding] = None,
) -> KVCache:
    shape = (cfg.num_layers, num_slots, cfg.num_kv_heads, max_ctx, cfg.hd)
    dt = jnp.dtype(dtype)

    def zeros(shp, d, shd):
        if shd is not None:
            # one-shot jit is the idiom for allocating directly into a
            # sharded layout (device_put of a host zeros array would
            # materialize the full cache on one device first); init-time
            # only, so the throwaway compile cache is fine
            return jax.jit(  # jaxlint: disable=jit-in-loop
                lambda: jnp.zeros(shp, d), out_shardings=shd
            )()
        return jnp.zeros(shp, d)

    scale_sharding = None
    if dt == jnp.int8 and sharding is not None:
        # scales drop the head_dim axis; reuse the kv spec minus its last entry
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = sharding.spec
        scale_sharding = NamedSharding(sharding.mesh, P(*tuple(spec)[:4]))
    if dt == jnp.int8:
        return KVCache(
            k=zeros(shape, dt, sharding),
            v=zeros(shape, dt, sharding),
            k_scale=zeros(shape[:4], jnp.float32, scale_sharding),
            v_scale=zeros(shape[:4], jnp.float32, scale_sharding),
        )
    return KVCache(k=zeros(shape, dt, sharding), v=zeros(shape, dt, sharding))




# ---------------------------------------------------------------------------
# paged layout (vLLM-style block pool; host bookkeeping in engine.paged)
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """k, v: [L, N, Hkv, bt, hd] — one pool of N physical blocks of bt
    tokens each, shared by all slots through per-slot block tables
    ([S, max_blocks] i32, engine.paged.BlockAllocator). Block 0 is the
    trash block (garbage-write target for inactive slots). int8 caches
    carry f32 scales [L, N, Hkv, bt], same scaled-int8 scheme as KVCache.
    int4 pools store nibble-packed int8 with last dim hd/2 (halves layout,
    models.quant.quantize_lastdim4) and the SAME scale shape — the packed
    last dim is how every consumer detects int4, so the pool stays
    self-describing through the stacked pytree."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_tokens(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def stacked(self):
        if self.k_scale is None:
            return (self.k, self.v)
        return (self.k, self.v, self.k_scale, self.v_scale)

    @staticmethod
    def from_stacked(t) -> "PagedKVCache":
        return PagedKVCache(*t)


def init_paged_cache(
    cfg: LlamaConfig,
    num_blocks: int,
    block_tokens: int,
    dtype: str = "bfloat16",
    sharding: Optional[jax.sharding.Sharding] = None,
) -> PagedKVCache:
    int4 = str(dtype) == "int4"
    if int4 and cfg.hd % 2:
        raise ValueError(f"int4 KV needs an even head_dim, got {cfg.hd}")
    # int4 pools store nibble-packed int8 along head_dim (hd/2 bytes/row)
    hd = cfg.hd // 2 if int4 else cfg.hd
    shape = (cfg.num_layers, num_blocks, cfg.num_kv_heads, block_tokens, hd)
    dt = jnp.dtype("int8") if int4 else jnp.dtype(dtype)
    quantized = int4 or dt == jnp.int8

    def zeros(shp, d, shd):
        if shd is not None:
            # allocate straight into the sharded layout (same idiom as
            # init_cache: a host zeros array would materialize the whole
            # pool on one device first); init-time only
            return jax.jit(  # jaxlint: disable=jit-in-loop
                lambda: jnp.zeros(shp, d), out_shardings=shd
            )()
        return jnp.zeros(shp, d)

    scale_sharding = None
    if quantized and sharding is not None:
        # scale pool drops the head_dim axis; reuse the pool spec minus it
        from jax.sharding import NamedSharding, PartitionSpec as P

        scale_sharding = NamedSharding(
            sharding.mesh, P(*tuple(sharding.spec)[:4]))
    if quantized:
        return PagedKVCache(
            k=zeros(shape, dt, sharding),
            v=zeros(shape, dt, sharding),
            k_scale=zeros(shape[:4], jnp.float32, scale_sharding),
            v_scale=zeros(shape[:4], jnp.float32, scale_sharding),
        )
    return PagedKVCache(k=zeros(shape, dt, sharding),
                        v=zeros(shape, dt, sharding))


def _pool_quant(layer_kv, k_new):
    """The quantizer matching a paged pool's storage: int4 when the pool's
    last dim is the packed hd/2 (self-describing layout), else int8.
    ``k_new`` carries the full head_dim."""
    int4 = layer_kv[0].shape[-1] * 2 == k_new.shape[-1]
    return (_quant_chunk4 if int4 else _quant_chunk), int4


def _gather_dequant(cache, scales, tables, dt, int4: bool):
    """Gather + dequantize a quantized pool's logical context for the XLA
    attend: [S, H, MB*bt, hd] in ``dt`` (int4 pools unpack first)."""
    g = gather_blocks(cache, tables)
    if int4:
        g = _unpack4(g)
    return (g.astype(dt)
            * gather_block_scales(scales, tables)[..., None].astype(dt))


def paged_decode_write(tables: jax.Array, positions: jax.Array,
                       raw: bool = False):
    """KV write policy for batched single-token decode over a block pool.

    tables: [S, MB] i32 block tables, positions: [S]. Writes k/v_new
    [S, 1, H, hd] at pool[tables[s, pos//bt], :, pos%bt]. Released slots'
    table rows are all-zeros, so their (static-shape-mandated) garbage
    writes land in the trash block.

    ``raw=False`` exposes the gathered logical context [S, H, MB*bt, hd]
    for the XLA attend; ``raw=True`` passes the pool through untouched for
    the Pallas paged kernel (which walks the tables itself)."""

    def write(layer_kv, k_new, v_new):
        dt = k_new.dtype
        bt = layer_kv[0].shape[2]
        s = jnp.arange(tables.shape[0])
        blk = tables[s, positions // bt]          # [S]
        off = positions % bt
        if len(layer_kv) == 4:  # scaled int8/int4 pool
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            quant, int4 = _pool_quant(layer_kv, k_new)
            kq, ks = quant(k_new[:, 0])    # [S, H, hd or hd/2], [S, H]
            vq, vs = quant(v_new[:, 0])
            new_k = k_layer.at[blk, :, off].set(kq)
            new_v = v_layer.at[blk, :, off].set(vq)
            new_ks = ks_layer.at[blk, :, off].set(ks)
            new_vs = vs_layer.at[blk, :, off].set(vs)
            new_kv = (new_k, new_v, new_ks, new_vs)
            if raw:
                return new_kv, (new_k, new_ks), (new_v, new_vs)
            keys = _gather_dequant(new_k, new_ks, tables, dt, int4)
            values = _gather_dequant(new_v, new_vs, tables, dt, int4)
            return new_kv, keys, values
        k_layer, v_layer = layer_kv               # [N, H, bt, hd]
        kdt = k_layer.dtype
        new_k = k_layer.at[blk, :, off].set(k_new[:, 0].astype(kdt))
        new_v = v_layer.at[blk, :, off].set(v_new[:, 0].astype(kdt))
        if raw:
            return (new_k, new_v), new_k, new_v
        return ((new_k, new_v), gather_blocks(new_k, tables).astype(dt),
                gather_blocks(new_v, tables).astype(dt))

    return write


def paged_prefill_write(table_row: jax.Array, offset: jax.Array,
                        length: jax.Array):
    """KV write policy for one chunked-prefill dispatch into a block table.

    table_row: [MB] i32, offset: absolute start position of this chunk,
    length: real (unpadded) tokens in the chunk. Token t of the chunk
    lands at pool[table_row[(offset+t)//bt], :, (offset+t)%bt]; padding
    rows (t >= length) are redirected to the trash block so a padded
    bucket can never clobber the sequence's own reserved blocks. Exposes
    the gathered FULL logical context [1, H, MB*bt, hd] so chunk tokens
    attend over the kept prefix + earlier chunks (resume-style)."""

    def write(layer_kv, k_new, v_new):  # k_new [1, T, H, hd]
        dt = k_new.dtype
        bt = layer_kv[0].shape[2]
        MB = table_row.shape[0]
        T = k_new.shape[1]
        t = jnp.arange(T)
        pos = offset + t
        valid = t < length
        blk = jnp.where(valid, table_row[jnp.minimum(pos // bt, MB - 1)], 0)
        off = pos % bt
        row = table_row[None]                     # [1, MB]
        if len(layer_kv) == 4:  # scaled int8/int4 pool
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            quant, int4 = _pool_quant(layer_kv, k_new)
            kq, ks = quant(k_new[0])       # [T, H, hd or hd/2], [T, H]
            vq, vs = quant(v_new[0])
            new_k = k_layer.at[blk, :, off].set(kq)
            new_v = v_layer.at[blk, :, off].set(vq)
            new_ks = ks_layer.at[blk, :, off].set(ks)
            new_vs = vs_layer.at[blk, :, off].set(vs)
            keys = _gather_dequant(new_k, new_ks, row, dt, int4)
            values = _gather_dequant(new_v, new_vs, row, dt, int4)
            return (new_k, new_v, new_ks, new_vs), keys, values
        k_layer, v_layer = layer_kv
        kdt = k_layer.dtype
        new_k = k_layer.at[blk, :, off].set(k_new[0].astype(kdt))
        new_v = v_layer.at[blk, :, off].set(v_new[0].astype(kdt))
        return ((new_k, new_v), gather_blocks(new_k, row).astype(dt),
                gather_blocks(new_v, row).astype(dt))

    return write


def verify_write(positions: jax.Array):
    """KV write policy for the batched speculative verify forward: writes
    the window chunk [S, T, H, hd] at cache[s, :, positions[s] + t] and
    exposes the full per-layer cache as keys ([S, H, C, hd]) —
    ``decode_write`` generalized to T tokens per slot. Rejected positions
    leave garbage KV *above* each slot's accepted frontier, which the
    decode masks never read and later writes overwrite — rollback is free
    by construction (same invariant as the bucketed prefill paths)."""

    def write(layer_kv, k_new, v_new):
        dt = k_new.dtype
        S, T = k_new.shape[0], k_new.shape[1]
        s = jnp.arange(S)[:, None]
        pmat = positions[:, None] + jnp.arange(T)[None, :]  # [S, T]
        if len(layer_kv) == 4:  # scaled int8 cache
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            kq, ks = _quant_chunk(k_new)  # [S, T, H, hd], [S, T, H]
            vq, vs = _quant_chunk(v_new)
            new_k = k_layer.at[s, :, pmat].set(kq)
            new_v = v_layer.at[s, :, pmat].set(vq)
            new_ks = ks_layer.at[s, :, pmat].set(ks)
            new_vs = vs_layer.at[s, :, pmat].set(vs)
            keys = new_k.astype(dt) * new_ks[..., None].astype(dt)
            values = new_v.astype(dt) * new_vs[..., None].astype(dt)
            return (new_k, new_v, new_ks, new_vs), keys, values
        k_layer, v_layer = layer_kv
        kdt = k_layer.dtype
        new_k = k_layer.at[s, :, pmat].set(k_new.astype(kdt))
        new_v = v_layer.at[s, :, pmat].set(v_new.astype(kdt))
        return (new_k, new_v), new_k.astype(dt), new_v.astype(dt)

    return write


def paged_verify_write(tables: jax.Array, positions: jax.Array,
                       ctx_limit: int):
    """KV write policy for the batched speculative verify forward over a
    block pool — ``paged_decode_write`` generalized to T tokens per slot.

    Window token t of slot s lands at
    ``pool[tables[s, (positions[s]+t)//bt], :, (positions[s]+t)%bt]``.
    Rows at or past ``ctx_limit`` (the runner's max_ctx) redirect to the
    trash block: near the context edge a window row beyond the last real
    position must never wrap onto the slot's own earlier rows via the
    clamped block index. Inactive/mid-prefill slots' device table rows
    are all-zeros, so their static-shape writes land in trash exactly
    like decode. Exposes the gathered logical context [S, H, MB*bt, hd]
    so window tokens attend over the prefix + the window so far.

    Rollback is a per-slot position rollback only: the rejected tail's
    rows (values AND int8 scale rows — they ride the same scatter) stay
    as garbage inside the slot's reserved speculation blocks and are
    overwritten by the next window/decode write before anything can
    attend to them."""

    def write(layer_kv, k_new, v_new):  # k_new [S, T, H, hd]
        dt = k_new.dtype
        bt = layer_kv[0].shape[2]
        MB = tables.shape[1]
        S, T = k_new.shape[0], k_new.shape[1]
        s = jnp.arange(S)[:, None]
        pmat = positions[:, None] + jnp.arange(T)[None, :]   # [S, T]
        safe = pmat < ctx_limit
        blk = jnp.where(
            safe, tables[s, jnp.minimum(pmat // bt, MB - 1)], 0)
        off = pmat % bt
        if len(layer_kv) == 4:  # scaled int8/int4 pool
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            quant, int4 = _pool_quant(layer_kv, k_new)
            kq, ks = quant(k_new)       # [S, T, H, hd or hd/2], [S, T, H]
            vq, vs = quant(v_new)
            new_k = k_layer.at[blk, :, off].set(kq)
            new_v = v_layer.at[blk, :, off].set(vq)
            new_ks = ks_layer.at[blk, :, off].set(ks)
            new_vs = vs_layer.at[blk, :, off].set(vs)
            keys = _gather_dequant(new_k, new_ks, tables, dt, int4)
            values = _gather_dequant(new_v, new_vs, tables, dt, int4)
            return (new_k, new_v, new_ks, new_vs), keys, values
        k_layer, v_layer = layer_kv               # [N, H, bt, hd]
        kdt = k_layer.dtype
        new_k = k_layer.at[blk, :, off].set(k_new.astype(kdt))
        new_v = v_layer.at[blk, :, off].set(v_new.astype(kdt))
        return ((new_k, new_v), gather_blocks(new_k, tables).astype(dt),
                gather_blocks(new_v, tables).astype(dt))

    return write


def verify_mask(cfg: LlamaConfig, positions: jax.Array, T: int,
                max_ctx: int) -> jax.Array:
    """[S, T, C] mask for the speculative verify forward: window token t
    (absolute position positions[s]+t) attends causally over the slot's
    prefix + the window so far."""
    c = jnp.arange(max_ctx)[None, None, :]
    pos = positions[:, None, None] + jnp.arange(T)[None, :, None]
    m = c <= pos
    if cfg.sliding_window:
        m &= c > pos - cfg.sliding_window
    return m


def decode_write(positions: jax.Array, raw: bool = False):
    """KV write policy for batched single-token decode.

    positions: [S] — write location per slot. Returns a ``kv_write`` closure
    for models.llama.forward: writes k/v_new [S, 1, H, hd] at
    cache[s, :, positions[s]] and exposes the full per-layer cache as keys
    ([S, H, C, hd]).

    ``raw=True`` (int8 cache + Pallas decode kernel): keys/values are passed
    through as ``(int8 cache, f32 scales)`` tuples — dequantization happens
    inside the flash kernel, so no [S, H, C, hd] bf16 copy is ever built."""

    def write(layer_kv, k_new, v_new):
        dt = k_new.dtype
        s = jnp.arange(layer_kv[0].shape[0])
        if len(layer_kv) == 4:  # scaled int8 cache
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            kq, ks = _quant_chunk(k_new[:, 0])  # [S, H, hd], [S, H]
            vq, vs = _quant_chunk(v_new[:, 0])
            # advanced indices (s, positions) separated by the head slice →
            # result dims [S, H, ...]
            new_k = k_layer.at[s, :, positions].set(kq)
            new_v = v_layer.at[s, :, positions].set(vq)
            new_ks = ks_layer.at[s, :, positions].set(ks)
            new_vs = vs_layer.at[s, :, positions].set(vs)
            new_kv = (new_k, new_v, new_ks, new_vs)
            if raw:
                return new_kv, (new_k, new_ks), (new_v, new_vs)
            keys = new_k.astype(dt) * new_ks[..., None].astype(dt)
            values = new_v.astype(dt) * new_vs[..., None].astype(dt)
            return new_kv, keys, values
        k_layer, v_layer = layer_kv  # [S, H, C, hd]
        kdt = k_layer.dtype
        new_k = k_layer.at[s, :, positions].set(k_new[:, 0].astype(kdt))
        new_v = v_layer.at[s, :, positions].set(v_new[:, 0].astype(kdt))
        return (new_k, new_v), new_k.astype(dt), new_v.astype(dt)

    return write


def prefill_write(slot: jax.Array, offset: jax.Array):
    """KV write policy for single-sequence prefill into one slot.

    Writes the whole chunk [1, T, H, hd] at cache[slot, :, offset:offset+T]
    and attends over the chunk itself (fresh context ⇒ T² attention, not
    T·C). Keys are exposed head-major: [1, H, T, hd]."""

    def write(layer_kv, k_new, v_new):
        k_hm = k_new.transpose(0, 2, 1, 3)  # [1, H, T, hd]
        v_hm = v_new.transpose(0, 2, 1, 3)
        zero = jnp.zeros((), jnp.int32)
        idx = (slot, zero, offset, zero)
        if len(layer_kv) == 4:  # scaled int8 cache
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            kq, ks = _quant_chunk(k_hm)  # [1, H, T, hd], [1, H, T]
            vq, vs = _quant_chunk(v_hm)
            new_k = lax.dynamic_update_slice(k_layer, kq, idx)
            new_v = lax.dynamic_update_slice(v_layer, vq, idx)
            new_ks = lax.dynamic_update_slice(ks_layer, ks, (slot, zero, offset))
            new_vs = lax.dynamic_update_slice(vs_layer, vs, (slot, zero, offset))
            # fresh-context prefill attends over the chunk itself, so the
            # exposed keys/values are the unquantized chunk — quantization
            # error only enters on later decode reads
            return (new_k, new_v, new_ks, new_vs), k_hm, v_hm
        k_layer, v_layer = layer_kv  # [S, H, C, hd]
        kdt = k_layer.dtype
        new_k = lax.dynamic_update_slice(k_layer, k_hm.astype(kdt), idx)
        new_v = lax.dynamic_update_slice(v_layer, v_hm.astype(kdt), idx)
        return (new_k, new_v), k_hm, v_hm

    return write


def resume_write(slot: jax.Array, offset: jax.Array):
    """KV write policy for suffix prefill into a slot that keeps a reused
    prefix (KV prefix-cache reuse; parity: llama.cpp ``common_part`` +
    slot cache_tokens, /root/reference/backend/cpp/llama/grpc-server.cpp:
    67-74,1651-1668).

    Writes the chunk [1, T, H, hd] at cache[slot, :, offset:offset+T] like
    prefill_write, but exposes the slot's FULL cache row as keys
    ([1, H, C, hd]) so the new tokens attend over the kept prefix."""

    def write(layer_kv, k_new, v_new):
        k_hm = k_new.transpose(0, 2, 1, 3)  # [1, H, T, hd]
        v_hm = v_new.transpose(0, 2, 1, 3)
        zero = jnp.zeros((), jnp.int32)
        idx = (slot, zero, offset, zero)
        dt = k_new.dtype

        def row(cache, scales=None):
            r = lax.dynamic_index_in_dim(cache, slot, 0, keepdims=True)
            if scales is None:
                return r.astype(dt)
            s = lax.dynamic_index_in_dim(scales, slot, 0, keepdims=True)
            return r.astype(dt) * s[..., None].astype(dt)

        if len(layer_kv) == 4:  # scaled int8 cache
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            kq, ks = _quant_chunk(k_hm)
            vq, vs = _quant_chunk(v_hm)
            new_k = lax.dynamic_update_slice(k_layer, kq, idx)
            new_v = lax.dynamic_update_slice(v_layer, vq, idx)
            new_ks = lax.dynamic_update_slice(ks_layer, ks, (slot, zero, offset))
            new_vs = lax.dynamic_update_slice(vs_layer, vs, (slot, zero, offset))
            return ((new_k, new_v, new_ks, new_vs),
                    row(new_k, new_ks), row(new_v, new_vs))
        k_layer, v_layer = layer_kv
        kdt = k_layer.dtype
        new_k = lax.dynamic_update_slice(k_layer, k_hm.astype(kdt), idx)
        new_v = lax.dynamic_update_slice(v_layer, v_hm.astype(kdt), idx)
        return (new_k, new_v), row(new_k), row(new_v)

    return write


def resume_mask(cfg: LlamaConfig, seq_len: int,
                offset: jax.Array, max_ctx: int) -> jax.Array:
    """[1, T, C] mask for suffix prefill: chunk token t (absolute position
    offset+t) attends causally over the kept prefix + the chunk. Padding
    rows (t ≥ tail length) write garbage KV beyond the sequence, exactly
    like prefill_mask — those positions are overwritten by later decode
    steps before anything can attend to them."""
    t = jnp.arange(seq_len)[None, :, None]
    c = jnp.arange(max_ctx)[None, None, :]
    pos = offset + t
    m = c <= pos
    if cfg.sliding_window:
        m &= c > pos - cfg.sliding_window
    return m


def decode_mask(cfg: LlamaConfig, positions: jax.Array, max_ctx: int) -> jax.Array:
    """[S, 1, C] attention mask for decode: attend to all written positions
    (≤ current), optionally sliding-window limited (Mistral-style)."""
    idx = jnp.arange(max_ctx)[None, None, :]
    pos = positions[:, None, None]
    m = idx <= pos
    if cfg.sliding_window:
        m &= idx > pos - cfg.sliding_window
    return m


def prefill_mask(cfg: LlamaConfig, seq_len: int, length: jax.Array) -> jax.Array:
    """[1, T, T] causal mask limited to the real (unpadded) length."""
    t = jnp.arange(seq_len)
    m = (t[None, :, None] >= t[None, None, :]) & (t[None, None, :] < length)
    if cfg.sliding_window:
        m &= t[None, None, :] > t[None, :, None] - cfg.sliding_window
    return m
