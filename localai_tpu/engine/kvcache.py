"""Slot-resident KV cache in HBM.

Replaces llama.cpp's per-slot KV management (kv_cache_clear / cache_tokens /
n_ctx-per-slot partitioning, /root/reference/backend/cpp/llama/
grpc-server.cpp:176,906,1546-1990) with a TPU-native layout: one statically
shaped tensor pair per model, stacked over layers so the layer loop can
``lax.scan`` it, sliced per slot by masking — never by ragged mutation.

Layout: k,v each [num_layers, num_slots, num_kv_heads, max_ctx, head_dim].
Heads lead the context dim so the last two axes are (context, head_dim) —
the (sublane, lane) tiling Mosaic requires for the flash kernels' per-head
HBM→VMEM DMA slices (ops.attention), and a contiguous stream per head.
All updates are functional; jit donation makes them in-place in HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from localai_tpu.models.llama import LlamaConfig


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jax.Array
    v: jax.Array

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_ctx(self) -> int:
        return self.k.shape[3]


def init_cache(
    cfg: LlamaConfig,
    num_slots: int,
    max_ctx: int,
    dtype: str = "bfloat16",
    sharding: Optional[jax.sharding.Sharding] = None,
) -> KVCache:
    shape = (cfg.num_layers, num_slots, cfg.num_kv_heads, max_ctx, cfg.hd)
    dt = jnp.dtype(dtype)
    if sharding is not None:
        zeros = jax.jit(
            lambda: jnp.zeros(shape, dt), out_shardings=sharding
        )()
        return KVCache(k=zeros, v=jax.jit(
            lambda: jnp.zeros(shape, dt), out_shardings=sharding
        )())
    return KVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))


def decode_write(positions: jax.Array):
    """KV write policy for batched single-token decode.

    positions: [S] — write location per slot. Returns a ``kv_write`` closure
    for models.llama.forward: writes k/v_new [S, 1, H, hd] at
    cache[s, :, positions[s]] and exposes the full per-layer cache as keys
    ([S, H, C, hd])."""

    def write(layer_kv, k_new, v_new):
        k_layer, v_layer = layer_kv  # [S, H, C, hd]
        s = jnp.arange(k_layer.shape[0])
        kdt = k_layer.dtype
        # advanced indices (s, positions) separated by the head slice →
        # result dims [S, H, hd], matching k_new[:, 0]
        new_k = k_layer.at[s, :, positions].set(k_new[:, 0].astype(kdt))
        new_v = v_layer.at[s, :, positions].set(v_new[:, 0].astype(kdt))
        return (new_k, new_v), new_k.astype(k_new.dtype), new_v.astype(v_new.dtype)

    return write


def prefill_write(slot: jax.Array, offset: jax.Array):
    """KV write policy for single-sequence prefill into one slot.

    Writes the whole chunk [1, T, H, hd] at cache[slot, :, offset:offset+T]
    and attends over the chunk itself (fresh context ⇒ T² attention, not
    T·C). Keys are exposed head-major: [1, H, T, hd]."""

    def write(layer_kv, k_new, v_new):
        k_layer, v_layer = layer_kv  # [S, H, C, hd]
        kdt = k_layer.dtype
        k_hm = k_new.transpose(0, 2, 1, 3)  # [1, H, T, hd]
        v_hm = v_new.transpose(0, 2, 1, 3)
        zero = jnp.zeros((), jnp.int32)
        idx = (slot, zero, offset, zero)
        new_k = lax.dynamic_update_slice(k_layer, k_hm.astype(kdt), idx)
        new_v = lax.dynamic_update_slice(v_layer, v_hm.astype(kdt), idx)
        return (new_k, new_v), k_hm, v_hm

    return write


def decode_mask(cfg: LlamaConfig, positions: jax.Array, max_ctx: int) -> jax.Array:
    """[S, 1, C] attention mask for decode: attend to all written positions
    (≤ current), optionally sliding-window limited (Mistral-style)."""
    idx = jnp.arange(max_ctx)[None, None, :]
    pos = positions[:, None, None]
    m = idx <= pos
    if cfg.sliding_window:
        m &= idx > pos - cfg.sliding_window
    return m


def prefill_mask(cfg: LlamaConfig, seq_len: int, length: jax.Array) -> jax.Array:
    """[1, T, T] causal mask limited to the real (unpadded) length."""
    t = jnp.arange(seq_len)
    m = (t[None, :, None] >= t[None, None, :]) & (t[None, None, :] < length)
    if cfg.sliding_window:
        m &= t[None, None, :] > t[None, :, None] - cfg.sliding_window
    return m
