"""Token→text streaming: incremental detokenization and stop-sequence logic.

The reference streams byte chunks from the C++ engine and reassembles UTF-8
runes on the Go side (/root/reference/core/backend/llm.go:122-138); stop
sequences are checked in the C++ slot loop (grpc-server.cpp, slot params
antiprompt). Here both live on the host next to the scheduler: tokens come
off the device as ids, text deltas are produced incrementally (never
re-decoding the whole sequence), and stop strings are enforced with holdback
so a stop sequence split across token boundaries is never emitted.
"""

from __future__ import annotations

from typing import Optional, Sequence


class IncrementalDetokenizer:
    """Produces text deltas from a growing token-id sequence.

    Uses the prefix-window algorithm (decode a sliding window, emit the
    difference) so BPE merge artifacts and multi-token UTF-8 characters are
    handled: a delta is only emitted once it no longer ends in a replacement
    character from an incomplete byte sequence.
    """

    def __init__(self, decode_fn, window: int = 8):
        self._decode = decode_fn
        self._ids: list[int] = []
        self._prefix_offset = 0
        self._read_offset = 0
        self._window = window

    @property
    def ids(self) -> list[int]:
        return self._ids

    def push(self, token_id: int) -> str:
        """Add one token; return the new text delta ('' if incomplete)."""
        self._ids.append(token_id)
        prefix = self._decode(self._ids[self._prefix_offset:self._read_offset])
        full = self._decode(self._ids[self._prefix_offset:])
        if full.endswith("�"):
            # incomplete UTF-8 sequence — wait for more tokens
            return ""
        if len(full) <= len(prefix) or not full.startswith(prefix):
            # tokenizer rewrote the window (BPE merge); emit nothing yet
            if len(self._ids) - self._prefix_offset > 4 * self._window:
                # safety: advance the window to bound re-decode cost
                self._prefix_offset = max(0, len(self._ids) - self._window)
                self._read_offset = len(self._ids)
            return ""
        delta = full[len(prefix):]
        self._read_offset = len(self._ids)
        if self._read_offset - self._prefix_offset > self._window:
            self._prefix_offset = self._read_offset - self._window
        return delta


class StopChecker:
    """Emits safe text, holding back any suffix that could begin a stop
    sequence; reports a hit with the stop text trimmed."""

    def __init__(self, stops: Sequence[str]):
        self._stops = [s for s in stops if s]
        self._holdback = max((len(s) for s in self._stops), default=1) - 1
        self._pending = ""
        self.stopped: Optional[str] = None

    def push(self, delta: str) -> str:
        """Feed a delta; return text that is safe to emit now."""
        if self.stopped is not None or not delta:
            return ""
        self._pending += delta
        for s in self._stops:
            idx = self._pending.find(s)
            if idx >= 0:
                self.stopped = s
                out, self._pending = self._pending[:idx], ""
                return out
        if not self._stops or self._holdback == 0:
            out, self._pending = self._pending, ""
            return out
        # hold back the longest suffix that is a prefix of any stop string
        keep = 0
        for k in range(min(self._holdback, len(self._pending)), 0, -1):
            tail = self._pending[-k:]
            if any(s.startswith(tail) for s in self._stops):
                keep = k
                break
        if keep:
            out, self._pending = self._pending[:-keep], self._pending[-keep:]
        else:
            out, self._pending = self._pending, ""
        return out

    def flush(self) -> str:
        """Return any held-back text at end of generation (no stop hit)."""
        out, self._pending = self._pending, ""
        return out if self.stopped is None else ""
