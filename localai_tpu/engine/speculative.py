"""Speculative decoding: draft-model propose, target-model verify.

Parity surface: the reference plumbs ``DraftModel``/``NDraft`` through its
config and proto (/root/reference/core/config/backend_config.go:143,
backend/backend.proto:210) into llama.cpp's speculative sampling. The TPU
redesign runs the whole window — draft scan, batched verify forward,
sequential accept/sample scan — as ONE compiled program per window:

  * the draft model decodes ``gamma+1`` greedy steps under ``lax.scan``
    (the +1 step feeds the last proposal so the draft KV has no hole when
    every token is accepted);
  * the target runs ONE ``gamma+1``-wide batched forward over all slots
    (positions offset per slot — a "verify" write policy scatters the chunk
    KV at each slot's frontier, exactly like decode but T tokens at once);
  * acceptance is a tiny ``lax.scan`` over the window positions running the
    REAL sampler chain (bias + penalties + top-k/p + per-slot PRNG) on the
    verify logits with counts updated sequentially — so emitted tokens are
    drawn from exactly the distribution non-speculative decode would use
    (naive-match acceptance: a draft token is accepted iff it equals the
    token the target itself sampled; on mismatch the target's sample is the
    correction). PRNG keys advance once per EMITTED token, preserving the
    seeded-stream contract.

KV rollback is free by construction: rejected positions hold garbage KV
*above* each slot's decode frontier (positions[s]), which the attention
masks never read and later writes overwrite — the same invariant the
bucketed prefill paths rely on (engine/kvcache.py).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.engine import kvcache as kvc
from localai_tpu.engine import sampling as smp
from localai_tpu.engine.kvcache import KVCache
from localai_tpu.engine.runner import DecodeState, ModelRunner
from localai_tpu.models import llama as mdl

log = logging.getLogger(__name__)

SKIP = -1  # sentinel in emitted rows: no token for this (step, slot)


def verify_write(positions: jax.Array):
    """KV write policy for the batched verify forward: writes the chunk
    [S, T, H, hd] at cache[s, :, positions[s] + t] and exposes the full
    per-layer cache as keys ([S, H, C, hd]) — decode_write generalized to
    T tokens per slot."""

    def write(layer_kv, k_new, v_new):
        dt = k_new.dtype
        S, T = k_new.shape[0], k_new.shape[1]
        s = jnp.arange(S)[:, None]
        pmat = positions[:, None] + jnp.arange(T)[None, :]  # [S, T]
        if len(layer_kv) == 4:  # scaled int8 cache
            k_layer, v_layer, ks_layer, vs_layer = layer_kv
            kq, ks = kvc._quant_chunk(k_new)  # [S, T, H, hd], [S, T, H]
            vq, vs = kvc._quant_chunk(v_new)
            new_k = k_layer.at[s, :, pmat].set(kq)
            new_v = v_layer.at[s, :, pmat].set(vq)
            new_ks = ks_layer.at[s, :, pmat].set(ks)
            new_vs = vs_layer.at[s, :, pmat].set(vs)
            keys = new_k.astype(dt) * new_ks[..., None].astype(dt)
            values = new_v.astype(dt) * new_vs[..., None].astype(dt)
            return (new_k, new_v, new_ks, new_vs), keys, values
        k_layer, v_layer = layer_kv
        kdt = k_layer.dtype
        new_k = k_layer.at[s, :, pmat].set(k_new.astype(kdt))
        new_v = v_layer.at[s, :, pmat].set(v_new.astype(kdt))
        return (new_k, new_v), new_k.astype(dt), new_v.astype(dt)

    return write


def verify_mask(cfg, positions: jax.Array, T: int, max_ctx: int) -> jax.Array:
    """[S, T, C] mask: window token t (absolute position positions[s]+t)
    attends causally over the slot's prefix + the window so far."""
    c = jnp.arange(max_ctx)[None, None, :]
    pos = positions[:, None, None] + jnp.arange(T)[None, :, None]
    m = c <= pos
    if cfg.sliding_window:
        m &= c > pos - cfg.sliding_window
    return m


class SpecDecoder:
    """Couples a target ModelRunner with a small draft model.

    The scheduler drives it exactly like multi-step decode, except each
    dispatch returns [gamma+1, S] token rows where SKIP (-1) marks
    positions past a slot's accepted window."""

    def __init__(self, target: ModelRunner, draft: ModelRunner,
                 gamma: int = 4):
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft.cfg.vocab_size} != target vocab "
                f"{target.cfg.vocab_size} — speculative decoding needs a "
                "shared tokenizer"
            )
        if draft.num_slots != target.num_slots:
            raise ValueError("draft and target must have equal slot counts")
        if getattr(target, "paged", False) or getattr(draft, "paged", False):
            raise ValueError(
                "speculative decoding requires contiguous KV caches "
                "(build the runners with paged=False)")
        self.target = target
        self.draft = draft
        self.gamma = int(gamma)
        self.num_slots = target.num_slots
        self.max_ctx = target.max_ctx
        self.cfg = target.cfg
        # accepted-token telemetry (window efficiency = emitted tokens per
        # ACTIVE slot-window, over the gamma+1 ceiling)
        self.total_emitted = 0
        self.total_windows = 0
        self.total_eligible = 0   # active slot-windows × (gamma+1)
        self.last_prefix_reused = 0
        from localai_tpu.obs import compile as obs_compile

        self._spec = obs_compile.watch(
            jax.jit(self._spec_fn, donate_argnums=(1, 2, 4, 5)),
            "spec_window",
        )

    # -- jitted program ---------------------------------------------------

    def _spec_fn(self, tparams, tkv: KVCache, tstate: DecodeState,
                 dparams, dkv: KVCache, dstate: DecodeState):
        gamma = self.gamma
        T = gamma + 1

        # 1) draft: gamma+1 greedy decode steps in a scan. The extra step
        # writes the last proposal's KV (no hole on full acceptance); its
        # sampled token is discarded.
        def draft_body(carry, _):
            kv, st = carry
            kv, st, tok = self.draft._decode_fn(dparams, kv, st)
            return (kv, st), tok

        (dkv, dstate), draft_toks = jax.lax.scan(
            draft_body, (dkv, dstate), None, length=T
        )
        proposals = draft_toks.T[:, :gamma]  # [S, gamma]

        # 2) target: one batched T-wide verify forward at each slot frontier
        cfg = self.cfg
        p0 = tstate.positions
        positions = p0[:, None] + jnp.arange(T)[None, :]     # [S, T]
        tokens = jnp.concatenate(
            [tstate.tokens[:, None], proposals], axis=1
        )  # [S, T]
        mask = verify_mask(cfg, p0, T, self.max_ctx)
        write = verify_write(p0)
        hidden, new_stack = mdl.forward(
            cfg, tparams, tokens, positions, write, tkv.stacked(), mask,
            self.target.rope,
        )
        logits = mdl.logits_from_hidden(cfg, tparams, hidden)  # [S, T, V]

        # 3) accept/sample scan over the window: the full sampler chain per
        # position with sequentially-updated counts — emitted tokens follow
        # the exact non-speculative sampling distribution.
        S = self.num_slots

        def acc_body(carry, xs):
            counts, keys, still, n_emit, final_tok = carry
            logits_t, draft_t, t = xs  # [S, V], [S], scalar
            tok, new_keys = smp.sample(
                logits_t, tstate.params, counts, keys, tstate.bias
            )
            emit_now = still & tstate.active
            # keys advance once per EMITTED token (seeded-stream contract,
            # same pattern as ModelRunner._decode_fn's inactive-slot hold)
            keys = jnp.where(emit_now, new_keys, keys)
            counts = counts.at[jnp.arange(S), tok].add(
                emit_now.astype(counts.dtype)
            )
            final_tok = jnp.where(emit_now, tok, final_tok)
            n_emit = n_emit + emit_now.astype(jnp.int32)
            is_match = emit_now & (t < gamma) & (tok == draft_t)
            emitted_t = jnp.where(emit_now, tok, SKIP)
            return (counts, keys, is_match, n_emit, final_tok), emitted_t

        init = (
            tstate.counts,
            tstate.keys,
            jnp.ones(S, jnp.bool_),
            jnp.zeros(S, jnp.int32),
            tstate.tokens,
        )
        draft_padded = jnp.concatenate(
            [proposals, jnp.full((S, 1), SKIP, jnp.int32)], axis=1
        )
        (counts, keys, _, n_emit, final_tok), emitted = jax.lax.scan(
            acc_body, init,
            (logits.transpose(1, 0, 2), draft_padded.T, jnp.arange(T)),
        )  # emitted [T, S]

        new_pos = jnp.minimum(p0 + n_emit, self.max_ctx - 1)
        tstate = dataclasses.replace(
            tstate, tokens=final_tok, positions=new_pos, keys=keys,
            counts=counts,
        )
        # 4) draft resync: roll its frontier back to the accepted length and
        # feed it the corrected token next window
        dstate = dataclasses.replace(
            dstate, tokens=final_tok, positions=new_pos,
        )
        return (KVCache.from_stacked(new_stack), tstate,
                dkv, dstate, emitted)

    # -- host API ---------------------------------------------------------

    def step_spec_async(self) -> jax.Array:
        """One speculative window over all slots; returns the [gamma+1, S]
        emitted-token device array (SKIP = nothing for that step/slot)."""
        (self.target.kv, self.target.state,
         self.draft.kv, self.draft.state, emitted) = self._spec(
            self.target.params, self.target.kv, self.target.state,
            self.draft.params, self.draft.kv, self.draft.state,
        )
        return emitted

    def step_spec(self) -> np.ndarray:
        # synchronous by contract (telemetry + tests); the scheduler's hot
        # path uses step_spec_async + copy_to_host_async
        rows = np.asarray(  # jaxlint: disable=host-sync-in-hot-path
            self.step_spec_async()
        )
        self.observe_window(rows)
        return rows

    def observe_window(self, rows: np.ndarray) -> None:
        """Fold one drained window into the acceptance telemetry. An active
        slot always emits ≥1 token, so active columns are the ones with any
        non-SKIP entry."""
        self.total_windows += 1
        emitted = (rows != SKIP).sum(axis=0)
        self.total_emitted += int(emitted.sum())
        self.total_eligible += int((emitted > 0).sum()) * (self.gamma + 1)

    # -- slot lifecycle (scheduler-facing, mirrors ModelRunner) -----------

    def admit(self, slot: int, prompt: list[int], **kw) -> int:
        """Prefill both models; the target's first sampled token seeds both
        token streams (the draft's own first sample is discarded)."""
        first = self.target.admit(slot, prompt, **kw)
        self.last_prefix_reused = self.target.last_prefix_reused
        # draft: plain greedy prefill — no resident reuse, no multimodal
        self.draft.admit(slot, prompt, temperature=0.0)
        st = self.draft.state
        self.draft.state = dataclasses.replace(
            st,
            tokens=st.tokens.at[slot].set(jnp.int32(first)),
            positions=st.positions.at[slot].set(
                self.target.state.positions[slot]
            ),
        )
        return first

    def resync_draft(self, slot: int, resident: list[int]) -> None:
        """Rebuild one slot's draft KV after non-speculative dispatches
        advanced the target without it (grammar-constrained interludes).
        ``resident`` is the scheduler's prompt+generated token record; its
        last element is the next token to feed."""
        prompt = list(resident[:-1]) or [0]
        self.draft.admit(slot, prompt, temperature=0.0)
        st = self.draft.state
        self.draft.state = dataclasses.replace(
            st,
            tokens=st.tokens.at[slot].set(jnp.int32(resident[-1])),
            # device-side copy of the target's frontier — no host sync
            positions=st.positions.at[slot].set(
                self.target.state.positions[slot]
            ),
        )

    def acquire_slot(self, slot: Optional[int] = None) -> Optional[int]:
        got = self.target.acquire_slot(slot)
        if got is not None:
            self.draft.acquire_slot(got)
        return got

    def free_slots(self) -> list[int]:
        return self.target.free_slots()

    def release(self, slot: int) -> None:
        self.target.release(slot)
        self.draft.release(slot)

    def set_bias(self, slot: int, bias_row) -> None:
        self.target.set_bias(slot, bias_row)

    def reusable_prefix(self, slot: int, resident, prompt,
                        valid_n=None) -> int:
        return self.target.reusable_prefix(slot, resident, prompt, valid_n)

    def slot_positions(self) -> np.ndarray:
        return self.target.slot_positions()

    def slot_position(self, slot: int) -> int:
        return self.target.slot_position(slot)

    @property
    def acceptance_rate(self) -> float:
        """Emitted tokens per active slot-window / (gamma+1): 1.0 = every
        window fully accepted for every active slot."""
        if not self.total_eligible:
            return 0.0
        return self.total_emitted / self.total_eligible

    def stats(self) -> dict:
        """Window telemetry snapshot (obs /metrics + GetMetrics surface)."""
        return {
            "gamma": self.gamma,
            "windows": self.total_windows,
            "emitted": self.total_emitted,
            "eligible": self.total_eligible,
            "acceptance_rate": self.acceptance_rate,
        }


def build_spec_decoder(target: ModelRunner, draft_ref: str, *,
                       model_path="models", gamma: int = 4,
                       dtype: str = "bfloat16") -> SpecDecoder:
    """Resolve ``draft_ref`` and couple it to ``target`` (manager entry)."""
    if getattr(target, "pp_enabled", False):
        # the verify forward here calls mdl.forward directly — it would
        # GSPMD over pipe-sharded stacked weights, all-gathering the full
        # weight set per window (defeating capacity mode)
        raise ValueError(
            "speculative decoding is not supported with pipeline "
            "parallelism")
    if getattr(target, "ga_n", 1) > 1:
        # self-extend targets carry an UNroped KV cache + identity rope
        # table; the verify forward here would compute position-blind
        # attention — reject rather than emit garbage
        raise ValueError(
            "speculative decoding is not supported with self-extend "
            "(grp_attn_n > 1)")
    from localai_tpu.models.registry import resolve_model

    draft = resolve_model(draft_ref, model_path=model_path, dtype=dtype)
    params = draft.params
    if target.mesh is not None:
        from localai_tpu.parallel import sharding as shd

        params = shd.shard_params(params, draft.cfg, target.mesh)
    runner = ModelRunner(
        draft.cfg, params,
        num_slots=target.num_slots,
        max_ctx=target.max_ctx,
        prefill_buckets=list(target.buckets[:-1]) or None,
        kv_dtype=target.kv_dtype,
        mesh=target.mesh,
        # spec windows run contiguous slot-row KV programs on both caches
        paged=False,
    )
    return SpecDecoder(target, runner, gamma=gamma)
