"""Compatibility shim over :mod:`localai_tpu.spec`.

The contiguous-only draft+verify window engine that used to live here
was replaced by the block-native speculation subsystem
(:class:`localai_tpu.spec.SpecEngine`): pluggable drafters behind one
protocol (co-located draft model, self-drafting n-gram lookup), a
verify-k batched target dispatch that works over BOTH KV layouts
(contiguous slot rows and the paged block-table mirror), and per-slot
accept/rollback inside the compiled program. One code path — this module
only keeps the old import surface alive:

* :data:`SKIP` — the emitted-row sentinel (now defined in engine.runner
  next to NAN_TOKEN);
* :func:`verify_write` / :func:`verify_mask` — the KV write policy and
  mask (now in engine.kvcache with the other policies);
* :class:`SpecDecoder` — a thin SpecEngine subclass pairing a target
  with a draft ModelRunner, preserving the historical constructor and
  the ``.draft`` attribute tests and callers use. Paged targets are
  fully supported now (the PR 6 rejection is gone).
"""

from __future__ import annotations

from localai_tpu.engine.kvcache import verify_mask, verify_write  # noqa: F401
from localai_tpu.engine.runner import SKIP, ModelRunner  # noqa: F401
from localai_tpu.spec.drafter import ModelDrafter
from localai_tpu.spec.engine import SpecEngine, build_spec_engine


class SpecDecoder(SpecEngine):
    """Target + draft-model speculation (the historical constructor).

    ``draft`` is a contiguous ModelRunner for the draft model; the
    target may use either KV layout. The scheduler drives it exactly
    like multi-step decode: each dispatch returns [gamma+1, S] token
    rows where SKIP (-1) marks positions past a slot's accepted
    window."""

    def __init__(self, target: ModelRunner, draft: ModelRunner,
                 gamma: int = 4):
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft.cfg.vocab_size} != target vocab "
                f"{target.cfg.vocab_size} — speculative decoding needs a "
                "shared tokenizer"
            )
        if draft.num_slots != target.num_slots:
            raise ValueError("draft and target must have equal slot counts")
        if getattr(draft, "paged", False):
            raise ValueError(
                "the draft runner must be contiguous (its window scans "
                "run over slot rows; build it with paged=False)")
        super().__init__(target, ModelDrafter(draft, gamma), gamma=gamma)

    @property
    def draft(self) -> ModelRunner:
        return self.drafter.runner


def build_spec_decoder(target: ModelRunner, draft_ref: str, *,
                       model_path="models", gamma: int = 4,
                       dtype: str = "bfloat16") -> SpecEngine:
    """Resolve ``draft_ref`` and couple it to ``target`` (legacy manager
    entry — new callers use :func:`localai_tpu.spec.build_spec_engine`)."""
    return build_spec_engine(
        target, drafter="model", draft_ref=draft_ref,
        model_path=model_path, gamma=gamma, dtype=dtype,
    )
