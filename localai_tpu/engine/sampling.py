"""On-device batched sampling — one fused kernel chain per decode step.

Replaces llama.cpp's per-slot CPU sampler chain (repetition penalties,
top-k/top-p/min-p/temperature — applied per token per slot on host) with a
vectorized device implementation over all slots at once: no host round-trip
between logits and sampled token. Parity surface: the sampler options the
reference plumbs via PredictOptions (/root/reference/backend/backend.proto
PredictOptions: TopK/TopP/MinP/Temperature/Penalty/PresencePenalty/
FrequencyPenalty/Seed/NKeep) minus mirostat (CPU-sequential by construction;
accepted in config, mapped to plain temperature sampling).

Design notes (TPU):
  * full-vocab ops are avoided after one ``lax.top_k`` to K=64..256
    candidates (covers llama.cpp's default top_k=40 and caps tail work);
    top-p/min-p/temperature run on the [S, K] candidate matrix.
  * greedy (temperature<=0) is a select on the same path — no branch.
  * PRNG: per-slot counter-based keys (threefry) so slots are independent
    and reproducible under fixed seed regardless of batch composition.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

MAX_TOPK = 256  # candidate cap; llama.cpp default top_k=40


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SamplingParams:
    """Per-slot sampling parameters, stored as [S] arrays on device."""

    temperature: jax.Array      # f32; <=0 → greedy
    top_k: jax.Array            # i32; 0 → disabled (use MAX_TOPK pool)
    top_p: jax.Array            # f32; 1.0 → disabled
    min_p: jax.Array            # f32; 0.0 → disabled
    repeat_penalty: jax.Array   # f32; 1.0 → disabled
    presence_penalty: jax.Array # f32
    frequency_penalty: jax.Array# f32

    @staticmethod
    def init(num_slots: int) -> "SamplingParams":
        # each field gets its own buffer — aliased leaves break jit donation
        def full(v):
            return jnp.full(num_slots, v, jnp.float32)

        return SamplingParams(
            temperature=full(1.0),
            top_k=jnp.full(num_slots, 40, jnp.int32),
            top_p=full(1.0),
            min_p=full(0.0),
            repeat_penalty=full(1.0),
            presence_penalty=full(0.0),
            frequency_penalty=full(0.0),
        )

    DEFAULTS = {
        "temperature": 1.0,
        "top_k": 40,
        "top_p": 1.0,
        "min_p": 0.0,
        "repeat_penalty": 1.0,
        "presence_penalty": 0.0,
        "frequency_penalty": 0.0,
    }

    def with_slot(self, slot: int, **kw) -> "SamplingParams":
        """Functional single-slot update (host-side, at admit time).

        Unspecified (None) fields reset to engine defaults so a reused slot
        never inherits the previous request's sampling options.
        """
        out = {}
        for f in dataclasses.fields(self):
            arr = getattr(self, f.name)
            val = kw.get(f.name)
            if val is None:
                val = self.DEFAULTS[f.name]
            out[f.name] = arr.at[slot].set(jnp.asarray(val, arr.dtype))
        return SamplingParams(**out)


def apply_penalties(
    logits: jax.Array,        # [S, V] f32
    counts: jax.Array,        # [S, V] i32 — token occurrence counts (prompt+generated)
    params: SamplingParams,
) -> jax.Array:
    """llama.cpp-style repetition penalty + OpenAI frequency/presence
    penalties, vectorized over slots."""
    seen = counts > 0
    rp = params.repeat_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen, penalized, logits)
    logits = logits - params.frequency_penalty[:, None] * counts.astype(jnp.float32)
    logits = logits - params.presence_penalty[:, None] * seen.astype(jnp.float32)
    return logits


def sample(
    logits: jax.Array,        # [S, V] (any float dtype)
    params: SamplingParams,
    counts: jax.Array,        # [S, V] i32
    keys: jax.Array,          # [S] jax PRNG keys
    bias: jax.Array | None = None,  # [S, V] f32 additive logit bias
                                    # (OpenAI logit_bias + grammar masks as -inf)
) -> tuple[jax.Array, jax.Array]:
    """Returns (tokens [S] i32, new_keys [S])."""
    S, V = logits.shape
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    logits = apply_penalties(logits, counts, params)

    k = min(MAX_TOPK, V)
    vals, idx = jax.lax.top_k(logits, k)           # [S, K] desc
    j = jnp.arange(k)[None, :]

    # per-slot top_k limit within the candidate pool (0 → disabled)
    tk = jnp.where(params.top_k[:, None] > 0, params.top_k[:, None], k)
    keep = j < tk

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = jnp.where(keep, vals / temp, -jnp.inf)
    probs = jax.nn.softmax(scaled, axis=-1)

    # top-p (nucleus): keep the smallest prefix with cumulative prob >= top_p
    csum = jnp.cumsum(probs, axis=-1)
    keep_p = (csum - probs) < params.top_p[:, None]
    # min-p: drop candidates below min_p * p_max
    keep_mp = probs >= params.min_p[:, None] * probs[:, :1]
    scaled = jnp.where(keep_p & keep_mp, scaled, -jnp.inf)

    new_keys = jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
    sub, carry = new_keys[:, 0], new_keys[:, 1]
    sampled_j = jax.vmap(lambda kk, l: jax.random.categorical(kk, l))(sub, scaled)

    greedy = params.temperature <= 0.0
    chosen_j = jnp.where(greedy, 0, sampled_j)
    tokens = jnp.take_along_axis(idx, chosen_j[:, None], axis=1)[:, 0]
    return tokens.astype(jnp.int32), carry


def update_counts(
    counts: jax.Array, tokens: jax.Array, active: jax.Array
) -> jax.Array:
    """Scatter-add sampled tokens into the occurrence counts (inactive slots
    add to a scratch row... no — they add 0)."""
    S = counts.shape[0]
    inc = active.astype(counts.dtype)
    return counts.at[jnp.arange(S), tokens].add(inc)


def count_prompt_tokens(
    counts: jax.Array, slot: jax.Array, tokens: jax.Array, length: jax.Array
) -> jax.Array:
    """Initialize a slot's counts from its prompt (so repetition penalties see
    the prompt, matching llama.cpp's penalty window over context)."""
    V = counts.shape[1]
    t = jnp.arange(tokens.shape[-1])
    valid = t < length
    row = jnp.zeros((V,), counts.dtype).at[tokens.reshape(-1)].add(
        valid.reshape(-1).astype(counts.dtype)
    )
    return counts.at[slot].set(row)
