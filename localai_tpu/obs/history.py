"""Multi-resolution metrics history (obs subsystem).

Every other signal in the stack is instantaneous: the flight ring holds
the last N dispatches, SLO windows a few minutes, /metrics the current
scrape. This module gives the key gauges/counters and the per-tenant
usage series a PERSISTENT past: each recorded point lands in three
downsampled rings at once —

====  ==========  ========  ==================================
res   capacity    span      downsample
====  ==========  ========  ==================================
1 s   600 pts     10 min    raw
10 s  720 pts     2 h       gauge: mean · counter: max
5 m   576 pts     2 d       gauge: mean · counter: max
====  ==========  ========  ==================================

Counter series carry cumulative monotone totals, so the bucket value is
the MAX total seen in the bucket (rate = successive differences);
gauge buckets keep (sum, count) and report the mean. Bucket timestamps
align to ``int(ts // res) * res`` — a point and its coarser buckets
always nest.

Persistence: when ``LOCALAI_HISTORY_DIR`` is set, a daemon writer
thread snapshots the whole store every ``LOCALAI_HISTORY_SNAPSHOT_S``
(default 30 s) seconds — JSON to a tmp file + ``os.replace`` so a crash
mid-write can never leave a torn snapshot — and boot re-onboards the
last snapshot, so the series survive a restart (the check_usage smoke
gates on exactly that). All file I/O happens on the writer thread or an
executor, never on the event loop and never under the store lock.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Optional

log = logging.getLogger(__name__)

RESOLUTIONS = (1, 10, 300)
CAPACITY = {1: 600, 10: 720, 300: 576}
SNAPSHOT_FILE = "history.json"


class History:
    """The in-process multi-resolution series store. Mutators take one
    short lock around list/deque arithmetic; snapshots copy under the
    lock and serialize outside it (FlightRecorder discipline)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name → {"kind": str, "rings": {res: deque[[ts, sum, n, max]]}}
        self._series: dict[str, dict] = {}
        self._dirty = False
        self._dir: Optional[str] = None
        self._writer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.snapshot_s = 30.0
        self.snapshots_written = 0

    # -- write side -------------------------------------------------------

    def record(self, name: str, value: float, *, kind: str = "gauge",
               ts: Optional[float] = None) -> None:
        """One point into all three rings. ``ts`` defaults to now (wall
        clock — history outlives the process, so monotonic won't do);
        explicit timestamps let tools/usage_report.py ingest BENCH_*
        points at their recorded times."""
        if ts is None:
            ts = time.time()
        value = float(value)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = {
                    "kind": kind,
                    "rings": {res: deque(maxlen=CAPACITY[res])
                              for res in RESOLUTIONS},
                }
            for res, ring in s["rings"].items():
                bucket = int(ts // res) * res
                if ring and ring[-1][0] == bucket:
                    cell = ring[-1]
                    cell[1] += value
                    cell[2] += 1
                    cell[3] = max(cell[3], value)
                elif ring and ring[-1][0] > bucket:
                    # out-of-order point: merge into its bucket if still
                    # resident, else drop (retention already passed it)
                    for cell in reversed(ring):
                        if cell[0] == bucket:
                            cell[1] += value
                            cell[2] += 1
                            cell[3] = max(cell[3], value)
                            break
                        if cell[0] < bucket:
                            break
                else:
                    ring.append([bucket, value, 1, value])
            self._dirty = True

    # -- read side --------------------------------------------------------

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def query(self, name: str, *, res: int = 10,
              since: float = 0.0) -> Optional[dict]:
        """Points for one series at one resolution, oldest first.
        Counter buckets report the max cumulative total in the bucket;
        gauge buckets the mean. None for an unknown series."""
        if res not in CAPACITY:
            res = min(CAPACITY, key=lambda r: abs(r - res))
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            kind = s["kind"]
            cells = [list(c) for c in s["rings"][res] if c[0] >= since]
        points = [
            {"ts": c[0],
             "value": c[3] if kind == "counter" else c[1] / max(1, c[2]),
             "count": c[2]}
            for c in cells
        ]
        return {"series": name, "kind": kind, "resolution_s": res,
                "capacity": CAPACITY[res], "points": points}

    # -- convenience feeds (scrape-time, host-side) -----------------------

    def observe_engine(self, model: str, m: dict) -> None:
        """The curated per-model engine series worth a past: called from
        the /metrics build (executor-side) with each scheduler's metrics
        dict. Worker-tier dicts that miss keys record nothing."""
        if "error" in m and len(m) == 1:
            return
        gauges = (("occupancy", "occupancy"),
                  ("queue_depth", "queue_depth"),
                  ("kv_utilization", "kv_utilization"),
                  # dispatch anatomy (obs.anatomy): None until the ring's
                  # window holds a non-compile dispatch — skip, don't zero
                  ("host_overhead_fraction", "host_overhead_fraction"),
                  ("device_bubble_fraction", "device_bubble_fraction"))
        for key, series in gauges:
            if m.get(key) is not None:
                self.record(f"{series}.{model}", m[key])
        counters = (("total_generated_tokens", "tokens_generated"),
                    ("total_prompt_tokens", "tokens_prompt"),
                    ("preemptions", "preemptions"),
                    ("shed_total", "requests_shed"))
        for key, series in counters:
            if key in m:
                self.record(f"{series}.{model}", m[key], kind="counter")

    def observe_ledger(self, ledger: Any) -> None:
        """Per-tenant and goodput/waste history from the process ledger
        (cumulative counters; the UI plots their differences)."""
        snap = ledger.snapshot()
        for tenant, panes in snap["tenants"].items():
            delivered = sum(p["delivered_tokens"] for p in panes.values())
            requests = sum(p["requests"] for p in panes.values())
            self.record(f"tenant_tokens.{tenant}", delivered,
                        kind="counter")
            self.record(f"tenant_requests.{tenant}", requests,
                        kind="counter")
        for model, tokens in snap["goodput_tokens"].items():
            self.record(f"goodput_tokens.{model}", tokens, kind="counter")
        waste_by_reason: dict[str, int] = {}
        for key, cell in snap["waste"].items():
            reason = key.partition("/")[0]
            waste_by_reason[reason] = (waste_by_reason.get(reason, 0)
                                       + cell["tokens"])
        for reason, tokens in waste_by_reason.items():
            self.record(f"waste_tokens.{reason}", tokens, kind="counter")

    # -- persistence ------------------------------------------------------

    def snapshot_dict(self) -> dict:
        with self._lock:
            series = {
                name: {"kind": s["kind"],
                       "rings": {str(res): [list(c) for c in ring]
                                 for res, ring in s["rings"].items()}}
                for name, s in self._series.items()
            }
        return {"version": 1, "saved_at": time.time(), "series": series}

    def save(self, directory: Optional[str] = None) -> Optional[str]:
        """Atomic snapshot: serialize outside the lock, write to a tmp
        sibling, ``os.replace`` into place. Returns the path (None when
        no directory is configured)."""
        directory = directory or self._dir
        if not directory:
            return None
        doc = self.snapshot_dict()
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, SNAPSHOT_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        self.snapshots_written += 1
        return path

    def load(self, directory: Optional[str] = None) -> bool:
        """Re-onboard the last snapshot (boot restore). Missing/corrupt
        files are a warning, never a crash — history is observability,
        not serving state."""
        directory = directory or self._dir
        if not directory:
            return False
        path = os.path.join(directory, SNAPSHOT_FILE)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except FileNotFoundError:
            return False
        except (OSError, ValueError) as e:
            log.warning("history snapshot %s unreadable: %s", path, e)
            return False
        series: dict[str, dict] = {}
        for name, s in (doc.get("series") or {}).items():
            rings = {}
            for res in RESOLUTIONS:
                cells = (s.get("rings") or {}).get(str(res)) or []
                rings[res] = deque(
                    ([float(c[0]), float(c[1]), int(c[2]), float(c[3])]
                     for c in cells if len(c) == 4),
                    maxlen=CAPACITY[res])
            series[name] = {"kind": s.get("kind", "gauge"), "rings": rings}
        with self._lock:
            self._series = series
            self._dirty = False
        return True

    def configure(self, directory: Optional[str],
                  snapshot_s: float = 30.0) -> None:
        """Attach a snapshot directory: restore what's there, then start
        the periodic writer thread (idempotent)."""
        self._dir = directory
        self.snapshot_s = max(1.0, snapshot_s)
        if not directory:
            return
        self.load(directory)
        if self._writer is None or not self._writer.is_alive():
            self._stop.clear()
            self._writer = threading.Thread(
                target=self._writer_loop, name="history-writer", daemon=True
            )
            self._writer.start()

    def _writer_loop(self) -> None:
        """Writer thread: flush a snapshot every interval while dirty.
        All disk I/O lives here — record() never blocks on a write."""
        while not self._stop.wait(self.snapshot_s):
            with self._lock:
                dirty, self._dirty = self._dirty, False
            if not dirty:
                continue
            try:
                self.save()
            except OSError as e:
                log.warning("history snapshot write failed: %s", e)

    def flush(self) -> Optional[str]:
        """Synchronous snapshot (shutdown/test hook)."""
        with self._lock:
            self._dirty = False
        try:
            return self.save()
        except OSError as e:
            log.warning("history flush failed: %s", e)
            return None

    def stop(self) -> None:
        self._stop.set()

    def reset(self) -> None:
        """Test hook: drop all series (the singleton is process-global)."""
        with self._lock:
            self._series.clear()
            self._dirty = False


def install_from_env(history: Optional[History] = None) -> bool:
    """Boot hook (AppState): LOCALAI_HISTORY_DIR turns persistence on;
    LOCALAI_HISTORY_SNAPSHOT_S tunes the writer cadence."""
    h = history or HISTORY
    directory = os.environ.get("LOCALAI_HISTORY_DIR", "")
    if not directory:
        return False
    try:
        snapshot_s = float(os.environ.get("LOCALAI_HISTORY_SNAPSHOT_S", 30))
    except ValueError:
        snapshot_s = 30.0
    h.configure(directory, snapshot_s=snapshot_s)
    return True


HISTORY = History()
