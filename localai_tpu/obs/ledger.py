"""Per-tenant cost ledger + goodput/waste decomposition (obs subsystem).

The accounting plane ROADMAP items 4 (telemetry-driven autoscaling) and 6
(per-tenant WFQ/quotas) build on. Two ideas fix the units:

* Orca's iteration-level scheduling makes the DISPATCH the natural
  accounting grain — every delivered token, device-dispatch millisecond
  and queue-wait second is attributable to exactly one request, hence to
  one (tenant, model, lane) pane.
* PagedAttention makes KV-BLOCK-SECONDS the memory cost unit: a request's
  context occupies ``ceil(tokens / block_tokens)`` blocks for its
  slot-resident lifetime, all host-side arithmetic — no device syncs.

Tenant identity is derived from the API key by :func:`derive_tenant`:
the raw key NEVER appears in a label, a trace, or any exposition — only
a short sha256 prefix (``t-<12 hex>``), or the stable ``anonymous``
bucket when auth is off. Label cardinality is bounded by an LRU of
``LOCALAI_TENANT_MAX`` tenants; overflow merges into one ``overflow``
pane and counts an eviction (the raw-key cardinality attack an open
endpoint would otherwise suffer becomes one bounded series).

Every request's work is classified exactly once at its terminal event
(``EngineTelemetry.finished`` — the single feed point all scheduler
tiers share) into GOODPUT (``stop``/``length`` deliveries) or a named
WASTE class:

====================  ====================================================
reason                meaning (unit)
====================  ====================================================
cancelled             tokens generated for a request the client abandoned
error                 tokens generated before a backend error
nan_quarantine        tokens on a request failed by the NaN row guard
spec_rejected         draft tokens proposed but rejected by verify
shed                  requests refused by SLO admission control (requests)
failover_reprefill    prompt tokens re-prefilled after a replica failover
migration_reprefill   prompt tokens re-prefilled by a migration fallback
====================  ====================================================

Per engine process the token-emitting classes reconcile exactly against
the flight ring: ``goodput_tokens + cancelled + error + nan_quarantine
tokens == FlightRecorder.total_tokens`` (both sides count sampled tokens,
EOS excluded). ``spec_rejected``/``shed``/``*_reprefill`` measure work
the ring never counted (draft lanes, refused admissions, repeated
prefill) and sit OUTSIDE that identity — the decomposition names them so
"the fleet is busy but goodput is flat" has a reason attached.

Feed discipline (double-count safety): the ledger is process-global, so
a request must be fed by exactly ONE scheduler tier. The rule is
"whoever stamped the tenant owns the feed": ``finished()`` only feeds
when ``request.tenant`` is non-empty, and ``InProcessReplica`` strips
the tenant before resubmitting to its shared-process inner engine — the
front-door FleetScheduler's feed is authoritative there. Worker
processes feed their own process-local ledger (tenant rides gRPC
metadata); the API tier harvests those panes over GetTelemetry as
drill-down and never sums them into its own totals.
"""

from __future__ import annotations

import hashlib
import math
import os
import threading
import time
from collections import OrderedDict, deque
from contextvars import ContextVar
from typing import Any, Optional

ANONYMOUS = "anonymous"   # auth off / exempt path: one stable bucket
OVERFLOW = "overflow"     # LRU-evicted tenants merge here

# token-emitting waste classes — these (plus goodput) reconcile against
# FlightRecorder.total_tokens; the rest measure work outside the ring
FLIGHT_WASTE = ("cancelled", "error", "nan_quarantine")
WASTE_REASONS = FLIGHT_WASTE + (
    "spec_rejected", "shed", "failover_reprefill", "migration_reprefill",
)

# the request's tenant travels with the asyncio task: set by the auth
# middleware, copied into executor threads by api.server.ContextExecutor,
# resolved by api.inference.build_gen_request
_tenant_var: ContextVar[str] = ContextVar("request_tenant", default="")


def current_tenant() -> str:
    """The tenant the auth middleware stamped on this task ('' outside a
    request context — direct scheduler submits stay unattributed)."""
    return _tenant_var.get()


def set_current_tenant(tenant: str) -> Any:
    """Stamp the calling context's tenant; returns the reset token."""
    return _tenant_var.set(tenant)


def derive_tenant(api_key: str) -> str:
    """API key → bounded tenant label. NEVER the raw key: a short sha256
    prefix identifies the tenant across restarts without leaking the
    secret into /metrics labels, traces, or snapshots."""
    if not api_key:
        return ANONYMOUS
    return "t-" + hashlib.sha256(api_key.encode("utf-8")).hexdigest()[:12]


def kv_block_seconds(prompt_tokens: int, completion_tokens: int,
                     resident_s: float, block_tokens: int = 16) -> float:
    """The PagedAttention memory cost of one finished request: final
    context footprint in blocks × slot-resident seconds. An upper-bound
    host estimate (the request grew into its last block over time), but
    monotone and comparable across tenants."""
    tokens = max(0, prompt_tokens) + max(0, completion_tokens)
    blocks = math.ceil(tokens / max(1, block_tokens))
    return blocks * max(0.0, resident_s)


def _new_pane() -> dict:
    return {
        "requests": 0,
        "delivered_tokens": 0,
        "prompt_tokens": 0,
        "dispatch_ms": 0.0,
        "queue_wait_ms": 0.0,
        "kv_block_seconds": 0.0,
        "waste_tokens": 0,
        "waste_requests": 0,
    }


def _merge_pane(dst: dict, src: dict) -> None:
    for k, v in src.items():
        dst[k] = dst.get(k, 0) + v


class TenantLedger:
    """The process-wide usage ledger. All mutators take one short lock
    around plain dict arithmetic (no I/O, no device work, no nested
    locks) — safe to call from the engine thread at drain points."""

    def __init__(self, max_tenants: Optional[int] = None,
                 events: int = 4096):
        if max_tenants is None:
            try:
                max_tenants = int(os.environ.get("LOCALAI_TENANT_MAX", 64))
            except ValueError:
                max_tenants = 64
        self.max_tenants = max(2, max_tenants)
        self._lock = threading.Lock()
        # tenant → {(model, lane) → pane}; OrderedDict is the LRU order
        self._tenants: OrderedDict[str, dict] = OrderedDict()
        # (reason, model) → {"tokens": n, "requests": n}
        self._waste: dict[tuple[str, str], dict] = {}
        # model → delivered tokens (goodput side of the decomposition)
        self._goodput: dict[str, int] = {}
        self.evictions_total = 0
        # bounded finished-request ring feeding /v1/usage ?since=/?window=
        self._events: deque = deque(maxlen=events)

    # -- feed points ------------------------------------------------------

    def note_request(self, *, tenant: str, model: str, lane: str,
                     reason: str, tokens: int, prompt_tokens: int,
                     dispatch_ms: float, queue_wait_ms: float,
                     kv_block_s: float) -> None:
        """One finished request, classified by its terminal reason:
        ``stop``/``length`` → goodput; anything else → the matching
        token-emitting waste class. Called from EngineTelemetry.finished
        — the single feed point every scheduler tier shares."""
        model = model or "engine"
        goodput = reason in ("stop", "length")
        with self._lock:
            pane = self._pane(tenant, model, lane)
            pane["requests"] += 1
            pane["prompt_tokens"] += max(0, prompt_tokens)
            pane["dispatch_ms"] += max(0.0, dispatch_ms)
            pane["queue_wait_ms"] += max(0.0, queue_wait_ms)
            pane["kv_block_seconds"] += max(0.0, kv_block_s)
            if goodput:
                pane["delivered_tokens"] += max(0, tokens)
                self._goodput[model] = (
                    self._goodput.get(model, 0) + max(0, tokens))
            else:
                waste_reason = (reason if reason in WASTE_REASONS
                                else "error")
                pane["waste_tokens"] += max(0, tokens)
                pane["waste_requests"] += 1
                self._waste_cell(waste_reason, model, tokens=max(0, tokens),
                                 requests=1)
            self._events.append({
                "ts": time.time(),
                "tenant": tenant,
                "model": model,
                "lane": lane,
                "reason": reason,
                "tokens": max(0, tokens),
                "prompt_tokens": max(0, prompt_tokens),
                "dispatch_ms": round(max(0.0, dispatch_ms), 3),
                "queue_wait_ms": round(max(0.0, queue_wait_ms), 3),
                "kv_block_seconds": round(max(0.0, kv_block_s), 3),
            })

    def note_waste(self, reason: str, *, model: str = "", tenant: str = "",
                   tokens: int = 0, requests: int = 0) -> None:
        """Waste observed OUTSIDE a request's terminal event: rejected
        draft tokens, shed admissions, failover/migration re-prefills.
        Tenant attribution is best-effort (the engine thread doesn't
        always know one) — the per-model decomposition is exact."""
        model = model or "engine"
        with self._lock:
            self._waste_cell(reason, model, tokens=max(0, tokens),
                             requests=max(0, requests))
            if tenant:
                pane = self._pane(tenant, model, "interactive")
                pane["waste_tokens"] += max(0, tokens)
                pane["waste_requests"] += max(0, requests)

    # -- internals (caller holds _lock) -----------------------------------

    def _pane(self, tenant: str, model: str,
              lane: str) -> dict:  # jaxlint: guarded-by(_lock)
        panes = self._tenants.get(tenant)
        if panes is None:
            panes = self._tenants[tenant] = {}
            while len(self._tenants) > self.max_tenants:
                self._evict()
        else:
            self._tenants.move_to_end(tenant)
        pane = panes.get((model, lane))
        if pane is None:
            pane = panes[(model, lane)] = _new_pane()
        return pane

    def _evict(self) -> None:  # jaxlint: guarded-by(_lock)
        """Fold the least-recently-seen evictable tenant into the
        ``overflow`` bucket — totals are conserved, cardinality bounded."""
        victim = next(
            (t for t in self._tenants if t not in (ANONYMOUS, OVERFLOW)),
            None)
        if victim is None:
            return
        panes = self._tenants.pop(victim)
        over = self._tenants.setdefault(OVERFLOW, {})
        for key, pane in panes.items():
            dst = over.get(key)
            if dst is None:
                over[key] = dict(pane)
            else:
                _merge_pane(dst, pane)
        self.evictions_total += 1

    def _waste_cell(self, reason: str, model: str, *, tokens: int,
                    requests: int) -> None:  # jaxlint: guarded-by(_lock)
        cell = self._waste.get((reason, model))
        if cell is None:
            cell = self._waste[(reason, model)] = {"tokens": 0,
                                                   "requests": 0}
        cell["tokens"] += tokens
        cell["requests"] += requests

    # -- read side --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able full-state copy (the GetTelemetry ``usage`` pane and
        the smoke's reconciliation input). Copy under the lock, format
        outside — same discipline as FlightRecorder.snapshot."""
        with self._lock:
            tenants = {
                t: {f"{m}/{lane}": dict(p)
                    for (m, lane), p in panes.items()}
                for t, panes in self._tenants.items()
            }
            waste = {f"{reason}/{m}": dict(cell)
                     for (reason, m), cell in self._waste.items()}
            goodput = dict(self._goodput)
            evictions = self.evictions_total
        return {
            "tenants": tenants,
            "waste": waste,
            "goodput_tokens": goodput,
            "evictions_total": evictions,
        }

    def goodput_totals(self, model: Optional[str] = None) -> dict:
        """The decomposition for one model (or all): delivered tokens,
        per-reason waste, and the flight-identity sum (delivered +
        token-emitting waste == FlightRecorder.total_tokens)."""
        with self._lock:
            delivered = (self._goodput.get(model, 0) if model
                         else sum(self._goodput.values()))
            waste: dict[str, dict] = {}
            for (reason, m), cell in self._waste.items():
                if model and m != model:
                    continue
                agg = waste.setdefault(reason,
                                       {"tokens": 0, "requests": 0})
                agg["tokens"] += cell["tokens"]
                agg["requests"] += cell["requests"]
        flight_tokens = delivered + sum(
            waste.get(r, {}).get("tokens", 0) for r in FLIGHT_WASTE)
        waste_tokens = sum(c["tokens"] for c in waste.values())
        total = delivered + waste_tokens
        return {
            "delivered_tokens": delivered,
            "waste": waste,
            "waste_tokens": waste_tokens,
            "flight_tokens": flight_tokens,
            "goodput_ratio": (delivered / total) if total else 1.0,
        }

    def usage_payload(self, *, since: Optional[float] = None,
                      window: Optional[float] = None) -> dict:
        """The GET /v1/usage body (OpenAI-usage-shaped: one ``data`` row
        per (tenant, model, lane) aggregation bucket). With ``since``/
        ``window`` the rows aggregate the bounded event ring instead of
        lifetime totals — ``coverage_start`` says how far back the ring
        actually reaches, so a truncated window is visible, not silent."""
        now = time.time()
        if window is not None:
            since = max(since or 0.0, now - window)
        if since is not None:
            with self._lock:
                events = [e for e in self._events if e["ts"] >= since]
                coverage = self._events[0]["ts"] if self._events else now
                evictions = self.evictions_total
            rows: dict[tuple, dict] = {}
            for e in events:
                key = (e["tenant"], e["model"], e["lane"])
                pane = rows.setdefault(key, _new_pane())
                pane["requests"] += 1
                pane["prompt_tokens"] += e["prompt_tokens"]
                pane["dispatch_ms"] += e["dispatch_ms"]
                pane["queue_wait_ms"] += e["queue_wait_ms"]
                pane["kv_block_seconds"] += e["kv_block_seconds"]
                if e["reason"] in ("stop", "length"):
                    pane["delivered_tokens"] += e["tokens"]
                else:
                    pane["waste_tokens"] += e["tokens"]
                    pane["waste_requests"] += 1
            data = [
                {"tenant": t, "model": m, "lane": lane, **pane}
                for (t, m, lane), pane in sorted(rows.items())
            ]
            return {
                "object": "usage",
                "start_time": since,
                "end_time": now,
                "coverage_start": coverage,
                "events": len(events),
                "data": data,
                "tenant_lru": {"evictions_total": evictions},
            }
        snap = self.snapshot()
        data = []
        for tenant, panes in sorted(snap["tenants"].items()):
            for key, pane in sorted(panes.items()):
                model, _, lane = key.partition("/")
                data.append({"tenant": tenant, "model": model,
                             "lane": lane, **pane})
        waste = [
            {"reason": key.partition("/")[0],
             "model": key.partition("/")[2], **cell}
            for key, cell in sorted(snap["waste"].items())
        ]
        return {
            "object": "usage",
            "start_time": None,
            "end_time": now,
            "data": data,
            "waste": waste,
            "goodput": self.goodput_totals(),
            "tenant_lru": {
                "evictions_total": snap["evictions_total"],
                "tenants": len(snap["tenants"]),
                "max_tenants": self.max_tenants,
            },
        }

    def export(self, registry: Any) -> None:
        """Sync the registry's tenant/goodput/waste families from the
        ledger (scrape-time, like update_engine_gauges). ``set_total`` is
        a max-merge, so re-exports and the update_engine_gauges spec/shed
        sync writing the same cells stay consistent."""
        snap = self.snapshot()
        for tenant, panes in snap["tenants"].items():
            for key, pane in panes.items():
                model, _, lane = key.partition("/")
                lbl = {"tenant": tenant, "model": model, "lane": lane}
                registry.tenant_requests.set_total(pane["requests"], **lbl)
                registry.tenant_tokens.set_total(
                    pane["delivered_tokens"], **lbl)
                registry.tenant_prompt_tokens.set_total(
                    pane["prompt_tokens"], **lbl)
                registry.tenant_dispatch_ms.set_total(
                    pane["dispatch_ms"], **lbl)
                registry.tenant_queue_wait_ms.set_total(
                    pane["queue_wait_ms"], **lbl)
                registry.tenant_kv_block_seconds.set_total(
                    pane["kv_block_seconds"], **lbl)
        registry.tenant_lru_evictions.set_total(snap["evictions_total"])
        for key, cell in snap["waste"].items():
            reason, _, model = key.partition("/")
            registry.waste_tokens.set_total(
                cell["tokens"], model=model, reason=reason)
            registry.waste_requests.set_total(
                cell["requests"], model=model, reason=reason)
        for model, tokens in snap["goodput_tokens"].items():
            registry.goodput_tokens.set_total(tokens, model=model)
            registry.goodput_ratio.set(
                self.goodput_totals(model)["goodput_ratio"], model=model)

    def reset(self) -> None:
        """Test hook: drop all state (the singleton is process-global)."""
        with self._lock:
            self._tenants.clear()
            self._waste.clear()
            self._goodput.clear()
            self._events.clear()
            self.evictions_total = 0


LEDGER = TenantLedger()
