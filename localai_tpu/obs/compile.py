"""XLA compile-time telemetry + the compiled-program cost observatory.

Capture paths, matching what this jax build actually exposes:

  * :func:`watch` wraps a jitted entry point (``engine/runner.py`` wraps
    all of its programs). jax compiles synchronously on the first dispatch
    of each static-argument shape while *execution* is async, so the wall
    time of that first call is trace+lower+compile to within one program
    execution — the same reasoning the scheduler uses to exclude fresh
    shapes from its step-time EMA. Later dispatches of a seen shape pass
    straight through with one set lookup + counter bump of overhead.
  * :func:`install` registers a ``jax.monitoring`` duration listener for
    compilation events. On this jax version only the persistent
    compilation cache emits them, so the listener is a supplement; newer
    versions emit real backend-compile durations and will land in the same
    series. Gated: a jax without ``jax.monitoring`` just skips it.

Both feed ``localai_xla_compile_total`` / ``localai_xla_compile_seconds_total``.

**Cost observatory** (``GET /debug/programs``): every watched program+shape
lands in the process-wide :data:`CATALOG` as its abstract signature
(``ShapeDtypeStruct`` leaves — no buffers pinned, donated args included).
``cost_analysis()``/``memory_analysis()`` are harvested LAZILY on the first
catalog report, by re-lowering from the stored avals: re-compiling at first
dispatch would double every compile on the serving path, so the observatory
pays that price only when an operator actually asks "where does the
bandwidth go". The scheduler feeds measured per-dispatch latency via
:func:`note_latency`; the report divides bytes-accessed and FLOPs by it and
by the device roofline (obs.device) into achieved fractions — the direct
answer to bench_micro's decode-bandwidth question.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Any, Callable, Optional

from localai_tpu.faults import registry as _faults
from localai_tpu.obs.metrics import REGISTRY, Registry

_install_lock = threading.Lock()
_installed = False
# every registry that ever asked for compile events: ONE jax.monitoring
# listener fans out to all of them (jax offers registration but no
# deregistration, so per-registry listeners would leak). Weak refs keep
# short-lived test registries collectable.
_registries: "weakref.WeakSet[Registry]" = weakref.WeakSet()


def _avalize(x: Any) -> Any:
    """Array → ShapeDtypeStruct (identity for non-arrays): the lowering
    signature the catalog stores instead of live buffers — holding real
    args would pin donated HBM and model params past unload."""
    if hasattr(x, "shape") and hasattr(x, "dtype") and hasattr(x, "ndim"):
        import jax

        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


class ProgramEntry:
    """One (program, shape-key): signature + counters + lazy cost."""

    def __init__(self, program: str, key: tuple, fn: Callable,
                 avals: tuple, statics: dict, compile_seconds: float):
        self.program = program
        self.key = key
        try:
            self.fn_ref = weakref.ref(fn)
        except TypeError:  # unweakrefable callables: better pinned than lost
            self.fn_ref = lambda fn=fn: fn
        self.avals = avals
        self.statics = statics
        self.compile_seconds = compile_seconds
        self.dispatches = 0
        self.cost: Optional[dict] = None       # lazily harvested, cached
        self.cost_error: str = ""


def _normalize_cost(analysis: Any) -> dict:
    """cost_analysis() returns a dict or a per-computation list of dicts
    depending on backend/version; fold to one {flops, bytes_accessed}."""
    if analysis is None:
        return {}
    entries = analysis if isinstance(analysis, (list, tuple)) else [analysis]
    flops = 0.0
    byts = 0.0
    for e in entries:
        if not isinstance(e, dict):
            continue
        flops += float(e.get("flops", 0.0) or 0.0)
        byts += float(e.get("bytes accessed", 0.0) or 0.0)
    return {"flops": flops, "bytes_accessed": byts}


class ProgramCatalog:
    """Process-wide compiled-program registry behind /debug/programs.

    Entries are keyed (program, watch-instance, shape-key): two loaded
    models both watch a "decode" program whose top-level args are pytrees
    (identical shape keys), and without the per-``watch()`` instance id
    the second model's entries would overwrite the first's. The latency
    EMA stays keyed (program, steps) — the scheduler feeding it does not
    know instances, so with several models loaded it blends their decode
    latencies (single-model serving, the v1 deployment, is exact)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[tuple, ProgramEntry] = {}
        # measured seconds per DISPATCH, EMA, keyed (program, steps)
        self._latency: dict[tuple, float] = {}

    def record(self, program: str, key: tuple, fn: Callable,
               args: tuple, kwargs: dict, compile_seconds: float) -> None:
        try:
            import jax

            avals = jax.tree.map(_avalize, args)
        except Exception:  # noqa: BLE001 — the catalog is best-effort
            avals = None
        entry = ProgramEntry(program, key, fn, avals, dict(kwargs),
                             compile_seconds)
        with self._lock:
            entry.dispatches = 1
            self._entries[(program, key)] = entry

    def dispatched(self, program: str, key: tuple) -> None:
        with self._lock:
            e = self._entries.get((program, key))
            if e is not None:
                e.dispatches += 1

    def note_latency(self, program: str, seconds: float, *,
                     steps: int = 1) -> None:
        """Fold one measured per-dispatch wall time into the (program,
        steps) EMA — called by the scheduler at its drain points, never on
        the dispatch path."""
        if seconds <= 0:
            return
        k = (program, int(steps))
        with self._lock:
            prev = self._latency.get(k)
            self._latency[k] = (seconds if prev is None
                                else 0.8 * prev + 0.2 * seconds)

    def _harvest(self, entry: ProgramEntry) -> None:
        """Lower+compile from the stored avals and cache the analysis.
        This is the one deliberately expensive call in the subsystem —
        report()-time only, guarded, and cached per entry."""
        fn = entry.fn_ref()
        if fn is None:
            entry.cost_error = "program no longer live (model unloaded)"
            return
        if entry.avals is None:
            entry.cost_error = "signature capture failed"
            return
        try:
            compiled = fn.lower(*entry.avals, **entry.statics).compile()
            cost = _normalize_cost(compiled.cost_analysis())
            try:
                mem = compiled.memory_analysis()
                cost.update(
                    argument_bytes=getattr(mem, "argument_size_in_bytes", None),
                    output_bytes=getattr(mem, "output_size_in_bytes", None),
                    temp_bytes=getattr(mem, "temp_size_in_bytes", None),
                    generated_code_bytes=getattr(
                        mem, "generated_code_size_in_bytes", None),
                )
            except Exception:  # noqa: BLE001 — memory stats are optional
                pass
            entry.cost = cost
        except Exception as e:  # noqa: BLE001 — a meshed program may not
            # re-lower from bare avals (sharding was on the buffers)
            entry.cost_error = f"{type(e).__name__}: {e}"

    def report(self, *, roofline: Optional[dict] = None,
               harvest: bool = True) -> list[dict]:
        """Catalog view joined with measured latency and the roofline.
        ``harvest=False`` skips lazy compilation (cheap listing)."""
        with self._lock:
            entries = list(self._entries.values())
            latency = dict(self._latency)
        peak_gbps = (roofline or {}).get("peak_gbps")
        peak_tflops = (roofline or {}).get("peak_tflops")
        out = []
        for e in entries:
            if harvest and e.cost is None and not e.cost_error:
                self._harvest(e)
            steps = int(e.statics.get("n", 1) or 1)
            lat = latency.get((e.program, steps))
            row: dict = {
                "program": e.program,
                # which watch() wrapper (≈ which runner) this entry is —
                # two loaded models both have a "decode"
                "instance": e.key[0] if e.key else 0,
                "statics": {k: v for k, v in e.statics.items()},
                "first_dispatch_seconds": round(e.compile_seconds, 4),
                "dispatches": e.dispatches,
                "dispatch_seconds_ema": (None if lat is None
                                         else round(lat, 6)),
            }
            if e.cost:
                row.update(e.cost)
                flops = e.cost.get("flops") or 0.0
                byts = e.cost.get("bytes_accessed") or 0.0
                if lat:
                    row["achieved_gflops"] = round(flops / lat / 1e9, 3)
                    row["achieved_gbps"] = round(byts / lat / 1e9, 3)
                    if peak_tflops:
                        row["flops_fraction"] = round(
                            flops / lat / (peak_tflops * 1e12), 4)
                    if peak_gbps:
                        row["bandwidth_fraction"] = round(
                            byts / lat / (peak_gbps * 1e9), 4)
            elif e.cost_error:
                row["cost_error"] = e.cost_error
            out.append(row)
        out.sort(key=lambda r: (r["program"], r["instance"],
                                str(r["statics"])))
        return out


CATALOG = ProgramCatalog()


def note_latency(program: str, seconds: float, *, steps: int = 1) -> None:
    CATALOG.note_latency(program, seconds, steps=steps)


# one id per watch() wrapper: it disambiguates catalog entries when two
# runners (two loaded models) watch same-named programs whose top-level
# args are pytrees and therefore produce identical shape keys
_WATCH_SEQ = itertools.count(1)


def watch(fn: Callable, program: str,
          registry: Optional[Registry] = None) -> Callable:
    """Wrap a jitted callable: the first call per static-kwargs shape is
    timed and recorded as a compilation of ``program`` (and catalogued for
    the cost observatory); later calls bump the dispatch counter."""
    reg = registry or REGISTRY
    seen: set = set()
    lock = threading.Lock()
    wid = next(_WATCH_SEQ)

    def wrapped(*args: Any, **kwargs: Any) -> Any:
        # program identity = watch instance + static kwargs + argument
        # shapes (array args with a new shape retrace even when the
        # statics repeat — e.g. the multimodal prefill keyed by embedding
        # row count)
        key = ((wid,)
               + tuple(getattr(a, "shape", None) for a in args)
               + tuple(sorted(kwargs.items())))
        with lock:
            fresh = key not in seen
            if fresh:
                seen.add(key)
        if not fresh:
            CATALOG.dispatched(program, key)
            return fn(*args, **kwargs)
        if _faults.ACTIVE:
            # chaos: a compile failure is a first-dispatch failure — the
            # site raises here, before the program is traced/compiled
            _faults.apply("engine.compile", key=program)
        t0 = time.monotonic()
        out = fn(*args, **kwargs)
        dt = time.monotonic() - t0
        reg.compile_count.inc(program=program)
        reg.compile_seconds.inc(dt, program=program)
        CATALOG.record(program, key, fn, args, kwargs, dt)
        return out

    wrapped.__name__ = getattr(fn, "__name__", program)
    return wrapped


def install(registry: Optional[Registry] = None) -> bool:
    """Register ``registry`` (default: the process-wide one) to receive
    jax.monitoring compile events; the single listener is installed on
    first call. Returns True when the listener is live."""
    global _installed
    with _install_lock:
        _registries.add(registry or REGISTRY)
        if _installed:
            return True
        try:
            from jax import monitoring
        except ImportError:
            return False

        def _on_duration(event: str, duration: float, **_kw: Any) -> None:
            if "compil" in event:
                for reg in list(_registries):
                    reg.compile_count.inc(program=event)
                    reg.compile_seconds.inc(duration, program=event)

        try:
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:  # noqa: BLE001 — telemetry must never break serving
            return False
        _installed = True
        return True
