"""XLA compile-time telemetry.

Two capture paths, matching what this jax build actually exposes:

  * :func:`watch` wraps a jitted entry point (``engine/runner.py`` wraps
    all of its programs). jax compiles synchronously on the first dispatch
    of each static-argument shape while *execution* is async, so the wall
    time of that first call is trace+lower+compile to within one program
    execution — the same reasoning the scheduler uses to exclude fresh
    shapes from its step-time EMA. Later dispatches of a seen shape pass
    straight through with one set lookup of overhead.
  * :func:`install` registers a ``jax.monitoring`` duration listener for
    compilation events. On this jax version only the persistent
    compilation cache emits them, so the listener is a supplement; newer
    versions emit real backend-compile durations and will land in the same
    series. Gated: a jax without ``jax.monitoring`` just skips it.

Both feed ``localai_xla_compile_total`` / ``localai_xla_compile_seconds_total``.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Optional

from localai_tpu.obs.metrics import REGISTRY, Registry

_install_lock = threading.Lock()
_installed = False
# every registry that ever asked for compile events: ONE jax.monitoring
# listener fans out to all of them (jax offers registration but no
# deregistration, so per-registry listeners would leak). Weak refs keep
# short-lived test registries collectable.
_registries: "weakref.WeakSet[Registry]" = weakref.WeakSet()


def watch(fn: Callable, program: str,
          registry: Optional[Registry] = None) -> Callable:
    """Wrap a jitted callable: the first call per static-kwargs shape is
    timed and recorded as a compilation of ``program``."""
    reg = registry or REGISTRY
    seen: set = set()
    lock = threading.Lock()

    def wrapped(*args: Any, **kwargs: Any) -> Any:
        # program identity = static kwargs + argument shapes (array args
        # with a new shape retrace even when the statics repeat — e.g. the
        # multimodal prefill keyed by embedding row count)
        key = (tuple(getattr(a, "shape", None) for a in args)
               + tuple(sorted(kwargs.items())))
        with lock:
            fresh = key not in seen
            if fresh:
                seen.add(key)
        if not fresh:
            return fn(*args, **kwargs)
        t0 = time.monotonic()
        out = fn(*args, **kwargs)
        reg.compile_count.inc(program=program)
        reg.compile_seconds.inc(time.monotonic() - t0, program=program)
        return out

    wrapped.__name__ = getattr(fn, "__name__", program)
    return wrapped


def install(registry: Optional[Registry] = None) -> bool:
    """Register ``registry`` (default: the process-wide one) to receive
    jax.monitoring compile events; the single listener is installed on
    first call. Returns True when the listener is live."""
    global _installed
    with _install_lock:
        _registries.add(registry or REGISTRY)
        if _installed:
            return True
        try:
            from jax import monitoring
        except ImportError:
            return False

        def _on_duration(event: str, duration: float, **_kw: Any) -> None:
            if "compil" in event:
                for reg in list(_registries):
                    reg.compile_count.inc(program=event)
                    reg.compile_seconds.inc(duration, program=event)

        try:
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:  # noqa: BLE001 — telemetry must never break serving
            return False
        _installed = True
        return True
