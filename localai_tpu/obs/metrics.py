"""Minimal OpenMetrics/Prometheus registry (the process-wide metric set).

Parity: the reference's OTel meter + Prometheus exporter with one
``api_call`` histogram labeled by method/path
(/root/reference/core/services/metrics.go:13-45, recorded by middleware
app.go:117-122, scraped at GET /metrics routes/localai.go:45). No
prometheus_client in this image, so the text exposition is hand-rolled —
it is a stable, tiny format.

Grown here into the engine telemetry surface: per-request latency
histograms (TTFT, TPOT, queue wait) and engine gauges/counters (batch
occupancy, KV-slot utilization, prompt/prefix-cache reuse, speculative
acceptance, XLA compile time). Event-time series are observed by
``obs.engine.EngineTelemetry``; point-in-time gauges are refreshed at
scrape time via ``update_engine_gauges`` from the scheduler's metrics
dict, so the decode loop never pays for a scrape.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
            30.0, 60.0)
# per-token decode latency lives orders of magnitude below API-call time
_TPOT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5)


def escape_label_value(value: object) -> str:
    r"""OpenMetrics label-value escaping: ``\`` → ``\\``, ``"`` → ``\"``,
    newline → ``\n`` — in that order, so a backslash introduced by the
    quote/newline escapes is not itself re-escaped."""
    return (str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key: tuple) -> str:
    return ",".join(f'{k}="{escape_label_value(v)}"' for k, v in key)


class Histogram:
    def __init__(self, name: str, help_text: str,
                 buckets: Iterable[float] = _BUCKETS):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._series: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * (len(self.buckets) + 1), 0.0, 0]  # counts, sum, n
                self._series[key] = s
            counts, _, _ = s
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            s[1] += value
            s[2] += 1

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, (counts, total, n) in sorted(self._series.items()):
                base = _fmt_labels(key)
                cum = 0
                for i, ub in enumerate(self.buckets):
                    cum += counts[i]
                    lbl = f"{base},le=\"{ub}\"" if base else f'le="{ub}"'
                    lines.append(f"{self.name}_bucket{{{lbl}}} {cum}")
                cum += counts[-1]
                lbl = f"{base},le=\"+Inf\"" if base else 'le="+Inf"'
                lines.append(f"{self.name}_bucket{{{lbl}}} {cum}")
                suffix = f"{{{base}}}" if base else ""
                lines.append(f"{self.name}_sum{suffix} {total}")
                lines.append(f"{self.name}_count{suffix} {n}")
        return "\n".join(lines)


class Counter:
    kind = "counter"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def set_total(self, value: float, **labels: str) -> None:
        """Sync the series to an externally tracked monotone total."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._series[key] = max(self._series.get(key, 0.0), value)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            for key, val in sorted(self._series.items()):
                base = _fmt_labels(key)
                suffix = f"{{{base}}}" if base else ""
                lines.append(f"{self.name}{suffix} {val}")
        return "\n".join(lines)


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._series[key] = value


class Registry:
    """The process-wide metric set.

    Every Histogram/Counter/Gauge attribute set in ``__init__`` is part of
    the /metrics exposition, in definition order."""

    def __init__(self) -> None:
        self.api_call = Histogram(
            "localai_api_call_seconds", "API call duration by method/path"
        )
        self.tokens_generated = Counter(
            "localai_tokens_generated_total", "Completion tokens emitted"
        )
        self.tokens_prompt = Counter(
            "localai_prompt_tokens_total", "Prompt tokens processed"
        )
        self.active_slots = Gauge(
            "localai_active_slots", "Occupied decode slots per model"
        )
        # -- engine telemetry (obs subsystem) --------------------------
        self.ttft = Histogram(
            "localai_ttft_seconds",
            "Time from request submit to first sampled token",
        )
        self.tpot = Histogram(
            "localai_tpot_seconds",
            "Mean per-output-token decode latency per request",
            buckets=_TPOT_BUCKETS,
        )
        self.queue_wait = Histogram(
            "localai_queue_wait_seconds",
            "Time a request waited for a free decode slot",
        )
        self.requests = Counter(
            "localai_requests_total",
            "Finished generation requests by finish reason",
        )
        self.preemptions = Counter(
            "localai_preemptions_total",
            "Requests that left a decode slot before natural completion",
        )
        self.batch_occupancy = Gauge(
            "localai_batch_occupancy",
            "Occupied fraction of decode slots (continuous-batching load)",
        )
        self.queue_depth = Gauge(
            "localai_queue_depth", "Requests waiting for a decode slot"
        )
        self.kv_utilization = Gauge(
            "localai_kv_slot_utilization",
            "Fraction of KV-cache rows holding live context",
        )
        # -- paged KV cache (engine/paged.py block pool) -------------------
        self.kv_blocks_free = Gauge(
            "localai_kv_blocks_free",
            "Paged-KV blocks available for admission (immediately free + "
            "reclaimable prefix-pool cache)",
        )
        self.kv_blocks_used = Gauge(
            "localai_kv_blocks_used",
            "Paged-KV blocks referenced by live sequences (reservations "
            "included)",
        )
        self.kv_blocks_cached = Gauge(
            "localai_kv_blocks_cached",
            "Paged-KV blocks held only by the prefix-sharing pool "
            "(evicted on demand)",
        )
        self.kv_overcommit = Gauge(
            "localai_kv_overcommit_ratio",
            "Paged-KV pool size as a ratio of the contiguous-footprint "
            "default (LOCALAI_KV_OVERCOMMIT; <1 overcommits HBM, >1 "
            "grows the prefix-sharing pool)",
        )
        self.prefill_chunk_queue = Gauge(
            "localai_prefill_chunk_queue_depth",
            "Prompt chunks queued behind the chunked-prefill lane "
            "(dispatched one per engine iteration, interleaved with decode)",
        )
        self.prefill_chunks = Counter(
            "localai_prefill_chunks_total",
            "Chunked-prefill dispatches issued by the engine thread",
        )
        self.decode_dispatches = Counter(
            "localai_decode_dispatches_total",
            "Compiled decode programs dispatched by the engine thread",
        )
        self.prompt_cache_hits = Counter(
            "localai_prompt_cache_hits_total",
            "Disk prompt-KV cache lookups that returned a usable prefix",
        )
        self.prompt_cache_misses = Counter(
            "localai_prompt_cache_misses_total",
            "Disk prompt-KV cache lookups with no usable prefix",
        )
        self.prompt_cache_hit_rate = Gauge(
            "localai_prompt_cache_hit_rate",
            "hits / (hits + misses) of the disk prompt-KV cache",
        )
        self.prefix_reused = Counter(
            "localai_prefix_tokens_reused_total",
            "Prompt tokens served from reused KV prefixes instead of prefill",
        )
        self.spec_accept_rate = Gauge(
            "localai_speculative_accept_rate",
            "Emitted tokens per active slot-window over the gamma+1 ceiling",
        )
        self.spec_windows = Counter(
            "localai_speculative_windows_total",
            "Speculative draft+verify windows dispatched",
        )
        self.spec_accept_ratio = Gauge(
            "localai_spec_accept_rate",
            "Draft tokens accepted / proposed (lifetime ratio)",
        )
        self.spec_draft_tokens = Counter(
            "localai_spec_draft_tokens_total",
            "Draft tokens proposed to the speculative verify dispatch",
        )
        self.spec_accepted_tokens = Counter(
            "localai_spec_accepted_tokens_total",
            "Draft tokens accepted by the target's accept/sample scan",
        )
        self.spec_tokens_per_dispatch = Gauge(
            "localai_spec_tokens_per_dispatch",
            "Mean emitted tokens per active slot-window (>1 = the "
            "verify-k dispatch beats single-step decode)",
        )
        self.compile_count = Counter(
            "localai_xla_compile_total",
            "XLA program compilations observed (first dispatch per shape)",
        )
        self.compile_seconds = Counter(
            "localai_xla_compile_seconds_total",
            "Wall seconds spent tracing+compiling XLA programs",
        )
        # -- flight recorder + SLO observatory (obs.flight / obs.slo) -----
        self.step_time_ms = Gauge(
            "localai_step_time_ms",
            "Per-token decode step time over the flight ring's resident "
            "dispatches — the last N, not a time window, so an idle "
            "engine reports its most recent activity (quantile label: "
            "p50/p99)",
        )
        self.dispatch_phase_ms = Gauge(
            "localai_dispatch_phase_ms",
            "Dispatch-anatomy phase time over the flight ring's recent "
            "window, compile rows excluded (phase label: gap/sched/"
            "launch/sync, quantile label: p50/p90/p99 — see obs.anatomy "
            "for phase semantics)",
        )
        self.host_overhead_fraction = Gauge(
            "localai_host_overhead_fraction",
            "Share of windowed dispatch wall time the host spent NOT "
            "blocked on the device (gap+sched+launch over dispatch "
            "wall) — the number fused multi-step dispatch must drive down",
        )
        self.device_bubble_fraction = Gauge(
            "localai_device_bubble_fraction",
            "Estimated share of windowed dispatch wall time the device "
            "sat idle: host phases not covered by a later result-fetch "
            "block (estimator — see obs.anatomy caveats)",
        )
        self.slo_burn_rate = Gauge(
            "localai_slo_burn_rate",
            "Error-budget burn rate per model and window "
            "(1.0 = burning exactly the error budget)",
        )
        self.overload_shedding = Gauge(
            "localai_overload_shedding",
            "1 while new generation work for the model is refused (429) "
            "by SLO burn-rate admission control",
        )
        self.requests_shed = Counter(
            "localai_requests_shed_total",
            "Generation requests refused with 429 by SLO burn-rate "
            "admission control",
        )
        # -- offline batch subsystem (localai_tpu.batch) -------------------
        self.batch_jobs = Gauge(
            "localai_batch_jobs",
            "Batch jobs by lifecycle state "
            "(validating/in_progress/completed/failed/cancelled/expired)",
        )
        self.batch_lines = Counter(
            "localai_batch_lines_total",
            "Batch input lines drained by result (completed/failed)",
        )
        self.batch_lane_paused = Gauge(
            "localai_batch_lane_paused",
            "1 while the background batch lane is paused because the SLO "
            "observatory reports overload shedding (in-flight lines are "
            "requeued, never failed)",
        )
        self.batch_queue_depth = Gauge(
            "localai_batch_queue_depth",
            "Requests waiting in the scheduler's background batch lane",
        )
        # -- fleet router (localai_tpu.fleet) ------------------------------
        self.fleet_replicas = Gauge(
            "localai_fleet_replicas",
            "Engine replicas per model by lifecycle state "
            "(starting/healthy/dead/respawning)",
        )
        self.fleet_routed = Counter(
            "localai_fleet_routed_total",
            "Requests placed by the fleet router by reason "
            "(affinity/directory/least_loaded/failover/queue_override)",
        )
        self.fleet_prefix_transfers = Counter(
            "localai_fleet_prefix_transfers_total",
            "Disaggregated prefill→decode KV-prefix handoffs completed",
        )
        self.fleet_prefix_transfer_bytes = Counter(
            "localai_fleet_prefix_transfer_bytes_total",
            "Packed KV-prefix bytes streamed between replicas over "
            "TransferPrefix",
        )
        # -- fleet KV economy (fleet.kveconomy) ----------------------------
        self.fleet_directory_entries = Gauge(
            "localai_fleet_directory_entries",
            "Prefix keys tracked by the fleet prefix directory "
            "(which replica holds which prefix blocks)",
        )
        self.fleet_directory_hits = Counter(
            "localai_fleet_directory_hits_total",
            "Routing probes the prefix directory answered with a live "
            "holder (request placed on known-warm KV)",
        )
        self.fleet_directory_misses = Counter(
            "localai_fleet_directory_misses_total",
            "Routing probes the prefix directory could not answer "
            "(unknown key or no eligible holder — ring heuristic decides)",
        )
        self.fleet_directory_drops = Counter(
            "localai_fleet_directory_drops_total",
            "Directory entries invalidated: stale holders dropped after "
            "a failed fetch + whole-replica invalidations on death",
        )
        self.fleet_sibling_transfers = Counter(
            "localai_fleet_sibling_transfers_total",
            "Directory-driven sibling KV-prefix fetches completed "
            "(prefix pulled over TransferPrefix instead of re-prefilled)",
        )
        self.fleet_sibling_transfer_bytes = Counter(
            "localai_fleet_sibling_transfer_bytes_total",
            "Packed KV bytes moved by sibling prefix fetches",
        )
        self.fleet_sibling_fallbacks = Counter(
            "localai_fleet_sibling_fallbacks_total",
            "Sibling fetches that failed (stale directory entry / dying "
            "donor) and fell back to a plain local prefill",
        )
        self.fleet_migrations = Counter(
            "localai_fleet_migrations_total",
            "Live in-flight slot migrations completed (request resumed "
            "on the destination replica mid-generation)",
        )
        self.fleet_migration_fallbacks = Counter(
            "localai_fleet_migration_fallbacks_total",
            "Live migrations that could not complete and fell back "
            "(full re-prefill re-dispatch, or error if already streamed)",
        )
        self.kv_tier_blocks = Gauge(
            "localai_kv_tier_blocks",
            "Cold prefix blocks currently resident in the host-RAM KV "
            "tier (spilled out of HBM)",
        )
        self.kv_tier_bytes = Gauge(
            "localai_kv_tier_bytes",
            "Host-RAM bytes held by the KV tier (bounded by "
            "LOCALAI_KV_TIER_MB)",
        )
        self.kv_tier_spills = Counter(
            "localai_kv_tier_spills_total",
            "Prefix blocks spilled HBM→host RAM at eviction instead of "
            "being discarded",
        )
        self.kv_tier_reloads = Counter(
            "localai_kv_tier_reloads_total",
            "Spilled prefix blocks re-onboarded host RAM→HBM on a "
            "prefix-match hit (a prefill saved by the tier)",
        )
        self.fleet_respawn_backoff = Gauge(
            "localai_fleet_respawn_backoff_s",
            "Current jittered-exponential respawn hold per dead replica "
            "(0 after a successful rejoin)",
        )
        # cross-host fleet (remote replica adoption + network faults):
        # remotes are evicted-with-redial, never respawned — this process
        # does not own a peer's lifecycle
        self.fleet_adoptions = Counter(
            "localai_fleet_adoptions_total",
            "Remote replicas adopted into a fleet pool (static "
            "LOCALAI_FLEET_HOSTS entries + federation-registry joins)",
        )
        self.fleet_evictions = Counter(
            "localai_fleet_evictions_total",
            "Remote replicas evicted from routing after consecutive "
            "failed health dials (partition / refused / flapping peer)",
        )
        self.fleet_redials = Counter(
            "localai_fleet_redials_total",
            "Evicted remote replicas successfully redialed back into "
            "the routing ring",
        )
        self.fleet_redial_backoff = Gauge(
            "localai_fleet_redial_backoff_s",
            "Current jittered-exponential redial hold per evicted remote "
            "replica (0 after a successful rejoin)",
        )
        self.fleet_rpc_retries = Counter(
            "localai_fleet_rpc_retries_total",
            "Bounded jittered retries of idempotent cross-host fleet "
            "RPCs, by rpc name (fleet.net.call_with_retries)",
        )
        self.fleet_rpc_deadlines = Counter(
            "localai_fleet_rpc_deadline_exceeded_total",
            "Cross-host fleet RPCs (dispatch/prefill stream inactivity "
            "or control-plane calls) that blew "
            "LOCALAI_FLEET_RPC_TIMEOUT_S",
        )
        # -- elastic capacity (fleet.autoscale) ----------------------------
        self.autoscale_decisions = Counter(
            "localai_autoscale_decisions_total",
            "Autoscale policy decisions applied per model by action "
            "(scale_out/scale_in/scale_to_zero/cold_start/swap/none)",
        )
        self.fleet_target_replicas = Gauge(
            "localai_fleet_target_replicas",
            "Decode replica count the autoscale controller is steering "
            "the fleet toward (0 while scaled to zero)",
        )
        self.model_swaps = Counter(
            "localai_model_swaps_total",
            "Hot weight swaps completed (fresh replicas booted on the "
            "new checkpoint, traffic shifted, old replicas drained)",
        )
        # -- fault injection + self-healing (localai_tpu.faults) -----------
        self.faults_injected = Counter(
            "localai_faults_injected_total",
            "Deterministic faults fired by injection site "
            "(LOCALAI_FAULT_* / POST /debug/faults — 0 in production)",
        )
        self.nan_rows = Counter(
            "localai_nan_rows_total",
            "Decode logits rows caught non-finite by the per-row NaN/inf "
            "guard (the affected request fails `error`, its slot is "
            "quarantined; co-batched requests keep streaming)",
        )
        self.quarantined_slots = Gauge(
            "localai_quarantined_slots",
            "Decode slots currently held out of admission by the NaN "
            "quarantine",
        )
        self.engine_rebuilds = Counter(
            "localai_engine_rebuilds_total",
            "Self-healing engine rebuilds completed (stall → drain → "
            "runner re-init → probe dispatch → engine thread restart)",
        )
        self.engine_failed = Gauge(
            "localai_engine_failed",
            "1 after the supervisor exhausted its bounded rebuild budget "
            "and marked the model failed (submits fail fast)",
        )
        self.autotune_lookups = Counter(
            "localai_autotune_lookups_total",
            "Per-shape kernel tuning-table lookups (ops.tuning) by "
            "result=hit|miss — a fleet whose table stopped matching its "
            "serving shapes shows an all-miss ratio here",
        )
        self.autotune_entries = Gauge(
            "localai_autotune_table_entries",
            "Entries in the loaded kernel tuning table "
            "(LOCALAI_TUNE_CACHE; 0 = defaults everywhere)",
        )
        self.autotune_sweep_seconds = Gauge(
            "localai_autotune_sweep_seconds",
            "Wall seconds of the last tools/autotune.py sweep per shape "
            "key",
        )
        self.paged_kernel_impl = Gauge(
            "localai_paged_kernel_impl",
            "1 for the paged decode attention implementation each engine "
            "selected (impl=pallas|lax) — a silent fallback off the "
            "Pallas kernel flips the labeled series",
        )
        self.kv_invariant_violations = Counter(
            "localai_kv_invariant_violations_total",
            "BlockAllocator.check_invariants violations observed at "
            "scheduler drains (LOCALAI_KV_CHECK=1) — any nonzero value "
            "is a block leak",
        )
        # -- fleet telemetry plane + anomaly profiler (obs.fleetview /
        # obs.profiler) ---------------------------------------------------
        self.trace_ring_size = Gauge(
            "localai_trace_ring_size",
            "Finished-trace ring capacity per trace kind "
            "(LOCALAI_TRACE_CAPACITY; default 256)",
        )
        self.profiles_captured = Counter(
            "localai_profiles_captured_total",
            "Anomaly-triggered jax.profiler captures by trigger "
            "(stall/slo_shed/step_p99_regression) — each one is listed "
            "with its triggering trace id at GET /debug/profiles",
        )
        # -- stall forensics + device health (obs.watchdog / obs.device) --
        self.engine_stalled = Gauge(
            "localai_engine_stalled",
            "1 while a guarded device round-trip has made no progress past "
            "the watchdog deadline (per channel)",
        )
        self.last_progress_age = Gauge(
            "localai_last_progress_age_seconds",
            "Seconds since the last heartbeat on an armed watchdog channel",
        )
        self.stalls = Counter(
            "localai_stalls_total",
            "Watchdog trips (stack-dump forensic spans recorded)",
        )
        self.device_ok = Gauge(
            "localai_device_ok",
            "1 when the last timeout-guarded device liveness probe succeeded",
        )
        self.device_probe_seconds = Gauge(
            "localai_device_probe_seconds",
            "Round-trip wall seconds of the last device liveness probe",
        )
        self.hbm_bytes_in_use = Gauge(
            "localai_hbm_bytes_in_use",
            "Device memory in use per device (memory_stats)",
        )
        self.hbm_peak_bytes = Gauge(
            "localai_hbm_peak_bytes_in_use",
            "Peak device memory in use per device (memory_stats)",
        )
        self.hbm_bytes_limit = Gauge(
            "localai_hbm_bytes_limit",
            "Device memory capacity per device (memory_stats)",
        )
        self.hbm_live_bytes = Gauge(
            "localai_hbm_live_bytes",
            "Live jax array bytes by category (kv_cache/weights/other)",
        )
        # -- usage accounting plane (obs.ledger) ---------------------------
        # tenant labels are ALWAYS derive_tenant() outputs (hashed key /
        # anonymous / overflow) — never a raw API key; cardinality is
        # bounded by the ledger's tenant LRU
        self.tenant_requests = Counter(
            "localai_tenant_requests_total",
            "Finished generation requests per (tenant, model, lane) "
            "ledger pane (tenant = hashed API key bucket)",
        )
        self.tenant_tokens = Counter(
            "localai_tenant_tokens_total",
            "Delivered (goodput) completion tokens per tenant pane",
        )
        self.tenant_prompt_tokens = Counter(
            "localai_tenant_prompt_tokens_total",
            "Prompt tokens processed per tenant pane",
        )
        self.tenant_dispatch_ms = Counter(
            "localai_tenant_dispatch_ms_total",
            "Engine-resident service milliseconds (submit→done minus "
            "queue wait) attributed per tenant pane",
        )
        self.tenant_queue_wait_ms = Counter(
            "localai_tenant_queue_wait_ms_total",
            "Milliseconds requests waited for a decode slot per tenant "
            "pane",
        )
        self.tenant_kv_block_seconds = Counter(
            "localai_tenant_kv_block_seconds_total",
            "Paged-KV memory cost per tenant pane: context blocks × "
            "slot-resident seconds (PagedAttention block-seconds)",
        )
        self.tenant_lru_evictions = Counter(
            "localai_tenant_lru_evictions_total",
            "Tenant panes folded into the `overflow` bucket when the "
            "ledger's LRU exceeded LOCALAI_TENANT_MAX",
        )
        self.goodput_tokens = Counter(
            "localai_goodput_tokens_total",
            "Tokens delivered by naturally finished requests "
            "(stop/length) per model — the goodput side of the "
            "decomposition",
        )
        self.goodput_ratio = Gauge(
            "localai_goodput_ratio",
            "delivered / (delivered + waste) tokens per model (1.0 with "
            "no recorded waste)",
        )
        self.waste_tokens = Counter(
            "localai_waste_tokens_total",
            "Wasted work in tokens per model by reason (spec_rejected/"
            "failover_reprefill/migration_reprefill/cancelled/error/"
            "nan_quarantine — reprefill classes count prompt tokens)",
        )
        self.waste_requests = Counter(
            "localai_waste_requests_total",
            "Requests whose work was (partly) wasted, per model by "
            "reason (shed counts refused admissions)",
        )

    def _all(self) -> list:
        return [v for v in self.__dict__.values()
                if isinstance(v, (Histogram, Counter))]

    def render(self) -> str:
        return "\n".join(m.render() for m in self._all()) + "\n"


def update_engine_gauges(name: str, m: dict,
                         registry: Optional[Registry] = None) -> None:
    """Refresh the point-in-time engine series for one model from its
    scheduler's ``metrics()`` dict. Called at /metrics scrape time (and by
    the CI smoke) — counters are synced with ``set_total`` (monotone),
    gauges overwritten. Tolerates worker-tier dicts that miss keys."""
    reg = registry or REGISTRY
    if "error" in m and len(m) == 1:
        return  # unreachable worker: leave the last good values standing
    active = m.get("active_slots") or []
    reg.tokens_prompt.set_total(m.get("total_prompt_tokens", 0), model=name)
    reg.tokens_generated.set_total(
        m.get("total_generated_tokens", 0), model=name
    )
    reg.active_slots.set(len(active), model=name)
    # the scheduler's definition is authoritative; recompute only for
    # worker-tier dicts predating the field. NOTE: preemptions are NOT
    # synced here — EngineTelemetry.finished() is that family's sole
    # writer (a second set_total path would double-count on aggregation).
    occupancy = m.get("occupancy")
    if occupancy is None and m.get("num_slots"):
        occupancy = len(active) / m["num_slots"]
    if occupancy is not None:
        reg.batch_occupancy.set(occupancy, model=name)
    reg.queue_depth.set(m.get("queue_depth", 0), model=name)
    if "batch_queue_depth" in m:
        reg.batch_queue_depth.set(m["batch_queue_depth"], model=name)
    if "kv_utilization" in m:
        reg.kv_utilization.set(m["kv_utilization"], model=name)
    if "kv_blocks_total" in m:  # paged KV engines only
        reg.kv_blocks_free.set(m.get("kv_blocks_free", 0), model=name)
        reg.kv_blocks_used.set(m.get("kv_blocks_used", 0), model=name)
        reg.kv_blocks_cached.set(m.get("kv_blocks_cached", 0), model=name)
        reg.kv_overcommit.set(
            m.get("kv_overcommit_ratio", 1.0), model=name)
        reg.prefill_chunk_queue.set(
            m.get("prefill_chunk_queue_depth", 0), model=name)
        reg.prefill_chunks.set_total(m.get("prefill_chunks", 0), model=name)
        impl = m.get("paged_attn_impl")
        if impl:
            # one-hot over the impl label so a kernel→fallback flip is a
            # visible series transition, not a silent value change
            for label in ("pallas", "lax"):
                reg.paged_kernel_impl.set(
                    1.0 if impl == label else 0.0, model=name, impl=label)
    if "kv_tier_spills" in m:
        # host-RAM tier attached (single engine OR the fleet roll-up —
        # the latter carries the tier sums without the kv_blocks pane)
        reg.kv_tier_blocks.set(m.get("kv_tier_blocks", 0), model=name)
        reg.kv_tier_bytes.set(m.get("kv_tier_bytes", 0), model=name)
        reg.kv_tier_spills.set_total(m.get("kv_tier_spills", 0), model=name)
        reg.kv_tier_reloads.set_total(
            m.get("kv_tier_reloads", 0), model=name)
    reg.decode_dispatches.set_total(m.get("dispatches", 0), model=name)
    if m.get("shed_total"):
        # shed admissions are whole-request waste (no tokens were ever
        # generated); the requests_shed family stays owned by obs.slo —
        # this is the decomposition's view of the same monotone count
        reg.waste_requests.set_total(m["shed_total"], model=name,
                                     reason="shed")
    if "quarantined_slots" in m:
        # point-in-time NaN-quarantine census; the nan_rows/rebuilds
        # counter families are event-time (scheduler/supervisor are their
        # sole writers) and deliberately NOT synced here
        reg.quarantined_slots.set(m["quarantined_slots"], model=name)
    reg.prefix_reused.set_total(m.get("prefix_tokens_reused", 0), model=name)
    pc = m.get("prompt_cache")
    if pc:
        hits, misses = pc.get("hits", 0), pc.get("misses", 0)
        reg.prompt_cache_hits.set_total(hits, model=name)
        reg.prompt_cache_misses.set_total(misses, model=name)
        if hits + misses:
            reg.prompt_cache_hit_rate.set(hits / (hits + misses), model=name)
    if "spec_acceptance_rate" in m:
        reg.spec_accept_rate.set(m["spec_acceptance_rate"], model=name)
        reg.spec_windows.set_total(m.get("spec_windows", 0), model=name)
        reg.spec_accept_ratio.set(
            m.get("spec_accept_rate", 0.0), model=name)
        reg.spec_draft_tokens.set_total(
            m.get("spec_draft_tokens", 0), model=name)
        reg.spec_accepted_tokens.set_total(
            m.get("spec_accepted_tokens", 0), model=name)
        reg.spec_tokens_per_dispatch.set(
            m.get("spec_tokens_per_dispatch", 0.0), model=name)
        # waste decomposition (obs.ledger): rejected draft tokens are
        # device work the flight ring never counted. Synced here (not
        # only via LEDGER.export) so worker/fleet tiers — whose ledgers
        # live in other processes — still land in the roll-up; set_total
        # max-merges, so the dual writers cannot double-count.
        rejected = (m.get("spec_draft_tokens", 0)
                    - m.get("spec_accepted_tokens", 0))
        if rejected > 0:
            reg.waste_tokens.set_total(rejected, model=name,
                                       reason="spec_rejected")
    # windowed step-time percentiles from the flight ring (the EMA's
    # windowed counterpart; absent until a post-compile dispatch lands)
    for q in ("p50", "p99"):
        v = m.get(f"step_ms_{q}")
        if v is not None:
            reg.step_time_ms.set(v, model=name, quantile=q)
    # dispatch anatomy (obs.anatomy): windowed phase percentiles + the
    # derived host/bubble fractions; absent keys (old-version payloads,
    # empty windows) simply leave the gauges untouched
    for ph, qs in (m.get("dispatch_phase_ms") or {}).items():
        for q, v in qs.items():
            if v is not None:
                reg.dispatch_phase_ms.set(v, model=name, phase=ph,
                                          quantile=q)
    v = m.get("host_overhead_fraction")
    if v is not None:
        reg.host_overhead_fraction.set(v, model=name)
    v = m.get("device_bubble_fraction")
    if v is not None:
        reg.device_bubble_fraction.set(v, model=name)


REGISTRY = Registry()
