"""Lock-protected span recorder with a bounded ring-buffer trace store.

Design constraints (they shape everything here):

  * **Zero device syncs.** Every timestamp is ``time.monotonic()`` taken on
    the host; no code path ever touches a jax array, so recording a span
    from the engine thread costs a dict append under a lock — it cannot
    stall a dispatch or force a D2H copy (jaxlint-clean by construction).
  * **Bounded memory.** Finished traces land in a ``deque(maxlen=...)``
    ring; a trace that never finishes (a leaked handle) is still visible
    via the active table until it does.
  * **Monotonic for math, wall clock for display.** Durations are computed
    from the monotonic timeline; ``start_unix`` in the JSON view is derived
    through one wall/monotonic anchor pair captured at import.

The unit is a :class:`RequestTrace` — one trace id, one request id, a flat
list of phase spans rendered as a single-root span tree (request phases are
sequential, so the tree is root + children). The HTTP middleware records
one-span ``kind="http"`` traces into the same store, so
``/debug/timeline/{id}`` can merge the API view and the engine view of the
same trace id.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Optional

# one anchor pair: monotonic drives all math, this converts for display
_WALL0 = time.time()
_MONO0 = time.monotonic()


def mono_to_wall(t: float) -> float:
    return _WALL0 + (t - _MONO0)


def new_trace_id() -> str:
    return "trace-" + uuid.uuid4().hex[:24]


class Span:
    """One named phase: [t0, t1] on the monotonic clock + attributes.
    ``t1 is None`` means still open; ``t1 == t0`` is a point event."""

    __slots__ = ("name", "t0", "t1", "attrs")

    def __init__(self, name: str, t0: float, t1: Optional[float] = None,
                 attrs: Optional[dict] = None):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs or {}

    @property
    def duration(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "name": self.name,
            "start_unix": round(mono_to_wall(self.t0), 6),
        }
        d["duration_ms"] = (None if self.t1 is None
                            else round((self.t1 - self.t0) * 1e3, 3))
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class RequestTrace:
    """Span recorder for one request. Append-only and lock-protected: the
    submitting thread, the engine thread, and an SSE writer may all touch
    the same trace."""

    def __init__(self, trace_id: str, request_id: str, *, kind: str = "request",
                 model: str = "", **attrs: Any):
        self.trace_id = trace_id
        self.request_id = request_id
        self.kind = kind
        self.model = model
        self.attrs: dict[str, Any] = dict(attrs)
        self.t0 = time.monotonic()
        self.t1: Optional[float] = None
        self.finished = False
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self._open: dict[str, Span] = {}

    # -- recording -------------------------------------------------------

    def begin(self, name: str, **attrs: Any) -> Span:
        span = Span(name, time.monotonic(), attrs=attrs)
        with self._lock:
            self._spans.append(span)
            self._open[name] = span
        return span

    def end(self, name: str, **attrs: Any) -> Optional[Span]:
        """Close the open span ``name`` (no-op when it was never begun —
        lifecycle paths diverge: a cancelled-in-queue request has no
        prefill/decode spans to close)."""
        now = time.monotonic()
        with self._lock:
            span = self._open.pop(name, None)
            if span is None:
                return None
            span.t1 = now
            span.attrs.update(attrs)
        return span

    def event(self, name: str, **attrs: Any) -> Span:
        """Point-in-time marker (t1 == t0)."""
        now = time.monotonic()
        span = Span(name, now, now, attrs=attrs)
        with self._lock:
            self._spans.append(span)
        return span

    def annotate(self, **attrs: Any) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def close_open(self) -> None:
        """Close every still-open span (finish on an abnormal path)."""
        now = time.monotonic()
        with self._lock:
            for span in self._open.values():
                span.t1 = now
            self._open.clear()

    # -- views -----------------------------------------------------------

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def to_dict(self) -> dict:
        """The span tree: one root (the request) + phase children."""
        with self._lock:
            attrs = dict(self.attrs)
            children = [s.to_dict() for s in self._spans]
        end = self.t1 if self.t1 is not None else (
            time.monotonic() if not self.finished else self.t0
        )
        return {
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "kind": self.kind,
            "model": self.model,
            "name": self.kind,
            "start_unix": round(mono_to_wall(self.t0), 6),
            "duration_ms": round((end - self.t0) * 1e3, 3),
            "finished": self.finished,
            "attrs": attrs,
            "children": children,
        }


def default_capacity() -> int:
    """Per-kind finished-trace ring size: ``LOCALAI_TRACE_CAPACITY``,
    default 256. Each trace kind (request/http/stall/batch) gets its own
    ring of this size; sizing up trades host RAM for a longer forensic
    horizon (a busy fleet front door can blow through 256 request traces
    in seconds). Exported as ``localai_trace_ring_size`` so a dashboard
    can tell 'trace evicted' from 'trace never recorded'."""
    try:
        return max(1, int(os.environ.get("LOCALAI_TRACE_CAPACITY", "")
                          or 256))
    except ValueError:
        return 256


class TraceStore:
    """Active table + bounded rings of finished traces, one ring per
    trace kind — high-volume HTTP spans (scrapes, probes) must not evict
    the engine request traces the subsystem exists to retain."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = (capacity if capacity is not None
                         else default_capacity())
        self._lock = threading.Lock()
        self._active: dict[int, RequestTrace] = {}
        self._done: dict[str, deque[RequestTrace]] = {}

    def _ring(self, kind: str) -> "deque[RequestTrace]":
        ring = self._done.get(kind)
        if ring is None:
            ring = self._done[kind] = deque(maxlen=self.capacity)
        return ring

    def start(self, trace: RequestTrace) -> RequestTrace:
        with self._lock:
            self._active[id(trace)] = trace
        return trace

    def finish(self, trace: RequestTrace) -> None:
        trace.close_open()
        trace.t1 = time.monotonic()
        trace.finished = True
        with self._lock:
            self._active.pop(id(trace), None)
            self._ring(trace.kind).append(trace)

    def record(self, trace: RequestTrace) -> None:
        """One-shot insert of an already-complete trace (HTTP spans)."""
        if trace.t1 is None:
            trace.t1 = time.monotonic()
        trace.finished = True
        with self._lock:
            self._ring(trace.kind).append(trace)

    def recent(self, limit: int = 50,
               kind: Optional[str] = None) -> list[RequestTrace]:
        """Newest-first: in-flight traces, then finished ones."""
        with self._lock:
            active = sorted(self._active.values(), key=lambda t: -t.t0)
            done = [t for ring in self._done.values() for t in ring]
        done.sort(key=lambda t: -t.t0)
        out = [t for t in active + done if kind is None or t.kind == kind]
        return out[:limit]

    def find(self, ident: str) -> list[RequestTrace]:
        """Every trace whose trace id OR request id matches, oldest first
        (the /debug/timeline lookup — one trace id may cover the HTTP span
        plus several engine requests for n>1 fan-out)."""
        with self._lock:
            pool = list(self._active.values()) + [
                t for ring in self._done.values() for t in ring
            ]
        hits = [t for t in pool
                if t.trace_id == ident or t.request_id == ident]
        hits.sort(key=lambda t: t.t0)
        return hits


STORE = TraceStore()
