"""Structured JSON logging with request trace-id binding.

The text log format stays the default (humans at a terminal); ``--log-format
json`` (env ``LOCALAI_LOG_FORMAT=json``) switches every line to one JSON
object so a collector can join server logs with the trace store and
/metrics on the ``trace_id`` field.

The trace id travels on a :mod:`contextvars` ContextVar: the API's
trace middleware binds the per-request id for the duration of the handler
(contextvars propagate through ``await``, so concurrent requests cannot
bleed ids into each other's log lines), and any ``log.*`` call made from
that context — handler code, model manager, scheduler submit path — carries
it automatically. Engine-thread log lines have no request context and
simply omit the field.

No jax imports here; safe to configure before the backend initializes.
"""

from __future__ import annotations

import contextvars
import io
import json
import logging
import sys
import time
import traceback
from typing import Optional

_TRACE_ID: contextvars.ContextVar[str] = contextvars.ContextVar(
    "localai_trace_id", default="")

# logging.LogRecord attributes that are plumbing, not payload — anything
# else passed via ``extra=`` lands in the JSON line
_RECORD_FIELDS = frozenset(vars(
    logging.LogRecord("", 0, "", 0, "", (), None)
)) | {"message", "asctime", "taskName"}


def bind_trace_id(trace_id: str) -> contextvars.Token:
    """Bind the current context's trace id; returns the reset token."""
    return _TRACE_ID.set(trace_id)


def unbind_trace_id(token: contextvars.Token) -> None:
    _TRACE_ID.reset(token)


def current_trace_id() -> str:
    return _TRACE_ID.get()


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, message, trace_id (when
    bound), exc (when raised), plus any ``extra=`` fields."""

    def format(self, record: logging.LogRecord) -> str:
        out: dict = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
                  + f".{int(record.msecs):03d}Z",
            "level": record.levelname.lower(),
            "logger": record.name,
            "message": record.getMessage(),
        }
        tid = _TRACE_ID.get()
        if tid:
            out["trace_id"] = tid
        if record.exc_info:
            buf = io.StringIO()
            traceback.print_exception(*record.exc_info, file=buf)
            out["exc"] = buf.getvalue()
        for key, value in record.__dict__.items():
            if key not in _RECORD_FIELDS and not key.startswith("_"):
                out[key] = value
        return json.dumps(out, default=str)


def setup(fmt: str = "text", level: int = logging.INFO,
          stream: Optional[object] = None) -> None:
    """Configure the root logger. ``fmt='json'`` installs the structured
    formatter; ``'text'`` keeps the classic single-line format."""
    handler = logging.StreamHandler(stream or sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)
