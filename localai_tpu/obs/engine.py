"""EngineTelemetry: the scheduler-facing facade of the obs subsystem.

Turns request lifecycle events into spans (queued → prefill → decode, with
``admitted``/``drained`` markers) and per-request histogram observations
(queue wait, TTFT, TPOT). Everything runs on host timestamps the scheduler
already holds — no device reads, no ``.item()``, nothing the jaxlint
host-sync rule could flag.

One instance per Scheduler (the in-process manager names it after the
model; the worker tier builds its own inside the worker process, keyed to
the trace id propagated over the RPC boundary)."""

from __future__ import annotations

import time
from typing import Any, Optional

from localai_tpu.obs import compile as obs_compile
from localai_tpu.obs import ledger as obs_ledger
from localai_tpu.obs import slo as obs_slo
from localai_tpu.obs.metrics import REGISTRY, Registry
from localai_tpu.obs.trace import STORE, RequestTrace, TraceStore

# finish reasons that mean the request left its slot early
PREEMPT_REASONS = ("cancelled", "error")
# finish reasons the SLO observatory counts: natural completions plus
# backend errors (a cancel is a client action, not a serving outcome;
# shed requests never reach a slot at all)
SLO_REASONS = ("stop", "length", "error")


class EngineTelemetry:
    def __init__(self, model: str = "", *,
                 registry: Optional[Registry] = None,
                 store: Optional[TraceStore] = None,
                 slo: Optional[obs_slo.SLOTracker] = None):
        self.model = model
        self.registry = registry or REGISTRY
        self.store = store or STORE
        self.slo = slo or obs_slo.SLO
        # PagedAttention block size for the ledger's KV-block-seconds
        # cost unit; the scheduler overwrites it from its runner when a
        # paged allocator is attached (16 is the paged default)
        self.kv_block_tokens = 16
        # supplement the first-dispatch compile timing the runner records
        obs_compile.install(self.registry)

    # -- request lifecycle ------------------------------------------------

    def queued(self, handle: Any) -> RequestTrace:
        """Called at submit(); returns the trace the scheduler attaches to
        the handle."""
        req = handle.request
        tid = (getattr(req, "trace_id", "") or req.correlation_id
               or f"req-{self.model or 'engine'}-{handle.id}")
        tr = RequestTrace(
            tid, f"{self.model or 'engine'}-{handle.id}", model=self.model,
            prompt_tokens=handle.prompt_tokens,
        )
        tr.begin("queued")
        self.store.start(tr)
        return tr

    def admitted(self, tr: Optional[RequestTrace], *, slot: int,
                 queue_wait: float, background: bool = False) -> None:
        """``background`` marks a batch-lane request: it waits in the
        queue BY DESIGN (only admitted when the interactive lane is
        empty), so its queue wait must not pollute the interactive
        latency histogram — traces still record it."""
        if tr is None:
            return
        tr.end("queued", seconds=round(queue_wait, 6))
        tr.event("admitted", slot=slot)
        tr.begin("prefill", slot=slot)
        # stashed for finished(): the SLO observatory wants queue wait on
        # the same completion event as the latency metrics
        tr.annotate(queue_wait_ms=round(queue_wait * 1e3, 3))
        if not background:
            self.registry.queue_wait.observe(queue_wait, model=self.model)

    def prefill_done(self, tr: Optional[RequestTrace], *, path: str = "",
                     prefix_reused: int = 0) -> None:
        if tr is None:
            return
        tr.end("prefill", path=path, prefix_reused=prefix_reused)
        tr.begin("decode")

    def finished(self, tr: Optional[RequestTrace], handle: Any,
                 reason: str, preempted: Optional[bool] = None) -> None:
        """Terminal event for every path: natural stop, length, cancel,
        admit failure, engine error. Derives TTFT/TPOT from the handle's
        host-side timing mirror and retires the trace.

        ``preempted`` marks a request that left a decode SLOT before
        natural completion; defaults from the reason, but a request
        cancelled while still queued passes False — queue abandonment is
        not slot churn.

        Background batch-lane requests (``request.priority > 0``) never
        become SLO events and stay out of the TTFT/TPOT histograms: a
        batch line queues behind ALL interactive work by design, so its
        latencies are meaningless against interactive targets — and
        counting them would let an offline job trip shedding of the
        interactive traffic the lane exists to protect (the executor
        would then pause on the shedding its own lines caused). Requests/
        preemptions counters and traces still record them."""
        if tr is None:
            return
        background = getattr(getattr(handle, "request", None),
                             "priority", 0) > 0
        n = handle.completion_tokens
        ttft = tpot = None
        if handle.t_first_token is not None:
            ttft = handle.t_first_token - handle.t_submit
            t_end = handle.t_done or time.monotonic()
            if n > 1:
                tpot = (t_end - handle.t_first_token) / (n - 1)
        tr.end("decode", tokens=n)
        tr.event("drained", finish_reason=reason)
        tr.annotate(
            finish_reason=reason,
            completion_tokens=n,
            ttft_ms=None if ttft is None else round(ttft * 1e3, 3),
            tpot_ms=None if tpot is None else round(tpot * 1e3, 3),
            tokens_per_second=round(handle.tokens_per_second, 3),
        )
        if ttft is not None and not background:
            self.registry.ttft.observe(ttft, model=self.model)
        if tpot is not None and not background:
            self.registry.tpot.observe(tpot, model=self.model)
        self.registry.requests.inc(model=self.model, finish_reason=reason)
        # sole writer of the preemptions family (the scheduler's
        # total_preemptions mirror feeds only the JSON metrics surface)
        if preempted is None:
            preempted = reason in PREEMPT_REASONS
        if preempted:
            self.registry.preemptions.inc(model=self.model, reason=reason)
        # usage accounting (obs.ledger): the single feed point every
        # scheduler tier shares. Gated on the request's tenant stamp —
        # "whoever stamped the tenant owns the feed": InProcessReplica
        # strips it before resubmitting to its shared-process inner
        # engine, so fleet requests are counted exactly once (by the
        # front door), and direct un-stamped submits stay unattributed.
        tenant = getattr(getattr(handle, "request", None), "tenant", "")
        if tenant:
            t_end = handle.t_done or time.monotonic()
            queue_wait_ms = tr.attrs.get("queue_wait_ms") or 0.0
            service_s = max(
                0.0, (t_end - handle.t_submit) - queue_wait_ms / 1e3)
            ledger_reason = reason
            if reason == "error" and getattr(handle, "nan_poisoned", False):
                ledger_reason = "nan_quarantine"
            obs_ledger.LEDGER.note_request(
                tenant=tenant,
                model=self.model or "engine",
                lane="batch" if background else "interactive",
                reason=ledger_reason,
                tokens=n,
                prompt_tokens=handle.prompt_tokens,
                dispatch_ms=service_s * 1e3,
                queue_wait_ms=queue_wait_ms,
                kv_block_s=obs_ledger.kv_block_seconds(
                    handle.prompt_tokens, n, service_s,
                    self.kv_block_tokens),
            )
        if reason in SLO_REASONS and not background:
            t_end = handle.t_done or time.monotonic()
            self.slo.observe(
                self.model or "engine",
                ttft_ms=None if ttft is None else ttft * 1e3,
                tpot_ms=None if tpot is None else tpot * 1e3,
                e2e_ms=(t_end - handle.t_submit) * 1e3,
                queue_ms=tr.attrs.get("queue_wait_ms"),
                error=(reason == "error"),
            )
        self.store.finish(tr)
