"""Dispatch anatomy: where each engine dispatch's wall time went.

The flight ring (:mod:`obs.flight`) records each dispatch as one
wall-clock blob. This module owns the VOCABULARY that splits that blob —
with zero added device syncs — into four phases, so metrics, the debug
API, fleet telemetry, and the bench harness all speak the same names:

======  ===========================================================
phase   meaning
======  ===========================================================
gap     idle since the previous dispatch retired: host scheduling /
        staging between drains (row processing, queue bookkeeping,
        waits) that no other phase claims
sched   admit / select / host-mirror work before entering the runner
launch  time for the jit call to return — JAX dispatch is async, so
        this is enqueue overhead only, not device compute
sync    time blocked at the EXISTING result fetch (``np.asarray`` /
        ``int(tok)``): device-bound time when the host arrived early
======  ===========================================================

Attribution model (interval tiling). Each record's phases decompose the
wall interval its ``dispatch_ms`` accounts for — for pipelined records
the inter-drain interval, for synchronous records the issue→drain span —
NOT the dispatch's own per-issue timeline. ``sched``/``launch`` are
accumulated host measurements since the previous record; ``sync`` is the
measured block at the drain; ``gap`` is everything the interval holds
that no measured phase claims (computed by exclusion). Consequences:

* ``gap + sched + launch + sync <= dispatch_ms`` holds structurally for
  every record (gap is clamped at 0, measured phases are clamped to the
  interval), and windowed phase totals tile the timeline without double
  counting.
* ``host_overhead_fraction`` = (gap+sched+launch) / dispatch wall — the
  share of accounted time the host spent NOT blocked on the device. This
  is the number ROADMAP's fused k-step dispatch must drive down.
* ``device_bubble_fraction`` is an ESTIMATOR, not a measurement: per
  record ``max(0, (gap+sched+launch) - sync)``. When the host later
  blocked ``sync`` ms, the device queue was covering at least that much
  host time (pipelining hid it — no bubble); host time the device never
  made the host pay for is presumed device idleness. It can under-count
  bubbles hidden by deep pipelines and over-count when the device
  finished mid-``sync``; trends and cross-phase comparisons are
  meaningful, single absolute samples are not.

Caveats worth restating wherever these numbers render: compile-bearing
rows are excluded (a single trace would drown every phase); ``launch``
can absorb device back-pressure (a full dispatch queue makes the async
call itself block); records written by sources that predate or skip
attribution carry all-zero phases and show up as ``unattributed``.
"""

from __future__ import annotations

from typing import Any, Optional

#: Phase column order — stable; UI stacked bars and bench lines rely on it.
PHASES = ("gap", "sched", "launch", "sync")

QUANTILES = ("p50", "p90", "p99")

#: One-line phase definitions, served with /debug/anatomy payloads.
PHASE_HELP = {
    "gap": ("idle since the previous dispatch retired — host scheduling/"
            "staging no measured phase claims (by exclusion)"),
    "sched": "admit/select/host-mirror work before entering the runner",
    "launch": "time for the async jit call to return (enqueue overhead)",
    "sync": "time blocked at the existing result fetch (device-bound)",
}

#: Window the scheduler/metrics plane summarizes over, matching the
#: step-time percentile window in Scheduler.metrics().
DEFAULT_WINDOW_S = 60.0


def summarize(flight: Any, window_s: Optional[float] = DEFAULT_WINDOW_S,
              now: Optional[float] = None) -> dict:
    """Windowed per-phase percentiles/totals + fractions for one ring."""
    return flight.phases(window_s=window_s, now=now)


def phase_quantiles(summary: dict) -> dict:
    """``{phase: {quantile: ms}}`` from a :func:`summarize` dict.

    Skips absent/None entries, so gauge feeding degrades cleanly on empty
    windows and on payloads from replicas that predate the phase columns.
    """
    out: dict = {}
    for ph in PHASES:
        qs = {}
        for q in QUANTILES:
            v = summary.get(f"{ph}_ms_{q}")
            if v is not None:
                qs[q] = float(v)
        if qs:
            out[ph] = qs
    return out


def breakdown(flight: Any, window_s: Optional[float] = DEFAULT_WINDOW_S,
              now: Optional[float] = None) -> dict:
    """``GET /debug/anatomy`` payload: summary + per-phase wall shares.

    Adds ``phase_share`` (each phase's fraction of the windowed dispatch
    wall), the ``unattributed`` remainder (records whose writers did not
    attribute phases — all-zero columns — land here, never silently in a
    phase), and the phase definitions for self-description.
    """
    s = summarize(flight, window_s=window_s, now=now)
    total = s.get("dispatch_ms_total") or 0.0
    attributed = 0.0
    shares: dict = {}
    for ph in PHASES:
        ms = s.get(f"{ph}_ms_total") or 0.0
        attributed += ms
        shares[ph] = round(ms / total, 4) if total > 0 else None
    unattr = max(0.0, total - attributed)
    s["phase_share"] = shares
    s["unattributed_ms_total"] = round(unattr, 3)
    s["unattributed_share"] = (round(unattr / total, 4)
                               if total > 0 else None)
    s["window_s"] = window_s
    s["definitions"] = dict(PHASE_HELP)
    return s
