"""Observability subsystem: tracing, telemetry, and introspection.

Cooperating pieces:

  * ``obs.metrics`` — the process-wide OpenMetrics registry (moved here
    from ``api.metrics``, which remains as a compatibility shim) extended
    with engine series: TTFT/TPOT/queue-wait histograms, batch occupancy,
    KV-slot utilization, prompt/prefix-cache hit rates, speculative accept
    rate, XLA compile count/seconds, stall + device-health gauges.
  * ``obs.trace`` — a lock-protected span recorder with a bounded
    ring-buffer trace store. All timestamps are ``time.monotonic()`` taken
    on the host; nothing here ever touches a device array, so
    instrumentation adds zero device syncs to the step loop.
  * ``obs.engine`` — ``EngineTelemetry``, the scheduler-facing facade that
    turns request lifecycle events (queued → admitted → prefill → decode →
    drained) into spans + histogram observations.
  * ``obs.watchdog`` — dispatch-heartbeat stall detection around every
    blocking device round-trip, with thread-stack forensic spans dumped
    into the trace store on a trip (``kind="stall"`` at ``/v1/traces``).
  * ``obs.device`` — timeout-guarded device liveness probe, per-device
    ``memory_stats()`` gauges, and a live-array HBM census (KV cache vs
    weights vs other) behind ``GET /debug/devices``.
  * ``obs.compile`` — XLA compile telemetry plus the compiled-program cost
    catalog (``cost_analysis``/``memory_analysis`` joined with measured
    dispatch latency into achieved-vs-roofline fractions) behind
    ``GET /debug/programs``.
  * ``obs.logging`` — structured JSON log formatter with the request
    trace id bound via contextvar by the API middleware.
  * ``obs.flight`` — the engine flight recorder: a lock-light fixed-size
    ring of per-dispatch records (step times, occupancy, queue depth, KV
    utilization, tokens, preemptions, spec acceptance) fed from the
    scheduler drain loop using host mirrors only, with windowed step-time
    percentiles (``GET /debug/flight``; snapshots ride every stall dump).
  * ``obs.slo`` — the SLO observatory: sliding-window TTFT/TPOT/e2e/
    queue-wait percentiles per model (1m/5m/30m), p95 targets from env/
    config, multi-window burn rates, and burn-rate admission control
    (429 + ``Retry-After`` with automatic recovery) behind
    ``GET /v1/slo`` and ``localai_overload_shedding``.
  * ``obs.fleetview`` — the fleet telemetry plane: per-replica
    GetTelemetry harvests (trace spans + flight ring + metrics) stitched
    into one skew-anchored waterfall per trace id
    (``GET /v1/traces/{id}``) and one merged fleet flight table
    (``GET /debug/fleet/flight``).
  * ``obs.profiler`` — anomaly-triggered ``jax.profiler`` capture:
    watchdog stalls, SLO shed onsets, and step-time p99 regressions fire
    a bounded, rate-limited, single-flight capture recorded in a manifest
    (``GET /debug/profiles``, ``localai_profiles_captured_total``).
  * ``obs.ledger`` — the per-tenant cost ledger + goodput/waste
    decomposition: every finished request attributes delivered tokens,
    dispatch milliseconds, queue wait and KV-block-seconds to a
    (tenant, model, lane) pane (tenant = hashed API key, LRU-bounded
    cardinality), and every dispatch's work splits into goodput vs named
    waste classes reconciled against the flight ring
    (``GET /v1/usage``, ``localai_tenant_*``/``localai_goodput_*``/
    ``localai_waste_*``).
  * ``obs.history`` — the multi-resolution metrics history: 1s/10s/5m
    downsampled rings for the key engine + usage series, snapshotted
    atomically under ``LOCALAI_HISTORY_DIR`` and re-onboarded at boot
    (``GET /debug/history/{series}``, the ``/usage`` UI pane).

HTTP surface: ``GET /v1/traces``, ``GET /debug/timeline/{request_id}``
(``api.traces``), ``GET /debug/devices``, ``GET /debug/programs``,
``GET /debug/stacks`` (``api.debug``), fed by the trace-id middleware in
``api.server``.
"""

from localai_tpu.obs.engine import EngineTelemetry
from localai_tpu.obs.flight import FlightRecorder
from localai_tpu.obs.history import HISTORY, History
from localai_tpu.obs.ledger import (
    LEDGER,
    TenantLedger,
    current_tenant,
    derive_tenant,
)
from localai_tpu.obs.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_label_value,
    update_engine_gauges,
)
from localai_tpu.obs.profiler import PROFILER, ProfileManager
from localai_tpu.obs.slo import SLO, SLOTracker
from localai_tpu.obs.trace import (
    STORE,
    RequestTrace,
    Span,
    TraceStore,
    new_trace_id,
)
from localai_tpu.obs.watchdog import WATCHDOG, StallEvent, Watchdog

__all__ = [
    "HISTORY",
    "LEDGER",
    "PROFILER",
    "REGISTRY",
    "SLO",
    "STORE",
    "WATCHDOG",
    "Counter",
    "EngineTelemetry",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "History",
    "ProfileManager",
    "Registry",
    "RequestTrace",
    "SLOTracker",
    "Span",
    "StallEvent",
    "TenantLedger",
    "TraceStore",
    "Watchdog",
    "current_tenant",
    "derive_tenant",
    "escape_label_value",
    "new_trace_id",
    "update_engine_gauges",
]
