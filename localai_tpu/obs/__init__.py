"""Observability subsystem: request tracing + engine telemetry.

Three cooperating pieces (PR: request-level tracing and engine telemetry):

  * ``obs.metrics`` — the process-wide OpenMetrics registry (moved here
    from ``api.metrics``, which remains as a compatibility shim) extended
    with engine series: TTFT/TPOT/queue-wait histograms, batch occupancy,
    KV-slot utilization, prompt/prefix-cache hit rates, speculative accept
    rate, XLA compile count/seconds.
  * ``obs.trace`` — a lock-protected span recorder with a bounded
    ring-buffer trace store. All timestamps are ``time.monotonic()`` taken
    on the host; nothing here ever touches a device array, so
    instrumentation adds zero device syncs to the step loop.
  * ``obs.engine`` — ``EngineTelemetry``, the scheduler-facing facade that
    turns request lifecycle events (queued → admitted → prefill → decode →
    drained) into spans + histogram observations.

HTTP surface: ``GET /v1/traces`` and ``GET /debug/timeline/{request_id}``
(``api.traces``), fed by the trace-id middleware in ``api.server``.
"""

from localai_tpu.obs.engine import EngineTelemetry
from localai_tpu.obs.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_label_value,
    update_engine_gauges,
)
from localai_tpu.obs.trace import (
    STORE,
    RequestTrace,
    Span,
    TraceStore,
    new_trace_id,
)

__all__ = [
    "REGISTRY",
    "STORE",
    "Counter",
    "EngineTelemetry",
    "Gauge",
    "Histogram",
    "Registry",
    "RequestTrace",
    "Span",
    "TraceStore",
    "escape_label_value",
    "new_trace_id",
    "update_engine_gauges",
]
