"""Device health: timeout-guarded liveness probe + HBM accounting.

Three independent questions, three tools:

  * **Is the device answering at all?** :func:`probe_device` dispatches a
    tiny jitted add from a SIDE thread and joins it with a timeout — the
    only safe way to ask, because a dead tunnel makes the dispatch block
    forever and a blocked probe must never take the caller (the bench main
    thread, an HTTP handler) down with it. The probe program is compiled
    once per process; repeat probes are a microsecond dispatch.
  * **How full is it?** :func:`device_memory` reads per-device
    ``memory_stats()`` (bytes_in_use / peak / limit — absent on CPU, where
    jax returns None) into gauges. Pure host metadata, no dispatch: safe
    at /metrics scrape time.
  * **Who is holding it?** :func:`hbm_census` walks ``jax.live_arrays()``
    and attributes bytes to KV cache vs weights vs other using identity
    sets supplied by the caller (the /debug/devices handler passes each
    loaded runner's ``kv`` leaves and param leaves). ``nbytes`` is
    metadata; the census never syncs.

:func:`roofline` is the shared peak table the compiled-program cost
observatory (obs.compile) divides by: known TPU generations by device_kind
substring, env overrides ``LOCALAI_PEAK_GBPS``/``LOCALAI_PEAK_TFLOPS``,
and an explicitly marked ``assumed`` fallback for unknown hosts (the CPU
test mesh still gets a nonzero fraction, clearly labeled).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Iterable, Optional

from localai_tpu.obs.metrics import REGISTRY, Registry

# device_kind substring (lowercased) → (peak HBM GB/s, peak bf16 TFLOP/s).
# Public spec-sheet numbers; the observatory reports fractions, so ±10% on
# the peak moves the fraction, not the measured numerator.
_ROOFLINES = (
    ("v6", (1640.0, 918.0)),
    ("v5p", (2765.0, 459.0)),
    ("v5 lite", (819.0, 197.0)),
    ("v5e", (819.0, 197.0)),
    ("v4", (1228.0, 275.0)),
    ("v3", (900.0, 123.0)),
    ("v2", (700.0, 46.0)),
)
# unknown device (CPU test mesh): a deliberately modest desktop-class guess,
# reported with assumed=True so nobody mistakes the fraction for a
# measurement of the host
_ASSUMED = (25.0, 0.5)


def roofline(device: Optional[Any] = None) -> dict:
    """Peak bandwidth/compute for ``device`` (default: first jax device).
    ``{"peak_gbps", "peak_tflops", "source": "env"|"table"|"assumed"}``."""
    env_bw = os.environ.get("LOCALAI_PEAK_GBPS")
    env_fl = os.environ.get("LOCALAI_PEAK_TFLOPS")
    if env_bw or env_fl:
        try:
            return {
                "peak_gbps": float(env_bw or _ASSUMED[0]),
                "peak_tflops": float(env_fl or _ASSUMED[1]),
                "source": "env",
            }
        except ValueError:
            pass
    kind = ""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = str(getattr(device, "device_kind", "")).lower()
    except Exception:  # noqa: BLE001 — no backend is still an answer
        pass
    for sub, (bw, fl) in _ROOFLINES:
        if sub in kind:
            return {"peak_gbps": bw, "peak_tflops": fl, "source": "table",
                    "device_kind": kind}
    return {"peak_gbps": _ASSUMED[0], "peak_tflops": _ASSUMED[1],
            "source": "assumed", "device_kind": kind}


# -- liveness probe ---------------------------------------------------------

@dataclasses.dataclass
class ProbeResult:
    ok: bool
    seconds: float
    error: str = ""
    device: str = ""

    def to_dict(self) -> dict:
        return {"ok": self.ok, "seconds": round(self.seconds, 4),
                "error": self.error, "device": self.device}


_probe_lock = threading.Lock()
_probe_fn = None  # compiled once; a probe must not re-pay trace+compile
# single-flight latch for the default probe: against a wedged device every
# probe thread blocks FOREVER, and a dashboard auto-refreshing
# /debug/devices would otherwise leak one such thread per request. While
# one default probe is in flight, later callers join IT instead of
# spawning another — at most one thread is ever parked on a dead dispatch.
_probe_inflight: dict = {"thread": None, "box": None}


def _default_probe() -> None:
    """Tiny device round-trip: dispatch + materialize one [8] add."""
    global _probe_fn
    import jax
    import jax.numpy as jnp

    with _probe_lock:
        if _probe_fn is None:
            _probe_fn = jax.jit(lambda a: a + 1)
    out = _probe_fn(jnp.arange(8, dtype=jnp.int32))
    jax.block_until_ready(out)


def probe_device(timeout: float = 5.0, *,
                 registry: Optional[Registry] = None,
                 fn: Optional[Any] = None) -> ProbeResult:
    """Run a liveness round-trip in a side thread; join with ``timeout``.

    A hung tunnel leaves the probe thread blocked (daemon — it dies with
    the process) and returns ok=False error="timeout" in ``timeout``
    seconds instead of hanging the caller. ``fn`` is a test hook
    (inject a blocking callable to exercise the timeout path)."""
    reg = registry or REGISTRY
    probe = fn or _default_probe

    def make_thread(box: dict) -> threading.Thread:
        def run() -> None:
            t0 = time.monotonic()
            try:
                probe()
                box["seconds"] = time.monotonic() - t0
            except Exception as e:  # noqa: BLE001 — a sick device is a
                # result, not a crash
                box["error"] = f"{type(e).__name__}: {e}"
                box["seconds"] = time.monotonic() - t0

        return threading.Thread(target=run, name="device-probe",
                                daemon=True)

    started = False
    if fn is None:
        with _probe_lock:
            t = _probe_inflight["thread"]
            if t is not None and t.is_alive():
                box = _probe_inflight["box"]  # join the in-flight probe
            else:
                box = {}
                t = make_thread(box)
                _probe_inflight.update(thread=t, box=box)
                started = True
    else:  # test-injected probes stay independent of the latch
        box = {}
        t = make_thread(box)
        started = True
    t0 = time.monotonic()
    if started:
        t.start()
    t.join(timeout)
    kind = ""
    try:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "") or "cpu"
    except Exception:  # noqa: BLE001
        pass
    if t.is_alive():
        res = ProbeResult(False, time.monotonic() - t0,
                          f"timeout after {timeout}s", kind)
    elif "error" in box:
        res = ProbeResult(False, box.get("seconds", 0.0), box["error"], kind)
    else:
        res = ProbeResult(True, box.get("seconds", 0.0), "", kind)
    reg.device_ok.set(1 if res.ok else 0)
    reg.device_probe_seconds.set(round(res.seconds, 4))
    return res


# -- memory stats + live-array census ---------------------------------------

def device_memory(registry: Optional[Registry] = None) -> list[dict]:
    """Per-device ``memory_stats()`` snapshot (gauges refreshed as a side
    effect). CPU devices report ``memory: null`` — jax has no allocator
    stats there."""
    reg = registry or REGISTRY
    out: list[dict] = []
    try:
        import jax

        devices = jax.devices()
    except Exception as e:  # noqa: BLE001
        return [{"error": f"{type(e).__name__}: {e}"}]
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — optional per backend
            stats = None
        entry: dict = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", ""),
            "memory": None,
        }
        if stats:
            mem = {
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
            entry["memory"] = mem
            dev = str(d.id)
            if mem["bytes_in_use"] is not None:
                reg.hbm_bytes_in_use.set(mem["bytes_in_use"], device=dev)
            if mem["peak_bytes_in_use"] is not None:
                reg.hbm_peak_bytes.set(mem["peak_bytes_in_use"], device=dev)
            if mem["bytes_limit"] is not None:
                reg.hbm_bytes_limit.set(mem["bytes_limit"], device=dev)
        out.append(entry)
    return out


def _id_set(arrays: Iterable[Any]) -> set[int]:
    return {id(a) for a in arrays}


def hbm_census(known: Optional[dict[str, Iterable[Any]]] = None,
               registry: Optional[Registry] = None) -> dict:
    """Attribute live jax array bytes to categories.

    ``known`` maps category → iterable of arrays ("kv_cache": the runners'
    cache leaves, "weights": their param leaves); everything else counts as
    "other". Identity is by ``id()`` of the snapshot the caller holds — a
    donation race merely shifts a buffer into "other" for one reading."""
    reg = registry or REGISTRY
    cats = {name: _id_set(arrs) for name, arrs in (known or {}).items()}
    totals = {name: 0 for name in cats}
    totals["other"] = 0
    count = 0
    try:
        import jax

        live = jax.live_arrays()
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}
    for arr in live:
        nbytes = getattr(arr, "nbytes", 0) or 0
        count += 1
        for name, ids in cats.items():
            if id(arr) in ids:
                totals[name] += nbytes
                break
        else:
            totals["other"] += nbytes
    out = {"arrays": count, "total_bytes": sum(totals.values()),
           "by_category": totals}
    for name, nbytes in totals.items():
        reg.hbm_live_bytes.set(nbytes, category=name)
    return out


def known_arrays(runners: Iterable[Any]) -> dict[str, list]:
    """Build the census ``known`` mapping from ModelRunner-shaped objects
    (anything with ``.kv`` and ``.params``); non-conforming entries are
    skipped."""
    kv: list = []
    weights: list = []
    for r in runners:
        cache = getattr(r, "kv", None)
        if cache is not None:
            try:
                import jax

                kv.extend(jax.tree.leaves(cache.stacked()))
            except Exception:  # noqa: BLE001
                pass
        params = getattr(r, "params", None)
        if params is not None:
            try:
                import jax

                weights.extend(jax.tree.leaves(params))
            except Exception:  # noqa: BLE001
                pass
    return {"kv_cache": kv, "weights": weights}


def update_device_gauges(runners: Iterable[Any] = (),
                         registry: Optional[Registry] = None) -> None:
    """Scrape-time refresh (no device dispatch): memory_stats + census.
    The probe is deliberately NOT here — /metrics must never push work onto
    a possibly-wedged device; probes run from /debug/devices, the bench,
    or an operator."""
    device_memory(registry)
    hbm_census(known_arrays(runners), registry)
