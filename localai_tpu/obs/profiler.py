"""Anomaly-triggered device profiler capture: ProfileManager.

The gap this closes (ROADMAP item 1): every profiler surface so far is
operator-initiated (``POST /backend/trace``, bench's manual runs) — but
the BENCH trajectory died of anomalies nobody was watching live (r03
crashed, r04 timed out, r05 completed zero phases).  A profile captured
*minutes after* an operator notices shows a healthy engine; the capture
has to fire **when** the anomaly happens.  This module arms exactly that:

  * **Triggers.**  Watchdog stall trips (the engine stopped moving), SLO
    shed onset (latency burned through the error budget), and a
    step-time p99 regression against the flight ring's own trailing
    window (decode quietly got slower).  Each trigger calls
    :meth:`ProfileManager.maybe_capture` with the trace id / model that
    tripped it, so the profile is joined to the forensic trace that
    explains *why* it exists.
  * **Bounds.**  ``LOCALAI_PROFILE_ON_ANOMALY=1`` arms the whole thing
    (default off — a profiler capture is real device overhead);
    ``LOCALAI_PROFILE_SECONDS`` bounds each capture,
    ``LOCALAI_PROFILE_MAX_PER_HOUR`` + ``LOCALAI_PROFILE_COOLDOWN_S``
    bound the rate, and a single-flight lock (shared with the manual
    ``POST /backend/trace``) guarantees at most one capture at a time —
    a stall storm produces one profile and a line of receipts, not a
    profiler pile-up on an already-sick device.
  * **Artifacts.**  Profiles land under a manifest directory; every
    capture appends ``{id, trigger, trace_id, reason, model, path,
    started_unix, seconds}`` to ``manifest.json`` (atomic rewrite),
    listed at ``GET /debug/profiles`` and counted as
    ``localai_profiles_captured_total{trigger=...}``.

The capture itself wraps ``jax.profiler.start_trace``/``stop_trace``
(the same machinery as ``POST /backend/trace``); tests inject a fake
``capture_fn`` and clock, so the trigger/rate-limit/single-flight state
machine is exercised without a device.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from localai_tpu.obs.metrics import REGISTRY, Registry

log = logging.getLogger(__name__)

TRIGGERS = ("stall", "slo_shed", "step_p99_regression", "manual")


def _env_float(name: str, fallback: float) -> float:
    try:
        return float(os.environ.get(name, "") or fallback)
    except ValueError:
        return fallback


def enabled_from_env() -> bool:
    return os.environ.get("LOCALAI_PROFILE_ON_ANOMALY", "0") == "1"


def _jax_capture(path: str, seconds: float) -> None:
    """The real capture: a bounded jax.profiler trace window (XProf/
    TensorBoard format, same as POST /backend/trace)."""
    import jax

    jax.profiler.start_trace(path)
    try:
        time.sleep(seconds)
    finally:
        jax.profiler.stop_trace()


class ProfileManager:
    """Bounded, single-flight, anomaly-triggered profiler captures."""

    def __init__(self, *, enabled: Optional[bool] = None,
                 seconds: Optional[float] = None,
                 out_dir: Optional[str] = None,
                 max_per_hour: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 regression_ratio: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 registry: Optional[Registry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 capture_fn: Optional[Callable[[str, float], None]] = None):
        self.enabled = enabled if enabled is not None else enabled_from_env()
        self.seconds = (seconds if seconds is not None
                        else _env_float("LOCALAI_PROFILE_SECONDS", 3.0))
        self.out_dir = (out_dir if out_dir is not None
                        else os.environ.get("LOCALAI_PROFILE_DIR",
                                            "profiles"))
        self.max_per_hour = int(
            max_per_hour if max_per_hour is not None
            else _env_float("LOCALAI_PROFILE_MAX_PER_HOUR", 4))
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else _env_float("LOCALAI_PROFILE_COOLDOWN_S", 300.0))
        # recent-vs-trailing p99 ratio that counts as a decode regression
        self.regression_ratio = (
            regression_ratio if regression_ratio is not None
            else _env_float("LOCALAI_PROFILE_REGRESSION_RATIO", 2.0))
        self.poll_s = (poll_s if poll_s is not None
                       else _env_float("LOCALAI_PROFILE_POLL_S", 5.0))
        self.registry = registry or REGISTRY
        self._clock = clock
        self._capture_fn = capture_fn or _jax_capture
        # single-flight: at most one capture at a time, manual included
        # (POST /backend/trace acquires the same lock)
        self._capture_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        self._lock = threading.Lock()
        self._entries: list[dict] = []       # jaxlint: guarded-by(_lock)
        self._recent: deque = deque()        # capture ts ring (hour cap)
        self._last_capture: Optional[float] = None
        self._seq = 0
        self._skipped: dict[str, int] = {}   # why triggers didn't capture
        # flight recorders watched for step-time regressions: name →
        # weakref (a shut-down scheduler's ring must not be kept alive)
        self._flights: dict[str, Any] = {}
        self._reg_counts: dict[str, int] = {}
        self._installed = False
        self._poll_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # the watchdog/SLO instances the hooks were registered on, kept
        # so stop() can DEREGISTER them — otherwise a stop()+install()
        # cycle double-registers and every stall fires two captures
        self._hooked_watchdog: Optional[Any] = None
        self._hooked_slo: Optional[Any] = None

    # -- configuration -----------------------------------------------------

    def configure(self, *, out_dir: Optional[str] = None,
                  seconds: Optional[float] = None,
                  max_per_hour: Optional[int] = None,
                  cooldown_s: Optional[float] = None,
                  enabled: Optional[bool] = None) -> None:
        """Boot-time overrides (AppState points ``out_dir`` under the
        backend-assets tree). Atomic reference swaps, same contract as
        SLOTracker.configure."""
        if out_dir is not None:
            self.out_dir = out_dir
        if seconds is not None:
            self.seconds = seconds
        if max_per_hour is not None:
            self.max_per_hour = max_per_hour
        if cooldown_s is not None:
            self.cooldown_s = cooldown_s
        if enabled is not None:
            self.enabled = enabled

    # -- single-flight surface (shared with POST /backend/trace) -----------

    def acquire_capture(self) -> bool:
        """Claim the one-capture-at-a-time slot (non-blocking)."""
        return self._capture_lock.acquire(blocking=False)

    def release_capture(self) -> None:
        self._capture_lock.release()

    # -- trigger path ------------------------------------------------------

    def maybe_capture(self, trigger: str, *, trace_id: str = "",
                      reason: str = "", model: str = "",
                      sync: bool = False) -> bool:
        """One anomaly happened — capture a profile if the budget allows.

        Returns True when a capture was STARTED (async on a daemon thread
        unless ``sync``). Every refusal is cheap and accounted: disabled,
        another capture in flight (single-flight), inside the cooldown,
        or over the per-hour cap."""
        if not self.enabled:
            return False
        now = self._clock()
        with self._lock:
            if self._last_capture is not None and \
                    now - self._last_capture < self.cooldown_s:
                self._skipped["cooldown"] = \
                    self._skipped.get("cooldown", 0) + 1
                return False
            while self._recent and now - self._recent[0] > 3600.0:
                self._recent.popleft()
            if len(self._recent) >= self.max_per_hour:
                self._skipped["hourly_cap"] = \
                    self._skipped.get("hourly_cap", 0) + 1
                return False
        if not self.acquire_capture():
            with self._lock:
                self._skipped["in_flight"] = \
                    self._skipped.get("in_flight", 0) + 1
            return False
        # budget committed under the state lock BEFORE the capture runs:
        # a burst of triggers during the capture window must land on the
        # cooldown/in-flight refusals, not queue up behind it
        with self._lock:
            self._last_capture = now
            self._recent.append(now)
            self._seq += 1
            seq = self._seq
        entry = {
            "id": f"profile-{seq:04d}-{trigger}",
            "trigger": trigger,
            "trace_id": trace_id,
            "reason": reason,
            "model": model,
            "seconds": self.seconds,
            "started_unix": round(time.time(), 3),
        }
        self._idle.clear()
        if sync:
            self._run_capture(entry)
        else:
            threading.Thread(target=self._run_capture, args=(entry,),
                             daemon=True,
                             name=f"profile-capture-{seq}").start()
        return True

    def _run_capture(self, entry: dict) -> None:
        """Owns the already-acquired capture lock; releases it when the
        bounded window closes, success or not."""
        path = os.path.join(self.out_dir, entry["id"])
        try:
            os.makedirs(path, exist_ok=True)
            self._capture_fn(path, self.seconds)
            entry["path"] = path
            entry["ok"] = True
        except Exception as e:  # noqa: BLE001 — a failed capture is a receipt
            entry["path"] = path
            entry["ok"] = False
            entry["error"] = str(e)
            log.warning("anomaly profile capture failed: %s", e)
        finally:
            self.release_capture()
        with self._lock:
            self._entries.append(entry)
            entries = list(self._entries)
        self.registry.profiles_captured.inc(trigger=entry["trigger"])
        self._write_manifest(entries)
        self._idle.set()
        log.warning("anomaly profile captured: %s (trigger=%s trace=%s)",
                    entry["id"], entry["trigger"], entry["trace_id"])

    def _write_manifest(self, entries: list[dict]) -> None:
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = os.path.join(self.out_dir, ".manifest.tmp")
            with open(tmp, "w") as f:
                json.dump({"profiles": entries}, f, indent=2)
            os.replace(tmp, os.path.join(self.out_dir, "manifest.json"))
        except OSError as e:
            log.warning("could not write profile manifest: %s", e)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until no capture is in flight (smoke/tests)."""
        return self._idle.wait(timeout)

    # -- views -------------------------------------------------------------

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._entries)

    def report(self) -> dict:
        """The GET /debug/profiles payload."""
        with self._lock:
            entries = list(self._entries)
            skipped = dict(self._skipped)
            recent = len(self._recent)
        return {
            "enabled": self.enabled,
            "seconds": self.seconds,
            "dir": self.out_dir,
            "max_per_hour": self.max_per_hour,
            "cooldown_s": self.cooldown_s,
            "captures_last_hour": recent,
            "skipped": skipped,
            "profiles": entries,
        }

    # -- step-time regression detector -------------------------------------

    def watch_flight(self, name: str, recorder: Any) -> None:
        """Watch a scheduler's flight ring for step-time p99 regressions
        (weakly — a shut-down engine's ring is dropped on the next
        sweep)."""
        with self._lock:
            self._flights[name] = weakref.ref(recorder)

    def unwatch_flight(self, name: str) -> None:
        with self._lock:
            self._flights.pop(name, None)
            self._reg_counts.pop(name, None)

    def check_regressions(self, *, recent_n: int = 32,
                          min_trailing: int = 32) -> list[str]:
        """One detection pass (the poll thread's unit; tests call it
        directly). Splits each watched ring's resident per-step samples
        into the newest ``recent_n`` vs everything before them, and fires
        when the recent p99 exceeds ``regression_ratio`` × the trailing
        p99 — "decode is suddenly N× slower than ITS OWN recent history",
        no absolute threshold to tune per model. Returns the model names
        that triggered."""
        with self._lock:
            flights = list(self._flights.items())
        fired = []
        for name, ref in flights:
            rec = ref()
            if rec is None:
                self.unwatch_flight(name)
                continue
            count = rec.count
            with self._lock:
                # don't re-judge the same records after a trigger: wait
                # for a full fresh recent window first
                if count - self._reg_counts.get(name, 0) < recent_n:
                    continue
            rows = rec.snapshot()
            steps = [r["step_ms"] for r in rows
                     if r["step_ms"] is not None and not r["compile"]]
            if len(steps) < recent_n + min_trailing:
                continue
            recent = np.asarray(steps[-recent_n:])
            trailing = np.asarray(steps[:-recent_n])
            t99 = float(np.percentile(trailing, 99))
            r99 = float(np.percentile(recent, 99))
            if t99 > 0 and r99 >= self.regression_ratio * t99:
                with self._lock:
                    self._reg_counts[name] = count
                if self.maybe_capture(
                        "step_p99_regression", model=name,
                        reason=(f"step p99 {r99:.2f}ms vs trailing "
                                f"{t99:.2f}ms over {len(trailing)} "
                                f"dispatches")):
                    fired.append(name)
        return fired

    # -- wiring ------------------------------------------------------------

    def install(self, *, watchdog: Any = None, slo: Any = None) -> None:
        """Hook the three triggers (idempotent): watchdog stall trips,
        SLO shed onsets, and the flight-ring regression poll thread."""
        with self._lock:
            if self._installed:
                return
            self._installed = True
        wd = watchdog
        if wd is None:
            from localai_tpu.obs.watchdog import WATCHDOG

            wd = WATCHDOG
        wd.on_stall(self._on_stall)
        tracker = slo
        if tracker is None:
            from localai_tpu.obs.slo import SLO

            tracker = SLO
        tracker.on_shed(self._on_shed)
        with self._lock:
            self._hooked_watchdog = wd
            self._hooked_slo = tracker
        self._stop.clear()
        t = threading.Thread(
            target=self._poll, name="profile-regression-poll", daemon=True)
        with self._lock:
            self._poll_thread = t
        t.start()

    def _on_stall(self, event: Any) -> None:
        if getattr(event, "kind", "") != "stall":
            return
        self.maybe_capture(
            "stall", trace_id=getattr(event, "trace_id", ""),
            reason=(f"watchdog channel {event.channel!r} made no progress "
                    f"for {event.age_seconds}s"))

    def _on_shed(self, model: str) -> None:
        self.maybe_capture(
            "slo_shed", model=model,
            reason=f"model {model!r} entered SLO burn-rate shedding")

    def _poll(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_regressions()
            except Exception:  # noqa: BLE001 — the poll outlives bugs
                pass

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._poll_thread = self._poll_thread, None
            wd, self._hooked_watchdog = self._hooked_watchdog, None
            slo, self._hooked_slo = self._hooked_slo, None
            self._installed = False
        # deregister the trigger hooks OUTSIDE the lock (they take their
        # own): a later install() must register exactly once, not stack
        # a second capture per stall on top of the first
        if wd is not None:
            wd.remove_callback(self._on_stall)
        if slo is not None:
            remove = getattr(slo, "remove_shed_callback", None)
            if remove is not None:
                remove(self._on_shed)
        if t is not None:
            t.join(timeout=5)


# the process-wide manager (like WATCHDOG/SLO); armed only when
# LOCALAI_PROFILE_ON_ANOMALY=1 wires install_from_env at server boot
PROFILER = ProfileManager()


def install_from_env(base_dir: str = "") -> bool:
    """Server-boot wiring: arm the process-wide manager when
    ``LOCALAI_PROFILE_ON_ANOMALY=1``. ``base_dir`` roots the default
    manifest dir (backend assets) unless ``LOCALAI_PROFILE_DIR`` chose
    an explicit location."""
    if not PROFILER.enabled:
        return False
    if base_dir and "LOCALAI_PROFILE_DIR" not in os.environ:
        PROFILER.configure(out_dir=os.path.join(base_dir, "profiles"))
    PROFILER.install()
    return True
