"""SLO observatory: sliding-window latency aggregates + burn-rate shedding.

Two halves, both host-side only (no device reads anywhere):

**Observe.** Every finished generation lands one event per model —
``(ts, ttft_ms, tpot_ms, e2e_ms, queue_ms, bad)`` — in a time-ordered,
horizon-pruned series with cumulative bad counts
(:meth:`SLOTracker.observe`, fed by ``obs.engine.EngineTelemetry``), so
the per-request admission check costs two bisects, not a scan.
Percentiles per metric are computed over sliding windows (1m/5m/30m) on
demand: the windowed view of serving latency that the cumulative
``/metrics`` histograms cannot give.

**React.** Targets are p95 latency bounds in milliseconds
(``LOCALAI_SLO_TTFT_P95_MS`` / ``_TPOT_`` / ``_E2E_`` / ``_QUEUE_``, or the
matching ``AppConfig.slo_*`` fields / ``--slo-*`` CLI flags; 0/unset
disables a target). An event is *bad* when any configured target is
exceeded (or the request finished with reason ``error``). With a 95%
objective the error budget is 5%, and the **burn rate** of a window is
``bad_fraction / 0.05`` — 1.0 means burning exactly the budget. When the
fast (1m) AND slow (5m) burn rates both exceed
``LOCALAI_SLO_BURN_THRESHOLD`` (default 2.0) with at least
``LOCALAI_SLO_MIN_EVENTS`` completions in the fast window, the model
enters *shedding*: the API admission hook refuses new generation work with
HTTP 429 + ``Retry-After`` (``localai_overload_shedding{model=...}=1``,
``localai_requests_shed_total``). Recovery is automatic with hysteresis:
shedding stops once the fast burn rate falls below
``LOCALAI_SLO_RECOVER_BURN`` (default 1.0) — which happens on its own as
the fast window slides past the violation burst (shed requests never
become events).

Multi-window burn-rate alerting follows the SRE-workbook shape; the
admission gate is the Sarathi-class "don't admit what you can't serve
inside the SLO" half, degraded gracefully to explicit 429s instead of
unbounded queueing.

``SLO`` is the process-wide instance (like ``REGISTRY``/``STORE``);
surfaced at ``GET /v1/slo`` and in the ``/metrics`` exposition at scrape
time via :meth:`SLOTracker.export_gauges`.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

from localai_tpu.obs.metrics import REGISTRY, Registry

# (label, seconds), fast → slow; FAST/SLOW drive the shedding decision
WINDOWS = (("1m", 60.0), ("5m", 300.0), ("30m", 1800.0))
FAST, SLOW = "1m", "5m"
_SPAN = {label: s for label, s in WINDOWS}
METRICS = ("ttft_ms", "tpot_ms", "e2e_ms", "queue_ms")
_TARGET_ENV = {
    "ttft_ms": "LOCALAI_SLO_TTFT_P95_MS",
    "tpot_ms": "LOCALAI_SLO_TPOT_P95_MS",
    "e2e_ms": "LOCALAI_SLO_E2E_P95_MS",
    "queue_ms": "LOCALAI_SLO_QUEUE_P95_MS",
}


def _env_float(name: str, fallback: float) -> float:
    try:
        return float(os.environ.get(name, "") or fallback)
    except ValueError:
        return fallback


def env_targets() -> dict[str, float]:
    """p95 targets from the environment (0/unset/garbage = no target)."""
    out = {}
    for metric, env in _TARGET_ENV.items():
        v = _env_float(env, 0.0)
        if v > 0:
            out[metric] = v
    return out


def targets_from_config(cfg) -> dict[str, float]:
    """p95 targets from AppConfig's ``slo_*_p95_ms`` fields (0 = unset)."""
    out = {}
    for metric, field in (("ttft_ms", "slo_ttft_p95_ms"),
                          ("tpot_ms", "slo_tpot_p95_ms"),
                          ("e2e_ms", "slo_e2e_p95_ms"),
                          ("queue_ms", "slo_queue_p95_ms")):
        v = float(getattr(cfg, field, 0.0) or 0.0)
        if v > 0:
            out[metric] = v
    return out


class _Event:
    __slots__ = ("ts", "ttft_ms", "tpot_ms", "e2e_ms", "queue_ms", "bad")

    def __init__(self, ts, ttft_ms, tpot_ms, e2e_ms, queue_ms, bad):
        self.ts = ts
        self.ttft_ms = ttft_ms
        self.tpot_ms = tpot_ms
        self.e2e_ms = e2e_ms
        self.queue_ms = queue_ms
        self.bad = bad


class _Series:
    """One model's completion history, time-ordered, with a parallel
    timestamp array and cumulative bad counts — so the admission path's
    per-request burn-rate checks are two bisects (O(log n)), not linear
    scans over 30 minutes of events (which would peak in cost exactly
    during the overload the gate exists to survive)."""

    __slots__ = ("events", "ts", "bad_cum")

    def __init__(self):
        self.events: list[_Event] = []
        self.ts: list[float] = []
        self.bad_cum: list[int] = []   # bad_cum[i] = bad events in [0..i]

    def append(self, e: _Event) -> None:
        # observe() stamps ts from one monotonic clock, so appends stay
        # time-ordered by construction
        self.events.append(e)
        self.ts.append(e.ts)
        prev = self.bad_cum[-1] if self.bad_cum else 0
        self.bad_cum.append(prev + (1 if e.bad else 0))

    def prune(self, horizon: float) -> None:
        """Bound memory by dropping expired events — LAZILY. Stale
        entries are already invisible to counts()/window() (both bisect
        on the cutoff), so the only reason to delete is memory; the O(n)
        rebuild runs only once the stale prefix dominates (≥ half, min
        64), which drops ≥ n/2 each time — amortized O(1) per event
        instead of a full-window rebuild on nearly every completion
        under steady traffic."""
        cut = bisect.bisect_left(self.ts, horizon)
        if cut < 64 or cut * 2 < len(self.ts):
            return
        base = self.bad_cum[cut - 1]
        del self.events[:cut]
        del self.ts[:cut]
        self.bad_cum = [b - base for b in self.bad_cum[cut:]]

    def counts(self, cutoff: float) -> tuple[int, int]:
        """(total, bad) for events with ts >= cutoff — two index ops."""
        i = bisect.bisect_left(self.ts, cutoff)
        n = len(self.ts) - i
        if n == 0:
            return 0, 0
        bad = self.bad_cum[-1] - (self.bad_cum[i - 1] if i else 0)
        return n, bad

    def window(self, cutoff: float) -> list[_Event]:
        return self.events[bisect.bisect_left(self.ts, cutoff):]


class SLOTracker:
    """Per-model sliding-window SLO aggregates + shedding state machine."""

    def __init__(self, *, registry: Optional[Registry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 targets: Optional[dict[str, float]] = None,
                 objective: Optional[float] = None,
                 burn_threshold: Optional[float] = None,
                 recover_burn: Optional[float] = None,
                 min_events: Optional[int] = None,
                 retry_after_s: Optional[int] = None):
        self.registry = registry or REGISTRY
        self._clock = clock
        self.targets = (dict(targets) if targets is not None
                        else env_targets())
        self.objective = (objective if objective is not None
                          else _env_float("LOCALAI_SLO_OBJECTIVE", 0.95))
        self.burn_threshold = (
            burn_threshold if burn_threshold is not None
            else _env_float("LOCALAI_SLO_BURN_THRESHOLD", 2.0))
        self.recover_burn = (
            recover_burn if recover_burn is not None
            else _env_float("LOCALAI_SLO_RECOVER_BURN", 1.0))
        self.min_events = (
            min_events if min_events is not None
            else int(_env_float("LOCALAI_SLO_MIN_EVENTS", 5)))
        self.retry_after_s = (
            retry_after_s if retry_after_s is not None
            else int(_env_float("LOCALAI_SLO_RETRY_AFTER_S", 30)))
        self._lock = threading.Lock()
        self._events: dict[str, _Series] = {}
        self._shedding: dict[str, bool] = {}
        self._shed_total: dict[str, int] = {}
        # fired once per not-shedding → shedding transition (the anomaly
        # profiler hooks this: a shed ONSET is the moment worth a capture,
        # not every request refused while shedding stands)
        self._shed_callbacks: list[Callable[[str], None]] = []

    # -- configuration ----------------------------------------------------

    def configure(self, *, targets: Optional[dict[str, float]] = None,
                  objective: Optional[float] = None,
                  burn_threshold: Optional[float] = None,
                  recover_burn: Optional[float] = None,
                  min_events: Optional[int] = None,
                  retry_after_s: Optional[int] = None) -> None:
        """Replace the given knobs (server boot wires AppConfig through
        here; omitted knobs keep their current values).

        Deliberately lock-free: each knob is an atomic reference swap
        (``targets`` is replaced wholesale with a fresh dict, never
        mutated in place), and the admission path reads them lock-free —
        a reader sees the old or the new configuration, both valid.
        Taking ``_lock`` here would promote every one of those hot reads
        to a lock acquisition for no consistency gain."""
        if targets is not None:
            self.targets = {k: float(v) for k, v in targets.items()
                            if float(v) > 0}
        if objective is not None:
            self.objective = objective
        if burn_threshold is not None:
            self.burn_threshold = burn_threshold
        if recover_burn is not None:
            self.recover_burn = recover_burn
        if min_events is not None:
            self.min_events = min_events
        if retry_after_s is not None:
            self.retry_after_s = retry_after_s

    def reset(self) -> None:
        """Drop all events and shedding state (tests, reconfiguration).
        Clears the per-model shedding gauges it owns so a stale 1 cannot
        outlive the state that justified it."""
        with self._lock:
            models = set(self._shedding) | set(self._events)
            self._events.clear()
            self._shedding.clear()
            self._shed_total.clear()
        for m in models:
            self.registry.overload_shedding.set(0, model=m)

    # -- observe ----------------------------------------------------------

    def observe(self, model: str, *, ttft_ms: Optional[float] = None,
                tpot_ms: Optional[float] = None,
                e2e_ms: Optional[float] = None,
                queue_ms: Optional[float] = None,
                error: bool = False,
                now: Optional[float] = None) -> None:
        """One finished request. ``bad`` is decided against the CURRENT
        targets at observe time; events older than the longest window are
        lazily pruned on the way in (see :meth:`_Series.prune`)."""
        now = self._clock() if now is None else now
        vals = {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms,
                "e2e_ms": e2e_ms, "queue_ms": queue_ms}
        bad = error or any(
            vals[m] is not None and vals[m] > t
            for m, t in self.targets.items()
        )
        horizon = now - WINDOWS[-1][1]
        with self._lock:
            series = self._events.get(model)
            if series is None:
                series = self._events[model] = _Series()
            series.append(
                _Event(now, ttft_ms, tpot_ms, e2e_ms, queue_ms, bad))
            series.prune(horizon)

    def _counts(self, model: str, seconds: float,
                now: float) -> tuple[int, int]:
        """(total, bad) in the window — O(log n), the admission-path
        primitive (should_shed runs on every generation request)."""
        with self._lock:
            series = self._events.get(model)
            if series is None:
                return 0, 0
            return series.counts(now - seconds)

    def _window(self, model: str, seconds: float,
                now: float) -> list[_Event]:
        with self._lock:
            series = self._events.get(model)
            if series is None:
                return []
            return series.window(now - seconds)

    # -- aggregates -------------------------------------------------------

    def burn_rate(self, model: str, window: str = FAST,
                  now: Optional[float] = None) -> float:
        """bad_fraction / error_budget over the window (0.0 when empty)."""
        now = self._clock() if now is None else now
        n, bad = self._counts(model, _SPAN[window], now)
        if n == 0:
            return 0.0
        budget = max(1e-9, 1.0 - self.objective)
        return bad / n / budget

    def windows(self, model: str, now: Optional[float] = None) -> dict:
        """Per-window aggregates: count, bad count, burn rate, and
        p50/p95/p99 per latency metric."""
        now = self._clock() if now is None else now
        budget = max(1e-9, 1.0 - self.objective)
        out = {}
        for label, seconds in WINDOWS:
            events = self._window(model, seconds, now)
            agg: dict = {
                "count": len(events),
                "bad": sum(1 for e in events if e.bad),
            }
            agg["burn_rate"] = (
                round(agg["bad"] / agg["count"] / budget, 4)
                if events else 0.0
            )
            for metric in METRICS:
                vals = [getattr(e, metric) for e in events
                        if getattr(e, metric) is not None]
                if vals:
                    p50, p95, p99 = np.percentile(vals, (50, 95, 99))
                    agg[metric] = {"p50": round(float(p50), 3),
                                   "p95": round(float(p95), 3),
                                   "p99": round(float(p99), 3)}
                else:
                    agg[metric] = None
            out[label] = agg
        return out

    # -- shedding state machine -------------------------------------------

    def _update_state(self, model: str, now: float) -> bool:
        """Run the hysteresis state machine for one model and keep its
        gauge current. Called from the admission path AND from every
        export/report — recovery must not depend on another request
        arriving (a shedding model whose clients all back off would
        otherwise stay latched at 1 forever)."""
        if not self.targets:
            # no objectives configured: never shed, and un-latch any
            # state left over from a previous configuration
            with self._lock:
                was = self._shedding.pop(model, False)
            if was:
                self.registry.overload_shedding.set(0, model=model)
            return False
        fast = self.burn_rate(model, FAST, now=now)
        slow = self.burn_rate(model, SLOW, now=now)
        n_fast, _ = self._counts(model, _SPAN[FAST], now)
        with self._lock:
            was = self._shedding.get(model, False)
            shedding = was
            if shedding:
                if fast < self.recover_burn:
                    shedding = False
            elif (fast >= self.burn_threshold
                    and slow >= self.burn_threshold
                    and n_fast >= self.min_events):
                shedding = True
            # don't resurrect a reset/unknown model's entry just to say
            # "not shedding" — only track models with actual state
            if shedding or model in self._shedding:
                self._shedding[model] = shedding
            callbacks = (list(self._shed_callbacks)
                         if shedding and not was else ())
        self.registry.overload_shedding.set(1 if shedding else 0,
                                            model=model)
        for cb in callbacks:  # onset only, outside the lock
            try:
                cb(model)
            except Exception:  # noqa: BLE001 — observers must not break
                pass           # the admission path
        return shedding

    def on_shed(self, cb: Callable[[str], None]) -> None:
        """Register a callback fired once per shedding ONSET (the
        not-shedding → shedding transition) with the model name.
        Exceptions are swallowed — an observer must never break the
        admission path that detected the overload."""
        with self._lock:
            self._shed_callbacks.append(cb)

    def remove_shed_callback(self, cb: Callable[[str], None]) -> None:
        """Unregister an onset callback (the anomaly profiler detaches
        at stop() so a torn-down manager's closure is not kept alive —
        and a later install cannot double-fire)."""
        with self._lock:
            try:
                self._shed_callbacks.remove(cb)
            except ValueError:
                pass

    def should_shed(self, model: str, now: Optional[float] = None) -> bool:
        """The admission-path decision, with hysteresis.

        Not shedding → shedding when fast AND slow burn rates exceed the
        threshold with enough fast-window evidence; shedding → recovered
        when the fast burn rate falls below ``recover_burn`` (shed
        requests never become events, so the fast window drains on its
        own).
        """
        return self._update_state(
            model, self._clock() if now is None else now)

    def shed(self, model: str) -> int:
        """Record one refused request; returns the Retry-After seconds.
        Sole writer of ``localai_requests_shed_total`` (the scheduler's
        ``shed_total`` mirror feeds only the JSON metrics surface)."""
        with self._lock:
            self._shed_total[model] = self._shed_total.get(model, 0) + 1
        self.registry.requests_shed.inc(model=model)
        return self.retry_after_s

    def shedding(self, model: str, now: Optional[float] = None) -> bool:
        """Current shedding state, recovery-aware (re-runs the state
        machine so a latched flag cannot outlive its windows)."""
        return self._update_state(
            model, self._clock() if now is None else now)

    def shed_total(self, model: str) -> int:
        with self._lock:
            return self._shed_total.get(model, 0)

    # -- export -----------------------------------------------------------

    def export_gauges(self, registry: Optional[Registry] = None,
                      now: Optional[float] = None) -> None:
        """Refresh the burn-rate + shedding gauges for every observed
        model (called at /metrics scrape time — the engine thread never
        touches the registry)."""
        reg = registry or self.registry
        now = self._clock() if now is None else now
        with self._lock:
            models = set(self._events) | set(self._shedding)
        for model in models:
            for label, _ in WINDOWS:
                reg.slo_burn_rate.set(
                    round(self.burn_rate(model, label, now=now), 4),
                    model=model, window=label)
            # re-runs the state machine: a scrape must observe recovery
            # even with zero traffic. The explicit set also materializes
            # the 0 series for observed-but-never-shedding models (and
            # for no-target configs), so dashboards can key on it.
            shedding = self._update_state(model, now)
            reg.overload_shedding.set(1 if shedding else 0, model=model)

    def report(self, now: Optional[float] = None) -> dict:
        """The GET /v1/slo payload."""
        now = self._clock() if now is None else now
        with self._lock:
            models = sorted(set(self._events) | set(self._shedding))
            targets = dict(self.targets)
        return {
            "targets": targets,
            "objective": self.objective,
            "burn_threshold": self.burn_threshold,
            "recover_burn": self.recover_burn,
            "min_events": self.min_events,
            "windows": [label for label, _ in WINDOWS],
            "models": {
                m: {
                    "shedding": self.shedding(m, now=now),
                    "shed_total": self.shed_total(m),
                    "windows": self.windows(m, now=now),
                }
                for m in models
            },
        }


# the process-wide observatory (EngineTelemetry and the API default to it)
SLO = SLOTracker()
