"""Engine flight recorder: a fixed-size ring of per-dispatch records.

The failure mode this exists for (BENCH r5): the engine stalls or a bench
round expires and the only artifact is ``decode_throughput 0.0`` — no
record of what the engine was doing for the preceding seconds, how far it
got, or what step times looked like right before the silence. Production
continuous-batching stacks (Orca, OSDI '22) treat the per-iteration
timeline as the primary debugging artifact; this is that timeline.

Design constraints (same contract as :mod:`obs.trace`):

  * **Zero device syncs.** Every field is a host-side mirror the scheduler
    already holds (slot dict sizes, queue depth, token counters, monotonic
    clocks). Nothing here ever touches a jax array.
  * **Lock-light, allocation-light.** The ring is column-major over
    preallocated numpy arrays; :meth:`record` writes one row in place
    under a short lock — no per-dispatch list/dict/object allocation, so
    feeding it from the drain loop costs a few scalar stores.
  * **Windowed percentiles from the ring.** Per-token step time
    (``dispatch_ms / steps``) percentiles (p50/p90/p99) are computed on
    demand from the resident rows, excluding compile-bearing first
    dispatches (``compile=True``), so the numbers answer "what is decode
    doing NOW", which the lifetime EMA cannot. Speculative windows record
    their MEASURED yield (mean emitted tokens per active slot-window) as
    ``steps`` plus per-dispatch ``spec_proposed``/``spec_accepted``
    counts — with speculation the default lane they are part of the
    decode timeline, not an exclusion.

One instance per Scheduler (``Scheduler.flight``); bench phases build
their own. Surfaced at ``GET /debug/flight`` and attached to every stall
forensic trace via the watchdog's context providers.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from localai_tpu.obs.trace import mono_to_wall


def _default_capacity() -> int:
    try:
        return max(1, int(os.environ.get("LOCALAI_FLIGHT_CAPACITY", "512")))
    except ValueError:
        return 512


class FlightRecorder:
    """Column-major ring of per-dispatch engine records."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = int(capacity) if capacity else _default_capacity()
        n = self.capacity
        self._lock = threading.Lock()
        self._ts = np.zeros(n)
        self._steps = np.zeros(n, np.int64)
        self._dispatch_ms = np.zeros(n)
        self._occupancy = np.zeros(n)
        self._batch_slots = np.zeros(n, np.int64)
        self._queue_depth = np.zeros(n, np.int64)
        self._kv_utilization = np.zeros(n)
        self._tokens = np.zeros(n, np.int64)
        self._preemptions = np.zeros(n, np.int64)
        self._spec_accept = np.full(n, np.nan)
        self._spec_proposed = np.zeros(n, np.int64)
        self._spec_accepted = np.zeros(n, np.int64)
        self._gap_ms = np.zeros(n)
        self._sched_ms = np.zeros(n)
        self._launch_ms = np.zeros(n)
        self._sync_ms = np.zeros(n)
        self._compile = np.zeros(n, bool)
        self._program: list[str] = [""] * n
        self._n = 0                # records ever written (ring head = n % cap)
        self.total_tokens = 0      # cumulative, survives wraparound

    # -- hot path (engine thread) -----------------------------------------

    def record(self, *, program: str, steps: int, dispatch_ms: float,
               occupancy: float, queue_depth: int, kv_utilization: float,
               tokens: int, preemptions: int = 0,
               spec_accept: Optional[float] = None,
               spec_proposed: int = 0, spec_accepted: int = 0,
               compile: bool = False, ts: Optional[float] = None,
               batch_slots: int = 0, gap_ms: float = 0.0,
               sched_ms: float = 0.0, launch_ms: float = 0.0,
               sync_ms: float = 0.0) -> None:
        """Append one dispatch record (host scalars only).

        ``batch_slots`` tags the record with the lane mix: how many of the
        occupied slots were background batch-lane requests at drain time
        (0 = pure interactive dispatch). ``spec_proposed``/
        ``spec_accepted`` are THIS dispatch's draft-token counts (0 for
        non-speculative dispatches) — the per-window accept trace the
        cumulative ``spec_accept`` ratio can't show.

        ``gap_ms``/``sched_ms``/``launch_ms``/``sync_ms`` decompose the
        wall interval ``dispatch_ms`` accounts for (see
        :mod:`obs.anatomy` for phase semantics). The scheduler guarantees
        their sum never exceeds ``dispatch_ms``; callers that cannot
        attribute phases pass the zero defaults and the record degrades
        to the undifferentiated pre-anatomy shape."""
        now = time.monotonic() if ts is None else ts
        with self._lock:
            i = self._n % self.capacity
            self._ts[i] = now
            self._steps[i] = steps
            self._dispatch_ms[i] = dispatch_ms
            self._occupancy[i] = occupancy
            self._batch_slots[i] = batch_slots
            self._queue_depth[i] = queue_depth
            self._kv_utilization[i] = kv_utilization
            self._tokens[i] = tokens
            self._preemptions[i] = preemptions
            self._spec_accept[i] = (np.nan if spec_accept is None
                                    else spec_accept)
            self._spec_proposed[i] = spec_proposed
            self._spec_accepted[i] = spec_accepted
            self._gap_ms[i] = gap_ms
            self._sched_ms[i] = sched_ms
            self._launch_ms[i] = launch_ms
            self._sync_ms[i] = sync_ms
            self._compile[i] = compile
            self._program[i] = program
            self._n += 1
            self.total_tokens += int(tokens)

    # -- read side ---------------------------------------------------------

    @property
    def count(self) -> int:
        """Records ever written (resident rows = min(count, capacity))."""
        # monotone int, torn reads impossible under the GIL; observability
        # readers tolerate being one record behind the engine thread
        return self._n  # jaxlint: disable=lock-guarded-attr

    def _order(self) -> np.ndarray:  # jaxlint: guarded-by(_lock)
        """Resident row indices, oldest → newest (caller holds the lock)."""
        if self._n <= self.capacity:
            return np.arange(self._n)
        head = self._n % self.capacity
        return np.concatenate([np.arange(head, self.capacity),
                               np.arange(head)])

    def snapshot(self, since: float = 0.0,
                 limit: Optional[int] = None) -> list[dict]:
        """Resident records oldest → newest as JSON-able dicts.

        ``since`` filters on the record's monotonic timestamp (pollers pass
        the ``ts`` of the last record they saw); ``limit`` keeps the newest
        N after filtering.
        """
        # copy the selected rows under the lock, format after releasing
        # it: building (up to capacity) dicts must not block the engine
        # thread's per-dispatch record() behind a scrape
        with self._lock:
            order = self._order()
            if since:
                order = order[self._ts[order] > since]
            if limit is not None and len(order) > limit:
                order = order[-limit:]
            cols = {
                "ts": self._ts[order].tolist(),
                "steps": self._steps[order].tolist(),
                "ms": self._dispatch_ms[order].tolist(),
                "occ": self._occupancy[order].tolist(),
                "batch": self._batch_slots[order].tolist(),
                "queue": self._queue_depth[order].tolist(),
                "kv": self._kv_utilization[order].tolist(),
                "tokens": self._tokens[order].tolist(),
                "preempt": self._preemptions[order].tolist(),
                "acc": self._spec_accept[order].tolist(),
                "proposed": self._spec_proposed[order].tolist(),
                "accepted": self._spec_accepted[order].tolist(),
                "gap": self._gap_ms[order].tolist(),
                "sched": self._sched_ms[order].tolist(),
                "launch": self._launch_ms[order].tolist(),
                "sync": self._sync_ms[order].tolist(),
                "compile": self._compile[order].tolist(),
                "program": [self._program[i] for i in order],
            }
        out = []
        for j in range(len(cols["ts"])):
            steps = cols["steps"][j]
            ms = cols["ms"][j]
            acc = cols["acc"][j]
            out.append({
                # ts stays unrounded: pollers feed it back as ?since=
                # and a rounded-up value would exclude its own record
                "ts": cols["ts"][j],
                "ts_unix": round(mono_to_wall(cols["ts"][j]), 6),
                "program": cols["program"][j],
                "steps": steps,
                "dispatch_ms": round(ms, 3),
                "step_ms": (round(ms / steps, 4) if steps > 0 else None),
                "occupancy": round(cols["occ"][j], 4),
                "batch_slots": cols["batch"][j],
                "queue_depth": cols["queue"][j],
                "kv_utilization": round(cols["kv"][j], 4),
                "tokens": cols["tokens"][j],
                "preemptions": cols["preempt"][j],
                "spec_accept": (None if np.isnan(acc) else round(acc, 4)),
                "spec_proposed": cols["proposed"][j],
                "spec_accepted": cols["accepted"][j],
                "gap_ms": round(cols["gap"][j], 3),
                "sched_ms": round(cols["sched"][j], 3),
                "launch_ms": round(cols["launch"][j], 3),
                "sync_ms": round(cols["sync"][j], 3),
                "compile": cols["compile"][j],
            })
        return out

    def percentiles(self, window_s: Optional[float] = None,
                    now: Optional[float] = None) -> dict:
        """Per-token step-time percentiles over the ring.

        The default window is the RING — the last ``capacity`` dispatches,
        however old (an idle engine keeps reporting its most recent
        activity rather than going blank); pass ``window_s`` to restrict
        to recent wall time. Compile-bearing first dispatches and
        speculative windows are excluded (see module docstring). Returns
        ``step_ms_p50/p90/p99`` (None when no eligible sample) plus the
        sample count.
        """
        with self._lock:
            order = self._order()
            mask = (self._steps[order] > 0) & ~self._compile[order]
            if window_s is not None:
                cutoff = (time.monotonic() if now is None else now) - window_s
                mask &= self._ts[order] >= cutoff
            rows = order[mask]
            per_step = (self._dispatch_ms[rows]
                        / np.maximum(self._steps[rows], 1))
        if len(per_step) == 0:
            return {"step_ms_p50": None, "step_ms_p90": None,
                    "step_ms_p99": None, "samples": 0}
        p50, p90, p99 = np.percentile(per_step, (50, 90, 99))
        return {
            "step_ms_p50": round(float(p50), 4),
            "step_ms_p90": round(float(p90), 4),
            "step_ms_p99": round(float(p99), 4),
            "samples": int(len(per_step)),
        }

    def phases(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> dict:
        """Per-phase dispatch-anatomy percentiles + windowed fractions.

        Same window semantics as :meth:`percentiles` (ring by default,
        ``window_s`` to restrict; compile-bearing rows excluded — a
        compile's minutes of tracing would drown every phase). For each
        phase in gap/sched/launch/sync: ``{phase}_ms_p50/p90/p99`` and
        ``{phase}_ms_total`` over the window, plus ``dispatch_ms_total``,
        ``host_ms_total`` (gap+sched+launch) and the two derived gauges:

        * ``host_overhead_fraction`` = host_ms_total / dispatch_ms_total —
          the share of accounted wall time the host spent NOT blocked on
          the device.
        * ``device_bubble_fraction`` — estimator of device idle share:
          per record ``max(0, (gap+sched+launch) - sync_ms)`` summed over
          the window, / dispatch_ms_total. A record whose host phases
          were fully covered by a later sync wait means the device queue
          hid the host time (no bubble); host time the device did NOT
          make the host wait for is (estimated) device idleness. An
          estimator, not a measurement — see :mod:`obs.anatomy`.
        """
        with self._lock:
            order = self._order()
            mask = ~self._compile[order]
            if window_s is not None:
                cutoff = (time.monotonic() if now is None else now) - window_s
                mask &= self._ts[order] >= cutoff
            rows = order[mask]
            ph_cols = {
                "gap": self._gap_ms[rows].copy(),
                "sched": self._sched_ms[rows].copy(),
                "launch": self._launch_ms[rows].copy(),
                "sync": self._sync_ms[rows].copy(),
            }
            dispatch = self._dispatch_ms[rows].copy()
        out: dict = {"samples": int(len(dispatch))}
        if len(dispatch) == 0:
            for ph in (*ph_cols, "host"):
                out[f"{ph}_ms_p50"] = None
                out[f"{ph}_ms_p90"] = None
                out[f"{ph}_ms_p99"] = None
            for ph in ph_cols:
                out[f"{ph}_ms_total"] = 0.0
            out["dispatch_ms_total"] = 0.0
            out["host_ms_total"] = 0.0
            out["host_overhead_fraction"] = None
            out["device_bubble_fraction"] = None
            return out
        for ph, arr in ph_cols.items():
            p50, p90, p99 = np.percentile(arr, (50, 90, 99))
            out[f"{ph}_ms_p50"] = round(float(p50), 4)
            out[f"{ph}_ms_p90"] = round(float(p90), 4)
            out[f"{ph}_ms_p99"] = round(float(p99), 4)
            out[f"{ph}_ms_total"] = round(float(arr.sum()), 3)
        host = ph_cols["gap"] + ph_cols["sched"] + ph_cols["launch"]
        # host percentiles are computed on the per-record SUM, not a sum
        # of per-phase percentiles (those don't compose)
        p50, p90, p99 = np.percentile(host, (50, 90, 99))
        out["host_ms_p50"] = round(float(p50), 4)
        out["host_ms_p90"] = round(float(p90), 4)
        out["host_ms_p99"] = round(float(p99), 4)
        bubble = np.maximum(0.0, host - ph_cols["sync"])
        total = float(dispatch.sum())
        out["dispatch_ms_total"] = round(total, 3)
        out["host_ms_total"] = round(float(host.sum()), 3)
        if total > 0:
            out["host_overhead_fraction"] = round(float(host.sum()) / total, 4)
            out["device_bubble_fraction"] = round(
                float(bubble.sum()) / total, 4)
        else:
            out["host_overhead_fraction"] = None
            out["device_bubble_fraction"] = None
        return out
