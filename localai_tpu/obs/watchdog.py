"""Dispatch-heartbeat stall watchdog: the "is anything moving?" half of obs.

The failure mode this exists for (BENCH r3/r4/r5): a dead axon tunnel makes
a device round-trip block FOREVER with no exception — the engine thread sits
inside ``np.asarray(tokens)``, the API keeps accepting requests, and nothing
in the tracing layer can distinguish "slow" from "gone". The watchdog turns
that silence into a signal:

  * call sites wrap each blocking device round-trip in :meth:`Watchdog.guard`
    (or ``arm``/``pulse``/``disarm`` for streaming loops). Cost per guarded
    round-trip is two monotonic reads and a dict update under a lock —
    nothing here ever touches a device array.
  * a background thread (:meth:`check` is the testable unit) looks for
    channels that are ARMED (an operation in flight) with no progress past
    ``deadline``. On a trip it sets the ``localai_engine_stalled`` gauge,
    records ``localai_last_progress_age_seconds``, dumps EVERY thread's
    stack (``sys._current_frames``) into the trace store as a forensic
    ``kind="stall"`` trace (retrievable at ``GET /v1/traces?kind=stall``),
    and fires registered callbacks.
  * the next pulse/disarm on a stalled channel clears the gauge and fires a
    ``recovered`` event — a stall is "no observable progress", not proof of
    death: a multi-minute XLA compile can trip it and then recover, which is
    exactly the breadcrumb an operator wants.

Channels are independent countdowns: the runner's blocking syncs share
``"device"``, each scheduler guards its drain under ``"engine:<model>"``,
worker RPC streams under ``"rpc:<model>"``, and bench phases use a fresh
channel per phase so an abandoned hung phase cannot mask the next one.

``WATCHDOG`` is the process-wide instance (like ``REGISTRY``/``STORE``);
its thread starts lazily when the first Scheduler comes up.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
import traceback
import uuid
from contextlib import contextmanager
from typing import Callable, Iterator, Optional

from localai_tpu.obs.metrics import REGISTRY, Registry
from localai_tpu.obs.trace import STORE, RequestTrace, TraceStore


def _default_deadline() -> float:
    try:
        return float(os.environ.get("LOCALAI_STALL_DEADLINE_S", "60"))
    except ValueError:
        return 60.0


@dataclasses.dataclass
class StallEvent:
    """What a callback receives: one trip or one recovery."""

    channel: str
    kind: str                 # "stall" | "recovered"
    age_seconds: float
    trace_id: str = ""        # the forensic stack-dump trace ("" on recovery)


class _Channel:
    __slots__ = ("armed", "last_progress", "stalled", "stalled_at")

    def __init__(self, now: float):
        self.armed = 0
        self.last_progress = now
        self.stalled = False
        self.stalled_at = 0.0


def dump_stacks() -> list[dict]:
    """Every live thread's stack as [{thread, daemon, stack}] — the
    forensic payload (host-only: ``sys._current_frames`` never touches
    jax)."""
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        t = names.get(ident)
        out.append({
            "thread": t.name if t else str(ident),
            "daemon": bool(t.daemon) if t else False,
            "stack": "".join(traceback.format_stack(frame)),
        })
    return out


class Watchdog:
    """Per-channel no-progress detector with forensic stack dumps."""

    def __init__(self, deadline: Optional[float] = None, *,
                 registry: Optional[Registry] = None,
                 store: Optional[TraceStore] = None,
                 poll_interval: Optional[float] = None):
        self.deadline = deadline if deadline is not None else _default_deadline()
        self.registry = registry or REGISTRY
        self.store = store or STORE
        self.poll_interval = poll_interval or max(0.25, self.deadline / 4.0)
        self._lock = threading.Lock()
        # serializes gauge emission: trip and recovery can race (check()
        # marks a channel stalled, then a pulse lands before the trip's
        # gauge write) — every emission re-reads the channel's CURRENT
        # state under this lock, so the last write always tells the truth
        self._gauge_lock = threading.Lock()
        self._channels: dict[str, _Channel] = {}
        self._callbacks: list[Callable[[StallEvent], None]] = []
        # forensic context providers: name -> zero-arg callable returning a
        # JSON-able dict attached to every stall dump (e.g. the scheduler's
        # flight-ring snapshot, so a stall trace carries the engine
        # timeline that preceded the silence)
        self._contexts: dict[str, Callable[[], dict]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- heartbeat API (hot path: two clock reads + one lock) -------------

    def _entry(self, channel: str, now: float) -> _Channel:  # jaxlint: guarded-by(_lock)
        ch = self._channels.get(channel)
        if ch is None:
            ch = self._channels[channel] = _Channel(now)
        return ch

    def pulse(self, channel: str = "engine") -> None:
        """Progress happened on ``channel`` (clears a standing stall)."""
        now = time.monotonic()
        recovered: Optional[StallEvent] = None
        with self._lock:
            ch = self._entry(channel, now)
            if ch.stalled:
                recovered = StallEvent(
                    channel, "recovered", round(now - ch.last_progress, 3)
                )
                ch.stalled = False
            ch.last_progress = now
        if recovered is not None:
            self._emit_clear(channel, recovered)

    def arm(self, channel: str = "engine") -> None:
        """An operation that MUST make progress started on ``channel``.
        The countdown only runs while at least one operation is armed —
        an idle engine can never stall."""
        now = time.monotonic()
        with self._lock:
            ch = self._entry(channel, now)
            if ch.armed == 0:
                ch.last_progress = now  # idle gap is not silence
            ch.armed += 1

    def disarm(self, channel: str = "engine") -> None:
        """The operation finished (counts as progress)."""
        self.pulse(channel)
        with self._lock:
            ch = self._channels.get(channel)
            if ch is not None and ch.armed > 0:
                ch.armed -= 1

    @contextmanager
    def guard(self, channel: str = "engine") -> Iterator[None]:
        """Arm around one blocking device round-trip."""
        self.arm(channel)
        try:
            yield
        finally:
            self.disarm(channel)

    # -- detection --------------------------------------------------------

    def on_stall(self, cb: Callable[[StallEvent], None]) -> None:
        """Register a callback fired on every trip AND recovery (the event's
        ``kind`` distinguishes them). Exceptions are swallowed — forensics
        must never kill the thing they observe."""
        with self._lock:
            self._callbacks.append(cb)

    def remove_callback(self, cb: Callable[[StallEvent], None]) -> None:
        """Unregister a stall callback (supervisors detach at scheduler
        shutdown so a dead engine's closure is not kept alive here)."""
        with self._lock:
            try:
                self._callbacks.remove(cb)
            except ValueError:
                pass

    def add_context(self, name: str, fn: Callable[[], dict]) -> None:
        """Register a forensic context provider: ``fn()`` returns a
        JSON-able dict recorded as a ``context`` event (attr ``source`` =
        ``name``) on every stall trace. Providers must be host-only and
        cheap; exceptions are swallowed per provider."""
        with self._lock:
            self._contexts[name] = fn

    def remove_context(self, name: str) -> None:
        """Unregister a provider (schedulers remove theirs at shutdown so
        a dead engine's closure is not kept alive by the watchdog)."""
        with self._lock:
            self._contexts.pop(name, None)

    def reset(self, channel: str) -> None:
        """Forget a channel's state entirely — armed count included.

        The self-healing rebuild path needs this: a truly wedged engine
        thread is parked inside a ``guard`` it will never exit, so its
        arm() has no matching disarm() and the channel would stay armed
        forever — every later idle gap past the deadline would fire a
        spurious stall (and another rebuild) on a healthy engine. The
        abandoned thread's eventual disarm() on the recreated channel is
        a no-op (disarm only decrements a positive count)."""
        with self._lock:
            self._channels.pop(channel, None)
        self._set_stall_gauge(channel)

    def stalled(self, channel: Optional[str] = None) -> bool:
        with self._lock:
            if channel is not None:
                ch = self._channels.get(channel)
                return bool(ch and ch.stalled)
            return any(c.stalled for c in self._channels.values())

    def status(self) -> dict[str, dict]:
        """Snapshot for /debug/devices: per-channel armed/age/stalled."""
        now = time.monotonic()
        with self._lock:
            return {
                name: {
                    "armed": ch.armed,
                    "stalled": ch.stalled,
                    "last_progress_age_seconds": round(
                        now - ch.last_progress, 3),
                }
                for name, ch in self._channels.items()
            }

    def check(self, now: Optional[float] = None) -> list[StallEvent]:
        """One detection pass (what the background thread runs; tests call
        it directly). Returns the trips it fired."""
        now = time.monotonic() if now is None else now
        trips: list[tuple[str, float]] = []
        with self._lock:
            for name, ch in self._channels.items():
                age = now - ch.last_progress
                if ch.armed > 0:
                    self.registry.last_progress_age.set(
                        round(age, 3), channel=name)
                elif not ch.stalled:
                    # idle channel: a stale age from the last armed scrape
                    # (e.g. a long compile that finished just under the
                    # deadline) must not keep flapping alerts
                    self.registry.last_progress_age.set(0.0, channel=name)
                if ch.armed > 0 and not ch.stalled and age > self.deadline:
                    ch.stalled = True
                    ch.stalled_at = now
                    trips.append((name, age))
        events = [self._emit_stall(name, age) for name, age in trips]
        return events

    # -- event plumbing (never under the channel lock) --------------------

    def _set_stall_gauge(self, channel: str) -> None:
        """Write engine_stalled from the channel's CURRENT state (not the
        event that triggered the write): a recovery racing a trip may emit
        in either order, and re-reading under the gauge lock guarantees
        the final write matches reality — no permanently latched 1."""
        with self._gauge_lock:
            with self._lock:
                ch = self._channels.get(channel)
                stalled = bool(ch and ch.stalled)
            self.registry.engine_stalled.set(
                1 if stalled else 0, channel=channel)
            if not stalled:
                self.registry.last_progress_age.set(0.0, channel=channel)

    def _emit_stall(self, channel: str, age: float) -> StallEvent:
        trace_id = f"stall-{uuid.uuid4().hex[:12]}"
        self.registry.last_progress_age.set(round(age, 3), channel=channel)
        self.registry.stalls.inc(channel=channel)
        self._set_stall_gauge(channel)
        try:
            tr = RequestTrace(
                trace_id, f"stall-{channel}", kind="stall",
                channel=channel,
                last_progress_age_seconds=round(age, 3),
                deadline_seconds=self.deadline,
            )
            stacks = dump_stacks()
            for s in stacks:
                tr.event("thread", **s)
            tr.annotate(threads=len(stacks))
            # attach registered forensic contexts (flight snapshots etc.):
            # the stall dump should answer "what was the engine doing for
            # the last N dispatches", not just "where is it parked now"
            with self._lock:
                contexts = list(self._contexts.items())
            for name, fn in contexts:
                try:
                    tr.event("context", source=name, **fn())
                except Exception:  # noqa: BLE001 — one provider ≠ the dump
                    tr.event("context", source=name, error="provider failed")
            self.store.record(tr)
        except Exception:  # noqa: BLE001 — forensics must not throw
            trace_id = ""
        event = StallEvent(channel, "stall", round(age, 3), trace_id)
        self._fire(event)
        return event

    def _emit_clear(self, channel: str, event: StallEvent) -> None:
        self._set_stall_gauge(channel)
        self._fire(event)

    def _fire(self, event: StallEvent) -> None:
        with self._lock:
            callbacks = list(self._callbacks)
        for cb in callbacks:
            try:
                cb(event)
            except Exception:  # noqa: BLE001
                pass

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Idempotent; the thread is a daemon and shared freely."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="stall-watchdog", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the watchdog outlives bugs
                pass

    def stop(self) -> None:
        self._stop.set()
        # claim the thread under the lock so a racing start()/stop() pair
        # can't both join (or leak) the same thread; join OUTSIDE the
        # lock — holding it across a 5 s join would block start()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=5)


# the process-wide watchdog (runner/scheduler/worker default to it);
# its thread starts when the first Scheduler calls start()
WATCHDOG = Watchdog()
