"""Fleet-wide telemetry plane: one pane of glass for a replicated model.

Since the fleet tier (PRs 7/12) a request can route through the front
door, a worker process, and a remote host — but every replica records its
spans, flight ring, and step-time percentiles into ITS OWN process.  This
module is the stitching half of the ``GetTelemetry`` harvest RPC: the
front door pulls each replica's pane (trace spans for one trace id, a
flight-ring snapshot, the scheduler metrics dict) and merges them into
the single views the operator actually reads:

  * :func:`stitch` — one waterfall per trace id: front-door spans and
    replica-side engine spans in one time-ordered tree, every remote span
    tagged ``replica=`` (``GET /v1/traces/{id}``, ``/debug/timeline/{id}``);
  * :func:`fleet_flight` — per-replica flight rings merged into one table
    with a ``replica`` column (``GET /debug/fleet/flight``).

**Clock skew.**  Monotonic clocks do not compare across processes, and
wall clocks do not compare across hosts.  Remote span trees are therefore
*anchored*: the remote trace's root is pinned to the local RPC span's
start (:func:`anchor_trace` — the dispatch RPC is the one event both
sides observed), so remote offsets are exact *relative to each other* and
correct to within the RPC's network latency relative to local spans.  The
applied shift is recorded as ``skew_offset_ms`` on every anchored trace
so a suspicious waterfall can be audited.

Everything here is host-side dict surgery — no device reads, no jax.
The module deliberately imports nothing from ``localai_tpu.fleet``:
replicas are duck-typed (``telemetry()``/``id``/``state``), so the obs
plane observes the fleet without depending on it.
"""

from __future__ import annotations

import logging
from typing import Any

log = logging.getLogger(__name__)

# flight records harvested per replica by default (one /debug/flight page)
DEFAULT_FLIGHT_LIMIT = 256
# recent request traces returned by a trace-id-less harvest
DEFAULT_RECENT = 20


def telemetry_payload(scheduler: Any, *, trace_id: str = "",
                      since: float = 0.0, limit: int = DEFAULT_FLIGHT_LIMIT,
                      recent: int = DEFAULT_RECENT,
                      store: Any = None) -> dict:
    """One replica's telemetry pane, built IN the replica's process.

    The single source of the GetTelemetry response shape — the gRPC
    servicer (worker/server.py) and ``InProcessReplica.telemetry`` both
    call this, so the two replica kinds cannot drift.  ``scheduler`` may
    be ``None`` (worker with no model loaded): the trace harvest still
    answers.
    """
    from localai_tpu.obs.trace import STORE

    store = store if store is not None else STORE
    if trace_id:
        hits = store.find(trace_id)
    else:
        hits = store.recent(limit=max(0, recent), kind="request")
    payload: dict = {"traces": [t.to_dict() for t in hits],
                     "flight": None, "metrics": {}}
    if scheduler is None:
        return payload
    flight = getattr(scheduler, "flight", None)
    if flight is not None:
        payload["flight"] = {
            # limit <= 0 = "spans only, skip the rows" (the trace-stitch
            # harvest); percentiles/counters are cheap and always ride
            "records": (flight.snapshot(since=since, limit=limit)
                        if limit > 0 else []),
            "percentiles": flight.percentiles(),
            # dispatch anatomy (obs.anatomy): windowed phase breakdown +
            # host/bubble fractions, so the fleet view gets per-replica
            # bubble columns without a second RPC
            "anatomy": flight.phases(
                window_s=60.0) if hasattr(flight, "phases") else None,
            "dispatches": flight.count,
            "tokens_total": flight.total_tokens,
            "capacity": flight.capacity,
        }
    try:
        payload["metrics"] = scheduler.metrics()
    except Exception as e:  # noqa: BLE001 — a stats hiccup ≠ no pane
        payload["metrics"] = {"error": str(e)}
    # this process's usage-ledger pane (obs.ledger): per-tenant panes +
    # waste decomposition. A worker process's ledger is fed by ITS
    # engine, so the front door can drill into per-replica attribution —
    # the harvest view keys these by replica and never sums them into
    # the front-door totals (the front door's own ledger already counts
    # every tenant-stamped request once)
    try:
        from localai_tpu.obs.ledger import LEDGER

        payload["usage"] = LEDGER.snapshot()
    except Exception as e:  # noqa: BLE001 — usage pane ≠ telemetry
        payload["usage"] = {"error": str(e)}
    return payload


# -- skew anchoring ----------------------------------------------------------


def anchor_trace(trace: dict, anchor_unix: float, *,
                 replica: str = "") -> dict:
    """Shift a harvested trace dict so its root starts at ``anchor_unix``
    (the local endpoint of the event both clocks observed — the dispatch
    RPC span's start).  Children shift by the same offset, so remote
    durations and relative ordering are preserved exactly; only the
    absolute placement is corrected.  Returns a new dict tagged with
    ``replica`` and the applied ``skew_offset_ms``."""
    offset = anchor_unix - float(trace.get("start_unix") or anchor_unix)
    out = dict(trace)
    out["start_unix"] = round(float(trace.get("start_unix", 0.0)) + offset, 6)
    attrs = dict(out.get("attrs") or {})
    if replica:
        attrs["replica"] = replica
    attrs["skew_offset_ms"] = round(offset * 1e3, 3)
    attrs["skew_anchored"] = True
    out["attrs"] = attrs
    children = []
    for span in trace.get("children", ()):  # each span shifts rigidly
        s = dict(span)
        if s.get("start_unix") is not None:
            s["start_unix"] = round(float(s["start_unix"]) + offset, 6)
        if replica:
            s["attrs"] = {**(s.get("attrs") or {}), "replica": replica}
        children.append(s)
    out["children"] = children
    return out


def replica_anchors(local_traces: list[dict]) -> dict[str, float]:
    """``{replica id: local anchor start_unix}`` from the front door's own
    spans: the ``rpc`` span records which replica served the dispatch, the
    ``prefix_transfer`` span which prefill/decode pair ran the handoff.
    First span wins per replica (a failover's second rpc span anchors the
    replica that actually served)."""
    anchors: dict[str, float] = {}
    for tr in local_traces:
        for span in tr.get("children", ()):
            attrs = span.get("attrs") or {}
            start = span.get("start_unix")
            if start is None:
                continue
            for key in ("replica", "prefill", "decode"):
                rid = attrs.get(key)
                if rid and rid not in anchors:
                    anchors[rid] = float(start)
    return anchors


def replica_ids_for_trace(local_traces: list[dict]) -> set[str]:
    """Every replica id the front door's spans say took part in this
    trace (dispatch targets, failover attempts, disagg prefill/decode)."""
    rids = set(replica_anchors(local_traces))
    for tr in local_traces:
        attrs = tr.get("attrs") or {}
        for key in ("replica", "prefill_replica"):
            if attrs.get(key):
                rids.add(attrs[key])
    return rids


# -- stitching ---------------------------------------------------------------


def _pull_panes(targets: list[tuple[str, Any]]) -> dict[str, dict]:
    """Run one bounded ``telemetry()`` pull per replica CONCURRENTLY:
    wedged replicas burn their deadlines in parallel, so the endpoint
    pays ~one fleet RPC deadline total, not one per wedged peer.
    ``telemetry()`` never raises (errors come back as unreachable
    panes), so gathering the futures is exception-free."""
    if not targets:
        return {}
    if len(targets) == 1:
        rid, fn = targets[0]
        return {rid: fn()}
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(8, len(targets)),
                            thread_name_prefix="fleetview-pull") as ex:
        futures = [(rid, ex.submit(fn)) for rid, fn in targets]
        return {rid: f.result() for rid, f in futures}


def harvest_for_trace(sm: Any, trace_id: str,
                      local_traces: list[dict]) -> dict[str, dict]:
    """Pull the remote half of one trace from every replica the local
    spans name.  One bounded ``telemetry()`` call per replica, run
    concurrently — NEVER on the event loop (the HTTP handlers run this
    in an executor); a wedged replica degrades to an error pane, not a
    hung endpoint."""
    pool = getattr(sm, "pool", None)
    if pool is None:
        return {}
    rids = replica_ids_for_trace(local_traces)
    targets = [
        (r.id, lambda tele=r.telemetry: tele(trace_id=trace_id,
                                             limit=0, recent=0))
        for r in pool.members()
        if r.id in rids and getattr(r, "telemetry", None) is not None
    ]
    return _pull_panes(targets)


def stitch(trace_id: str, local_traces: list[dict],
           harvested: dict[str, dict]) -> dict:
    """Merge the front door's traces with each replica's harvested half
    into ONE waterfall.  Local spans keep their clocks; remote span trees
    are skew-anchored to the local rpc/prefix_transfer span for their
    replica (falling back to the earliest local root when the local spans
    never named the replica).  In-process replicas share the front door's
    trace store and mark their payloads ``shared_store``: their harvested
    traces already present locally (same trace id + request id) are
    dropped rather than duplicated.  Cross-process panes are NEVER
    deduped — request ids are per-process counters, so a worker's
    ``model-0`` legitimately coexists with the front door's
    ``model-0``."""
    anchors = replica_anchors(local_traces)
    fallback = min((float(t["start_unix"]) for t in local_traces
                    if t.get("start_unix") is not None),
                   default=0.0)
    seen = {(t.get("trace_id"), t.get("request_id")) for t in local_traces}
    panes: dict[str, dict] = {}
    stitched: list[dict] = []
    for rid, payload in harvested.items():
        if not isinstance(payload, dict) or payload.get("error"):
            panes[rid] = {
                "unreachable": True,
                "error": (payload or {}).get("error", "no payload"),
            }
            continue
        shared = bool(payload.get("shared_store"))
        anchored = []
        for rt in payload.get("traces", ()):
            if shared and (rt.get("trace_id"),
                           rt.get("request_id")) in seen:
                continue  # in-process replica: already in the local store
            anchored.append(anchor_trace(
                rt, anchors.get(rid, fallback), replica=rid))
        panes[rid] = {"traces": anchored}
        stitched.extend(anchored)
    events: list[dict] = []
    all_traces = list(local_traces) + stitched
    origin = min((float(t["start_unix"]) for t in all_traces
                  if t.get("start_unix") is not None), default=0.0)
    known = set(harvested) | set(anchors)
    for tr in all_traces:
        attrs = tr.get("attrs") or {}
        # the replica column means "recorded ON replica X", not "served
        # by X": harvested trees carry their replica from anchoring; an
        # in-process replica's engine trace sits in the LOCAL store under
        # its rid as the model name (PR 7 per-replica identities); the
        # front door's own spans stay untagged
        if attrs.get("skew_anchored"):
            rid = attrs.get("replica", "")
        elif tr.get("model") in known:
            rid = tr["model"]
        else:
            rid = ""
        for span in tr.get("children", ()):
            sa = span.get("attrs") or {}
            events.append({
                # strictly "recorded ON" — a front-door rpc span's attrs
                # still say which replica it dispatched to
                "replica": rid,
                "source": tr.get("request_id", ""),
                "kind": tr.get("kind", ""),
                "name": span.get("name", ""),
                "offset_ms": round(
                    (float(span.get("start_unix") or origin) - origin) * 1e3,
                    3),
                "duration_ms": span.get("duration_ms"),
                "attrs": sa,
            })
    events.sort(key=lambda e: e["offset_ms"])
    return {
        "trace_id": trace_id,
        "start_unix": round(origin, 6),
        "traces": local_traces,
        "replicas": panes,
        "waterfall": events,
    }


def stitched_trace(sm: Any, trace_id: str,
                   local_traces: list[dict]) -> dict:
    """harvest + stitch in one call (the ``/v1/traces/{id}`` body)."""
    return stitch(trace_id, local_traces,
                  harvest_for_trace(sm, trace_id, local_traces))


# -- fleet flight merge ------------------------------------------------------


def fleet_flight(sm: Any, *, since: float = 0.0,
                 limit: int = DEFAULT_FLIGHT_LIMIT) -> dict:
    """Merge every replica's flight ring into one table with a
    ``replica`` column.  Rows are ordered by their wall-clock stamp
    (``ts_unix``) — an approximation across hosts (wall clocks skew where
    monotonic clocks don't exist at all), good enough for the "what was
    the FLEET doing" read this view exists for; per-replica sections keep
    the exact per-replica ordering.  Unhealthy or wedged replicas degrade
    to a ``state``/``unreachable`` pane, never a failed endpoint."""
    pool = getattr(sm, "pool", None)
    if pool is None:
        return {"replicas": {}, "records": []}
    panes: dict[str, dict] = {}
    merged: list[dict] = []
    targets: list[tuple[str, Any]] = []
    states: dict[str, str] = {}
    for r in pool.members():
        states[r.id] = r.state
        if r.state != "healthy":
            panes[r.id] = {"state": r.state}
            continue
        tele = getattr(r, "telemetry", None)
        if tele is None:
            panes[r.id] = {"state": r.state,
                           "error": "no telemetry surface"}
            continue
        targets.append((r.id, lambda tele=tele: tele(
            trace_id="", since=since, limit=limit, recent=0)))
    for rid, payload in _pull_panes(targets).items():
        state = states.get(rid, "")
        if not isinstance(payload, dict) or payload.get("error"):
            panes[rid] = {
                "state": state, "unreachable": True,
                "error": (payload or {}).get("error", "no payload"),
            }
            continue
        flight = payload.get("flight") or {}
        records = flight.get("records") or []
        # anatomy pane is .get()-guarded throughout: a mixed-version
        # fleet where some replicas predate the phase columns degrades
        # to None fractions / blank columns, never a KeyError
        anatomy = flight.get("anatomy") or {}
        panes[rid] = {
            "state": state,
            "records": len(records),
            "percentiles": flight.get("percentiles"),
            "anatomy": flight.get("anatomy"),
            "host_overhead_fraction": anatomy.get("host_overhead_fraction"),
            "device_bubble_fraction": anatomy.get("device_bubble_fraction"),
            "dispatches": flight.get("dispatches"),
            "tokens_total": flight.get("tokens_total"),
        }
        for rec in records:
            row = {**rec, "replica": rid}
            for ph in ("gap_ms", "sched_ms", "launch_ms", "sync_ms"):
                row.setdefault(ph, None)  # old-version replica → blank
            merged.append(row)
    merged.sort(key=lambda rec: rec.get("ts_unix") or 0.0)
    return {"replicas": panes, "records": merged, "count": len(merged)}


# -- fleet usage harvest -----------------------------------------------------


def fleet_usage(sm: Any) -> dict:
    """Per-replica usage-ledger panes (obs.ledger snapshots) for one
    fleet-served model — the drill-down half of ``GET /v1/usage``.  Keyed
    by replica id and deliberately NOT summed: the front door's own
    ledger already counts every tenant-stamped request exactly once
    ("whoever stamped the tenant owns the feed"), so these panes answer
    "which replica did tenant X's work", not "how much work was done".
    Unhealthy/wedged replicas degrade to an error pane, never a failed
    endpoint."""
    pool = getattr(sm, "pool", None)
    if pool is None:
        return {}
    targets: list[tuple[str, Any]] = []
    panes: dict[str, dict] = {}
    for r in pool.members():
        if r.state != "healthy":
            panes[r.id] = {"state": r.state}
            continue
        tele = getattr(r, "telemetry", None)
        if tele is None:
            panes[r.id] = {"error": "no telemetry surface"}
            continue
        targets.append((r.id, lambda tele=tele: tele(
            trace_id="", limit=0, recent=0)))
    for rid, payload in _pull_panes(targets).items():
        if not isinstance(payload, dict) or payload.get("error"):
            panes[rid] = {
                "unreachable": True,
                "error": (payload or {}).get("error", "no payload"),
            }
            continue
        usage = payload.get("usage")
        if payload.get("shared_store"):
            # in-process replica: its "ledger" IS the front door's
            # process-global singleton — echoing it per replica would
            # present the same totals N times as if they were distinct
            panes[rid] = {"shared_ledger": True}
        else:
            panes[rid] = usage if isinstance(usage, dict) else {}
    return panes
