"""Durable state for the offline batch subsystem: files + jobs.

Two stores, both JSON-persisted under ``AppConfig.upload_path`` with
atomic writes (tmp + rename), reloaded at boot:

**FileRegistry** — the ONE ``/v1/files`` registry. The assistants API
used to own file persistence (``uploadedFiles.json``); that registry is
extracted here and grows a first-class ``purpose`` field
(``assistants`` | ``batch`` | ``batch_output``), so batch input uploads,
assistant attachments, and batch result downloads all flow through the
same metadata + traversal-guarded content path. ``AssistantStore`` now
delegates to a shared instance — existing assistants routes/tests are
unchanged.

**BatchStore** — OpenAI-Batch-shaped job records (``batches.json``) with
crash-safe state transitions::

    validating ──► in_progress ──► completed
        │               │      └─► failed
        └───────────────┴──────────► cancelled / expired

Transitions are validated (an illegal edge raises), stamped
(``in_progress_at``/``completed_at``/...), and persisted atomically.
Line-level durability is append-only JSONL: the executor appends one
result (or error) record per input line and flushes before counting it
done, so a crash mid-job loses at most the in-flight lines — on reload
the executor re-derives the done-set from the output/error files and
resumes from the first missing ``custom_id``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path
from typing import Optional

from localai_tpu.utils.paths import verify_path

log = logging.getLogger(__name__)

UPLOADED_FILES_FILE = "uploadedFiles.json"
BATCHES_FILE = "batches.json"
# batch job state + per-line artifacts live under this subdirectory of
# the upload dir: register_bytes writes BASENAMES into the upload root,
# so a crafted upload can never collide with (and poison) job state
JOBS_SUBDIR = "batch_jobs"
# upload-root filenames a client may not claim (the registry's own
# persistence — an upload under this name would be clobbered by the next
# metadata save, or worse, parsed as state on reboot)
RESERVED_NAMES = frozenset({UPLOADED_FILES_FILE})

FILE_PURPOSES = ("assistants", "batch", "batch_output")

# legal lifecycle edges (OpenAI Batch states; "cancelling" is collapsed
# into an immediate cancel — the executor observes it within one poll)
TERMINAL_STATES = frozenset({"completed", "failed", "cancelled", "expired"})
_TRANSITIONS = {
    "validating": {"in_progress", "failed", "cancelled", "expired"},
    "in_progress": {"completed", "failed", "cancelled", "expired"},
}


def _id_num(s: str, prefix: str) -> int:
    try:
        return int(s.removeprefix(prefix))
    except ValueError:
        return 0


def _atomic_save(path: Path, data: list[dict]) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(data, indent=2))
    tmp.replace(path)


def _load(path: Path) -> list[dict]:
    try:
        data = json.loads(path.read_text())
        return data if isinstance(data, list) else []
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as e:
        log.warning("cannot load %s: %s", path, e)
        return []


class FileRegistry:
    """The unified ``/v1/files`` metadata registry + content store."""

    def __init__(self, upload_dir: str | Path):
        self.upload_dir = Path(upload_dir)
        self._lock = threading.Lock()
        self.files: list[dict] = _load(self.upload_dir / UPLOADED_FILES_FILE)
        # ids continue past the largest persisted one, so restarts never
        # mint colliding file ids (same divergence as AssistantStore)
        self._next = 1 + max(
            [_id_num(f.get("id", ""), "file-") for f in self.files] + [0]
        )

    def _save(self) -> None:  # jaxlint: guarded-by(_lock)
        _atomic_save(self.upload_dir / UPLOADED_FILES_FILE, self.files)

    def next_id(self) -> str:
        with self._lock:
            n = self._next
            self._next += 1
            return f"file-{n}"

    # -- write -----------------------------------------------------------

    def register_bytes(self, filename: str, content: bytes,
                       purpose: str) -> dict:
        """Persist an upload: content under the upload dir (basename only,
        traversal-guarded), metadata in the registry. Raises ValueError on
        a bad filename or a name collision."""
        safe_name = Path(filename).name or "upload"
        if safe_name in RESERVED_NAMES or safe_name == JOBS_SUBDIR:
            raise ValueError(f"filename {safe_name!r} is reserved")
        save_path = verify_path(safe_name, self.upload_dir)
        if save_path.exists():
            raise ValueError("File already exists")
        self.upload_dir.mkdir(parents=True, exist_ok=True)
        save_path.write_bytes(content)
        return self._register(safe_name, len(content), purpose)

    def register_path(self, path: Path, purpose: str) -> dict:
        """Register a file ALREADY written inside the upload dir — at any
        depth (the batch executor's artifacts live in the ``batch_jobs``
        subdirectory). The stored filename is the path RELATIVE to the
        upload dir, so content lookups stay traversal-guarded."""
        rel = Path(path).resolve().relative_to(self.upload_dir.resolve())
        return self._register(rel.as_posix(),
                              Path(path).stat().st_size, purpose)

    def _register(self, name: str, size: int, purpose: str) -> dict:
        f = {
            "id": self.next_id(),
            "object": "file",
            "bytes": size,
            "created_at": int(time.time()),
            "filename": name,
            "purpose": purpose,
        }
        with self._lock:
            self.files.append(f)
            self._save()
        return f

    def delete(self, fid: str) -> bool:
        """Remove metadata + content; True when the id existed. Missing
        content is not an error (metadata cleanup proceeds — files.go
        parity)."""
        with self._lock:
            f = next((x for x in self.files if x["id"] == fid), None)
            if f is None:
                return False
            try:
                verify_path(f["filename"], self.upload_dir).unlink()
            except (FileNotFoundError, ValueError):
                pass
            self.files = [x for x in self.files if x["id"] != fid]
            self._save()
        return True

    # -- read ------------------------------------------------------------

    # lock-free readers: ``files`` only ever grows via append or is
    # rebound to a fresh list under the lock — a scan sees a complete
    # (possibly one-entry-stale) snapshot, which the HTTP tier tolerates
    def get(self, fid: str) -> Optional[dict]:  # jaxlint: disable=lock-guarded-attr
        return next((f for f in self.files if f["id"] == fid), None)

    def list(self, purpose: str = "") -> list[dict]:  # jaxlint: disable=lock-guarded-attr
        return [f for f in self.files
                if not purpose or f.get("purpose") == purpose]

    def content_path(self, fid: str) -> Optional[Path]:
        f = self.get(fid)
        if f is None:
            return None
        return verify_path(f["filename"], self.upload_dir)


class BatchStore:
    """Durable batch-job records with validated state transitions."""

    def __init__(self, upload_dir: str | Path, registry: FileRegistry,
                 *, expiry_h: float = 24.0):
        self.upload_dir = Path(upload_dir)
        # job state + artifacts in a subdir the upload API cannot name
        # (register_bytes strips paths to basenames): a crafted upload
        # can neither pre-seed an output file nor plant a batches.json
        self.jobs_dir = self.upload_dir / JOBS_SUBDIR
        self.registry = registry
        self.expiry_h = expiry_h
        self._lock = threading.Lock()
        self.jobs: list[dict] = _load(self.jobs_dir / BATCHES_FILE)
        self._next = 1 + max(
            [_id_num(j.get("id", ""), "batch_") for j in self.jobs] + [0]
        )

    def _save(self) -> None:  # jaxlint: guarded-by(_lock)
        _atomic_save(self.jobs_dir / BATCHES_FILE, self.jobs)

    # -- job lifecycle ----------------------------------------------------

    def create(self, *, endpoint: str, input_file_id: str,
               completion_window: str = "24h",
               metadata: Optional[dict] = None) -> dict:
        with self._lock:
            bid = f"batch_{self._next}"
            self._next += 1
            job = {
                "id": bid,
                "object": "batch",
                "endpoint": endpoint,
                "input_file_id": input_file_id,
                "completion_window": completion_window,
                "status": "validating",
                "output_file_id": None,
                "error_file_id": None,
                "created_at": int(time.time()),
                "in_progress_at": None,
                "completed_at": None,
                "failed_at": None,
                "cancelled_at": None,
                "expired_at": None,
                "request_counts": {"total": 0, "completed": 0, "failed": 0},
                "metadata": metadata or {},
            }
            self.jobs.append(job)
            self._save()
        return job

    # lock-free readers (same contract as FileRegistry): ``jobs`` only
    # appends, and job dicts are merged under the lock — pollers tolerate
    # a one-transition-stale view
    def get(self, bid: str) -> Optional[dict]:  # jaxlint: disable=lock-guarded-attr
        return next((j for j in self.jobs if j["id"] == bid), None)

    def list(self) -> list[dict]:  # jaxlint: disable=lock-guarded-attr
        return list(self.jobs)

    def transition(self, bid: str, status: str, **updates) -> dict:
        """Move a job along a legal lifecycle edge, stamp the matching
        ``<status>_at`` timestamp, merge ``updates``, persist atomically.
        Raises ValueError on an unknown job or an illegal edge — the state
        machine is the crash-safety contract, so it is enforced, not
        advisory."""
        with self._lock:
            job = self.get(bid)
            if job is None:
                raise ValueError(f"unknown batch {bid!r}")
            cur = job["status"]
            if status != cur:
                if status not in _TRANSITIONS.get(cur, ()):  # terminal too
                    raise ValueError(
                        f"illegal batch transition {cur!r} → {status!r}")
                job["status"] = status
                stamp = f"{status}_at"
                if stamp in job and job[stamp] is None:
                    job[stamp] = int(time.time())
            job.update(updates)
            self._save()
        return job

    def update(self, bid: str, persist: bool = True, **updates) -> dict:
        """Update non-state fields (request_counts, output_file_id, ...).
        ``persist=False`` touches only the in-memory record — the batch
        executor uses it for per-line progress counts, which re-derive
        from the durable output/error files on crash-resume, so a full
        ``batches.json`` rewrite per drained line would buy nothing."""
        with self._lock:
            job = self.get(bid)
            if job is None:
                raise ValueError(f"unknown batch {bid!r}")
            job.update(updates)
            if persist:
                self._save()
        return job

    def cancel(self, bid: str) -> Optional[dict]:
        """API-side cancel: non-terminal → cancelled (the executor notices
        within one poll and abandons in-flight lines). Terminal jobs are
        returned unchanged; unknown → None. Tolerates the executor racing
        this check into a terminal state — a cancel of a just-completed
        job returns its terminal record, never an error."""
        job = self.get(bid)
        if job is None:
            return None
        if job["status"] in TERMINAL_STATES:
            return job
        try:
            return self.transition(bid, "cancelled")
        except ValueError:
            # the executor finished the job between the check and the
            # transition; its terminal state stands
            return self.get(bid)

    def runnable(self) -> Optional[dict]:  # jaxlint: disable=lock-guarded-attr
        """Oldest non-terminal job (FIFO — one active job at a time keeps
        the background lane's footprint predictable)."""
        live = [j for j in self.jobs if j["status"] not in TERMINAL_STATES]
        return min(live, key=lambda j: j["created_at"]) if live else None

    def expire_due(self, now: Optional[float] = None) -> list[dict]:  # jaxlint: disable=lock-guarded-attr
        """Expire non-terminal jobs older than the expiry horizon."""
        now = time.time() if now is None else now
        horizon = self.expiry_h * 3600.0
        out = []
        for j in list(self.jobs):
            if (j["status"] not in TERMINAL_STATES
                    and now - j["created_at"] > horizon):
                out.append(self.transition(j["id"], "expired"))
        return out

    # -- line-level durability (append-only JSONL) ------------------------

    def output_path(self, job: dict) -> Path:
        return self.jobs_dir / f"{job['id']}_output.jsonl"

    def error_path(self, job: dict) -> Path:
        return self.jobs_dir / f"{job['id']}_error.jsonl"

    def append_line(self, path: Path, record: dict) -> None:
        """One durable result line: append + flush + fsync, so a line
        counted completed survives the process dying right after."""
        import os

        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def done_custom_ids(self, job: dict,
                        include_synthetic: bool = True) -> set[str]:
        """The crash-resume set: custom_ids already durably recorded in
        the output or error file (malformed lines are skipped — they were
        mid-write when the process died, and their line re-runs).

        Records flagged ``synthetic_id`` (validation failures on lines
        that never declared a custom_id — their id is a made-up
        ``line-N``) are excluded with ``include_synthetic=False``: the
        executor's drain filter must not let a synthetic id shadow a
        REAL custom_id that happens to spell ``line-N``."""
        done: set[str] = set()
        for path in (self.output_path(job), self.error_path(job)):
            try:
                text = path.read_text()
            except FileNotFoundError:
                continue
            for line in text.splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not include_synthetic and rec.get("synthetic_id"):
                    continue
                cid = rec.get("custom_id")
                if cid:
                    done.add(str(cid))
        return done

    # -- observability ----------------------------------------------------

    def export_gauges(self, registry=None) -> None:  # jaxlint: disable=lock-guarded-attr
        """Refresh ``localai_batch_jobs{state}`` at /metrics scrape time
        (every state gets a series, so dashboards can key on zeros)."""
        from localai_tpu.obs.metrics import REGISTRY

        reg = registry or REGISTRY
        counts = {s: 0 for s in
                  ("validating", "in_progress", *sorted(TERMINAL_STATES))}
        for j in self.jobs:
            counts[j["status"]] = counts.get(j["status"], 0) + 1
        for state, n in counts.items():
            reg.batch_jobs.set(n, state=state)
