"""Offline batch-inference subsystem (OpenAI Batch API shape).

``store`` holds the durable halves — the unified ``/v1/files``
:class:`~localai_tpu.batch.store.FileRegistry` and the crash-safe
:class:`~localai_tpu.batch.store.BatchStore` job records —
``executor`` drains jobs through the engine scheduler's background
priority lane (``engine.scheduler.PRIORITY_BATCH``), and
``api.batches`` exposes the HTTP surface.
"""

from localai_tpu.batch.executor import BatchExecutor
from localai_tpu.batch.store import BatchStore, FileRegistry

__all__ = ["BatchExecutor", "BatchStore", "FileRegistry"]
