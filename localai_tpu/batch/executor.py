"""Background batch executor: drains JSONL jobs through the engine's
batch lane.

One daemon thread owns the whole offline workload:

  * claims the oldest runnable job from the :class:`~localai_tpu.batch.
    store.BatchStore`, parses its input JSONL, and validates every line
    against the existing wire schema (``api/schema.py`` —
    ``OpenAIRequest``): bad JSON, a missing/duplicate ``custom_id``, an
    unsupported URL, or a schema violation becomes a durable error-file
    record, never a crash;
  * submits valid lines through ``Scheduler.submit`` at
    ``PRIORITY_BATCH`` with bounded in-flight concurrency
    (``--batch-concurrency``), so batch work only ever fills slots the
    interactive lane left idle;
  * **pauses entirely while the SLO observatory reports overload
    shedding for the job's model**: in-flight lines are cancelled and
    requeued (their slots free immediately, nothing is recorded as
    failed), ``localai_batch_lane_paused`` flips to 1, and the lane
    resumes on its own when the observatory recovers — batch work is
    invisible to interactive TTFT/TPOT SLOs by construction;
  * appends one result record per line (flush+fsync before counting it
    done), so a crash loses at most the in-flight lines and a restarted
    executor resumes from the durable done-set
    (:meth:`BatchStore.done_custom_ids`).

Each job leaves a ``kind="batch"`` trace (validate/run spans, line
counts) in the trace store, and every drained line counts into
``localai_batch_lines_total{result=...}``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from localai_tpu.api import schema as sc
from localai_tpu.engine.scheduler import PRIORITY_BATCH
from localai_tpu.obs import slo as obs_slo
from localai_tpu.obs import trace as obs_trace
from localai_tpu.obs.metrics import REGISTRY

log = logging.getLogger(__name__)

SUPPORTED_URLS = ("/v1/chat/completions", "/v1/completions")


class LineError(ValueError):
    """A per-line validation failure (becomes an error-file record).

    ``custom_id`` carries the line's REAL custom_id whenever the line got
    far enough to declare one, so clients can reconcile error records
    against the ids they submitted; empty only for lines that are not
    valid JSON objects (those get a synthetic ``line-N`` id)."""

    def __init__(self, message: str, custom_id: str = ""):
        super().__init__(message)
        self.custom_id = custom_id


def _count_lines(path) -> int:
    try:
        return sum(1 for l in path.read_text().splitlines() if l.strip())
    except FileNotFoundError:
        return 0


def parse_line(raw: str, lineno: int, endpoint: str,
               seen: set[str]) -> tuple[str, sc.OpenAIRequest, dict]:
    """One input JSONL line → (custom_id, validated request, body dict).
    Raises :class:`LineError` with a client-readable message."""
    try:
        obj = json.loads(raw)
    except ValueError as e:
        raise LineError(f"line {lineno}: invalid JSON: {e}") from None
    if not isinstance(obj, dict):
        raise LineError(f"line {lineno}: not a JSON object")
    cid = str(obj.get("custom_id") or "")
    if not cid:
        raise LineError(f"line {lineno}: custom_id is required")
    if cid in seen:
        # deliberately NOT tagged with the real id: the first occurrence
        # owns it, and an error record carrying it would poison the
        # done-set (done_custom_ids reads the error file too) and skip
        # the valid line
        raise LineError(f"line {lineno}: duplicate custom_id {cid!r}")
    if (obj.get("method") or "POST").upper() != "POST":
        raise LineError(f"line {lineno}: method must be POST",
                        custom_id=cid)
    url = obj.get("url") or endpoint
    if url != endpoint:
        raise LineError(
            f"line {lineno}: url {url!r} does not match batch endpoint "
            f"{endpoint!r}", custom_id=cid)
    body = obj.get("body")
    if not isinstance(body, dict):
        raise LineError(f"line {lineno}: body must be a JSON object",
                        custom_id=cid)
    try:
        req = sc.OpenAIRequest.model_validate(body)
    except Exception as e:  # pydantic ValidationError → line error
        raise LineError(f"line {lineno}: invalid request: {e}",
                        custom_id=cid) from None
    if isinstance(req.prompt, list):
        raise LineError(
            f"line {lineno}: list prompts are not supported in batch "
            "(one prompt per line)", custom_id=cid)
    req.stream = False  # there is no client to stream to
    return cid, req, body


class BatchExecutor:
    """The background-lane drain thread (one per process)."""

    def __init__(self, store, get_serving: Callable[[str], tuple[Any, Any]],
                 *, concurrency: int = 2, poll_s: float = 0.25,
                 deadline_s: Optional[float] = None,
                 slo: Optional[obs_slo.SLOTracker] = None,
                 registry=None, trace_store=None):
        self.store = store
        # model name → (serving model, model config); blocking (lazy
        # weight load) — only ever called from this executor's thread
        self.get_serving = get_serving
        self.concurrency = max(1, concurrency)
        self.poll_s = poll_s
        # per-line wall-clock deadline (the same knob as the interactive
        # tier's request deadline): a wedged generation must not pin the
        # executor forever — on expiry the handle is cancelled, the line
        # records a timeout error, and the drain moves on even if the
        # engine itself never responds
        from localai_tpu.api.inference import request_deadline_s

        self.deadline_s = (deadline_s if deadline_s and deadline_s > 0
                           else request_deadline_s())
        self.slo = slo or obs_slo.SLO
        self.registry = registry or REGISTRY
        self.trace_store = trace_store or obs_trace.STORE
        self._wake = threading.Event()
        self._stopping = False
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.paused = False  # mirror of the lane-paused gauge (tests/UI)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Idempotent thread start (AppState calls this at boot when jobs
        survived a restart, and on every job creation)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, name="batch-executor", daemon=True
            )
            self._thread.start()

    def wake(self) -> None:
        self._wake.set()

    def stop(self, timeout: float = 10.0) -> None:
        # flip the flag under the lock so a concurrent start() can't
        # observe _stopping=False after this stop claimed the thread;
        # join outside the lock (start() must stay callable meanwhile)
        with self._lock:
            self._stopping = True
            t = self._thread
        self._wake.set()
        if t is not None:
            t.join(timeout)

    # -- main loop ---------------------------------------------------------

    def _run(self) -> None:
        # lock-free poll of the stop flag: a bool read is atomic and the
        # loop only needs eventual visibility (one poll_s of slack)
        while not self._stopping:  # jaxlint: disable=lock-guarded-attr
            try:
                self.store.expire_due()
                job = self.store.runnable()
                if job is None:
                    self._wake.wait(timeout=self.poll_s * 4)
                    self._wake.clear()
                    continue
                self._run_job(job)
            except Exception:  # noqa: BLE001 — executor must not die
                log.exception("batch executor iteration failed")
                time.sleep(self.poll_s)

    def _set_paused(self, paused: bool) -> None:
        if paused != self.paused:
            self.paused = paused
            log.info("batch lane %s", "paused (SLO shedding)" if paused
                     else "resumed")
        self.registry.batch_lane_paused.set(1 if paused else 0)

    def _job_live(self, bid: str) -> bool:
        job = self.store.get(bid)
        # same lock-free stop-flag poll as _run: atomic bool read, the
        # drain loop re-checks every line
        return (job is not None
                and not self._stopping  # jaxlint: disable=lock-guarded-attr
                and job["status"] == "in_progress")

    # -- one job -----------------------------------------------------------

    def _run_job(self, job: dict) -> None:
        bid = job["id"]
        tr = obs_trace.RequestTrace(f"batch-{bid}", bid, kind="batch",
                                    endpoint=job["endpoint"],
                                    input_file_id=job["input_file_id"])
        self.trace_store.start(tr)
        try:
            if job["status"] == "validating":
                tr.begin("validate")
                lines, n_invalid = self._validate(job)
                tr.end("validate", lines=len(lines), invalid=n_invalid)
                if not lines:
                    self._finish(job, tr, "failed")
                    return
                job = self.store.transition(bid, "in_progress")
            else:  # crash-resume: re-parse (errors are already durable)
                tr.begin("validate", resume=True)
                lines, n_invalid = self._validate(job, record_errors=False)
                tr.end("validate", lines=len(lines), invalid=n_invalid,
                       resume=True)
            tr.begin("run")
            self._drain(job, lines)
            tr.end("run")
            job = self.store.get(bid)
            if job["status"] == "in_progress":
                done = self.store.done_custom_ids(job)
                if {cid for cid, _, _ in lines} <= done:
                    self._finish(job, tr, "completed")
                # else: stopped by shutdown mid-job; stays in_progress and
                # resumes from the durable done-set next boot
            else:
                self._finish(job, tr, job["status"], transition=False)
        except Exception as e:  # noqa: BLE001 — a broken job must not wedge
            log.exception("batch job %s failed", bid)
            tr.annotate(error=str(e))
            try:
                self._finish(job, tr, "failed")
            except ValueError:
                pass  # already terminal (e.g. cancelled during the failure)
        finally:
            self._set_paused(False)
            self.store.export_gauges(self.registry)
            self.trace_store.finish(tr)

    def _validate(self, job: dict,
                  record_errors: bool = True) -> tuple[list, int]:
        """Parse + validate the input file. Invalid lines become durable
        error records (once — resume passes record_errors=False); returns
        (valid lines as (custom_id, request, body), invalid count)."""
        meta = self.store.registry.get(job["input_file_id"])
        path = self.store.registry.content_path(job["input_file_id"])
        if meta is None or path is None:
            raise ValueError(
                f"input file {job['input_file_id']!r} not found")
        if meta.get("purpose") != "batch":
            # the API checks this at create time; re-check here so a
            # forged/mutated job record can't point the executor at an
            # arbitrary registry file
            raise ValueError(
                f"input file {job['input_file_id']!r} has purpose "
                f"{meta.get('purpose')!r}, not 'batch'")
        text = path.read_text()
        lines: list[tuple[str, sc.OpenAIRequest, dict]] = []
        seen: set[str] = set()
        # already-durable records (a crash between error appends and the
        # in_progress transition re-enters the record_errors=True branch)
        durable = self.store.done_custom_ids(job) if record_errors else set()
        n_invalid = 0
        # enumerate PHYSICAL lines (blank ones skipped in the loop, not
        # pre-filtered), so "line N" in error records matches the line
        # number the client sees in their editor
        for i, raw in enumerate(text.splitlines()):
            if not raw.strip():
                continue
            try:
                cid, req, body = parse_line(raw, i + 1, job["endpoint"],
                                            seen)
            except LineError as e:
                n_invalid += 1
                # the line's real custom_id when it declared one, so
                # clients can reconcile failures against their ids; the
                # done-set check makes re-validation after a crash
                # idempotent (no duplicate error records). Records
                # falling back to a made-up line-N id are flagged so the
                # drain's resume filter ignores them.
                rid = e.custom_id or f"line-{i + 1}"
                if record_errors and rid not in durable:
                    self._record_error(job, rid, 400, str(e),
                                       synthetic=not e.custom_id)
                continue
            seen.add(cid)
            lines.append((cid, req, body))
        # counts re-derive from the durable output/error files so a
        # crash-resumed job reports its real progress (first pass: the
        # error file holds exactly the invalid lines just recorded)
        self.store.update(job["id"], request_counts={
            "total": len(lines) + n_invalid,
            "completed": _count_lines(self.store.output_path(job)),
            "failed": _count_lines(self.store.error_path(job)),
        })
        return lines, n_invalid

    def _drain(self, job: dict, lines: list) -> None:
        """Submit lines through the batch lane, bounded in-flight, pausing
        (and requeueing in-flight work) while the SLO observatory sheds."""
        bid = job["id"]
        # synthetic line-N error ids excluded: they must not shadow a
        # real custom_id that happens to spell "line-N"
        done = self.store.done_custom_ids(job, include_synthetic=False)
        pending = deque(
            (cid, req, body) for cid, req, body in lines if cid not in done
        )
        # cid → (handle, req, body, sm, cfg, response id, submit time)
        inflight: dict[str, tuple] = {}
        models = {req.model for _, req, _ in pending}

        def lane_paused() -> bool:
            return any(self.slo.shedding(m) for m in models if m)

        while (pending or inflight) and self._job_live(bid):
            # harvest finished generations FIRST — before the pause
            # check. A completion can itself re-trip shedding (its
            # latency is an SLO event), and discarding already-finished
            # work on pause would livelock the job: every recovery's
            # first completion would re-pause the lane and be thrown
            # away. Finished work is paid for; only UNfinished in-flight
            # lines are requeued.
            now = time.monotonic()
            progressed = False
            for cid in list(inflight):
                handle, req, body, sm, cfg, rid, t_sub = inflight[cid]
                if handle._done.is_set():
                    del inflight[cid]
                    self._record_result(job, cid, handle, req, sm, cfg,
                                        rid)
                    progressed = True
                elif now - t_sub > self.deadline_s:
                    # per-line deadline (the interactive tier's request
                    # deadline): cancel and move on WITHOUT waiting for
                    # the engine — a wedged generation must not pin the
                    # whole lane (any late result is simply discarded)
                    handle.cancel()
                    del inflight[cid]
                    self._record_error(
                        job, cid, 504,
                        f"generation exceeded the {self.deadline_s:.0f}s "
                        "deadline and was cancelled")
                    self._bump(job, failed=1)
                    progressed = True
            if lane_paused():
                # pause the WHOLE lane: cancel in-flight generations (the
                # slots free for interactive traffic immediately) and put
                # their lines back — requeued, never failed
                self._set_paused(True)
                for cid, (handle, req, body, *_rest) in inflight.items():
                    handle.cancel()
                    pending.appendleft((cid, req, body))
                inflight.clear()
                time.sleep(self.poll_s)
                continue
            self._set_paused(False)
            while pending and len(inflight) < self.concurrency:
                cid, req, body = pending.popleft()
                try:
                    handle, sm, cfg, rid = self._submit_line(job, req)
                except Exception as e:  # noqa: BLE001 — bad line ≠ dead job
                    self._record_error(job, cid, 500, str(e))
                    self._bump(job, failed=1)
                    continue
                inflight[cid] = (handle, req, body, sm, cfg, rid,
                                 time.monotonic())
            if not progressed:
                time.sleep(self.poll_s / 5)
        if not self._job_live(bid):
            for handle, *_ in inflight.values():
                handle.cancel()
        # progress counts were updated in memory per line; persist the
        # final tally once (counts re-derive from the durable output/
        # error files on crash-resume anyway)
        self.store.update(bid, request_counts=dict(
            self.store.get(bid)["request_counts"]))

    def _submit_line(self, job: dict, req: sc.OpenAIRequest):
        from localai_tpu.api import inference as inf
        from localai_tpu.templates.chat import (
            build_chat_prompt,
            build_completion_prompt,
        )

        sm, base_cfg = self.get_serving(req.model)
        cfg = inf.merge_request(base_cfg, req)
        if job["endpoint"] == "/v1/chat/completions":
            messages = [m.model_dump(exclude_none=True)
                        for m in req.messages]
            if cfg.template.use_tokenizer_template or cfg.template.chat_template:
                from localai_tpu.templates.chat import (
                    apply_tokenizer_template,
                )

                prompt = apply_tokenizer_template(
                    sm.tokenizer, messages,
                    chat_template=cfg.template.chat_template,
                )
            else:
                prompt = build_chat_prompt(sm.templates, cfg, messages)
            rid = sc.new_id("chatcmpl")
        else:
            prompt = build_completion_prompt(
                sm.templates, cfg, str(req.prompt or ""))
            rid = sc.new_id("cmpl")
        gr = inf.build_gen_request(
            sm, cfg, req, prompt,
            correlation_id=f"{job['id']}", trace_id=f"batch-{job['id']}",
            priority=PRIORITY_BATCH,
        )
        return sm.scheduler.submit(gr), sm, cfg, rid

    def _record_result(self, job: dict, cid: str, handle, req, sm, cfg,
                       rid: str) -> None:
        from localai_tpu.api import inference as inf

        if handle.finish_reason == "cancelled":
            # job cancelled between the pause check and drain exit: the
            # line is neither completed nor failed — it re-runs on resume
            return
        if handle.finish_reason == "error" and not handle.text:
            self._record_error(job, cid, 502,
                               "generation failed in the backend")
            self._bump(job, failed=1)
            return
        text = inf.finetune_result(cfg, "", handle.text)
        usage = sc.usage(handle.prompt_tokens, handle.completion_tokens)
        finish = handle.finish_reason or "stop"
        if job["endpoint"] == "/v1/chat/completions":
            body = sc.chat_response(rid, req.model, [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish,
            }], usage)
        else:
            body = sc.completion_response(rid, req.model, [{
                "index": 0, "text": text, "finish_reason": finish,
            }], usage)
        self.store.append_line(self.store.output_path(job), {
            "id": sc.new_id("batch_req"),
            "custom_id": cid,
            "response": {"status_code": 200, "request_id": rid,
                         "body": body},
            "error": None,
        })
        self.registry.batch_lines.inc(result="completed")
        self._bump(job, completed=1)

    def _record_error(self, job: dict, cid: str, code: int,
                      message: str, synthetic: bool = False) -> None:
        rec = {
            "id": sc.new_id("batch_req"),
            "custom_id": cid,
            "response": {"status_code": code,
                         "body": sc.error_body(message, code=code)},
            "error": {"code": str(code), "message": message},
        }
        if synthetic:
            # cid is a made-up line-N (the line never declared one) —
            # flagged so resume filters don't treat it as a real id
            rec["synthetic_id"] = True
        self.store.append_line(self.store.error_path(job), rec)
        self.registry.batch_lines.inc(result="failed")

    def _bump(self, job: dict, completed: int = 0, failed: int = 0) -> None:
        """Per-line progress: in-memory only (live for GET /v1/batches;
        durable truth is the output/error files — _drain persists the
        final tally once)."""
        counts = dict(self.store.get(job["id"])["request_counts"])
        counts["completed"] += completed
        counts["failed"] += failed
        self.store.update(job["id"], persist=False, request_counts=counts)

    def _finish(self, job: dict, tr, status: str,
                transition: bool = True) -> None:
        """Terminal bookkeeping: register output/error files in the
        registry (purpose=batch_output → downloadable at
        /v1/files/{id}/content) and move the job to its terminal state."""
        updates = {}
        for key, path in (("output_file_id", self.store.output_path(job)),
                          ("error_file_id", self.store.error_path(job))):
            if job.get(key) is None and path.exists():
                updates[key] = self.store.registry.register_path(
                    path, "batch_output")["id"]
        if transition:
            job = self.store.transition(job["id"], status, **updates)
        elif updates:
            job = self.store.update(job["id"], **updates)
        tr.annotate(status=job["status"], **job["request_counts"])
        log.info("batch %s → %s (%s)", job["id"], job["status"],
                 job["request_counts"])
