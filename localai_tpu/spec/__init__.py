"""Block-native speculative decoding for the paged+meshed hot path.

One speculation code path for both KV layouts: pluggable drafters
(:mod:`.drafter` — a co-located draft model, or self-drafting n-gram
prompt lookup needing no second model), a verify-k batched target
dispatch (``ModelRunner.verify_async``), and per-slot accept/rollback
inside the compiled program. :class:`.engine.SpecEngine` is the
scheduler-facing lane; ``engine.speculative.SpecDecoder`` remains as a
thin compatibility shim over it."""

from localai_tpu.engine.runner import SKIP
from localai_tpu.spec.drafter import Drafter, ModelDrafter, NGramDrafter
from localai_tpu.spec.engine import SpecEngine, build_spec_engine

__all__ = [
    "SKIP",
    "Drafter",
    "ModelDrafter",
    "NGramDrafter",
    "SpecEngine",
    "build_spec_engine",
]
