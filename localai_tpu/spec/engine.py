"""SpecEngine: block-native speculative decoding for the serving hot path.

Speculative sampling (Leviathan et al., "Fast Inference from Transformers
via Speculative Decoding") composed with block-granular paged KV (Kwon et
al., PagedAttention): a :class:`~localai_tpu.spec.drafter.Drafter`
proposes ``gamma`` tokens per slot, ONE batched target forward scores the
whole window per dispatch (``ModelRunner.verify_async`` — the verify-k
dispatch that amortizes the per-step host round-trip exactly like the
contiguous ``decode_n`` programs), and the on-device accept/sample scan
emits each slot's accepted prefix + correction while rolling that slot's
frontier back independently — co-batched slots never notice a neighbor's
rejection.

Paged targets write draft rows through the block-table mirror into
speculation blocks reserved at admission (``begin_admit(spec_tokens=)``);
a rejected tail is a per-slot position rollback — the table never
changes, the garbage rows (int8 scale rows included) are overwritten
before anything can attend to them. Contiguous targets use the same
verify API over slot rows, so there is exactly ONE speculation code path
for both KV layouts (the old ``engine.speculative.SpecDecoder`` is now a
shim over this class).

The scheduler drives :meth:`step_spec_async` exactly like multi-step
decode; each dispatch returns ``[gamma+1, S]`` token rows where SKIP (-1)
marks positions past a slot's accepted window, and ``observe_window``
folds the drained rows into acceptance telemetry + the drafter's
history."""

from __future__ import annotations

import logging
from typing import Any, Optional

import numpy as np

from localai_tpu.engine.runner import SKIP, ModelRunner
from localai_tpu.faults import registry as _faults
from localai_tpu.spec.drafter import Drafter, ModelDrafter, NGramDrafter

log = logging.getLogger(__name__)


class SpecEngine:
    """Couples a target ModelRunner (paged or contiguous) with a Drafter.

    Implements the scheduler's engine surface (slot lifecycle + spec
    windows) by delegating state ops to the target and proposal ops to
    the drafter. Single-writer threading model: every mutator runs on
    the scheduler's engine thread (or its single-owner recovery thread),
    same as ModelRunner — cross-thread readers (metrics scrapes) only
    see monotone counters."""

    # self-healing: a rebuild re-inits the target AND the drafter (both
    # expose reinit()), unlike the legacy draft-pair design
    supports_rebuild = True

    def __init__(self, target: ModelRunner, drafter: Drafter,
                 gamma: Optional[int] = None,
                 min_accept: Optional[float] = None,
                 cooldown: Optional[int] = None):
        import os
        from collections import deque

        self.target = target
        self.drafter = drafter
        self.gamma = int(gamma if gamma is not None else drafter.gamma)
        if self.gamma != drafter.gamma:
            raise ValueError(
                f"engine gamma {self.gamma} != drafter gamma "
                f"{drafter.gamma}")
        self.num_slots = target.num_slots
        self.max_ctx = target.max_ctx
        self.cfg = target.cfg
        self.paged = bool(getattr(target, "paged", False))
        # host drafters need the previous window drained before proposing
        self.pipeline_safe = bool(drafter.device_proposals)
        # acceptance-floor backoff: a drafter that keeps proposing but
        # never gets drafts accepted turns every dispatch into a
        # gamma+1-wide verify emitting ~1 token — strictly worse than
        # plain decode. When the accept ratio over the last
        # _accept_window windows drops below min_accept, speculation
        # self-suppresses for `cooldown` dispatches, then re-probes
        # (workloads change). LOCALAI_SPEC_MIN_ACCEPT=0 disables.
        if min_accept is None:
            try:
                min_accept = float(os.environ.get(
                    "LOCALAI_SPEC_MIN_ACCEPT", "0.1") or 0.1)
            except ValueError:
                min_accept = 0.1
        if cooldown is None:
            try:
                cooldown = int(os.environ.get(
                    "LOCALAI_SPEC_COOLDOWN", "64") or 64)
            except ValueError:
                cooldown = 64
        self.min_accept = max(0.0, float(min_accept))
        self.cooldown = max(1, int(cooldown))
        self._recent: "deque[tuple[int, int]]" = deque(maxlen=16)
        self._cooldown_left = 0
        # window telemetry (engine-thread writers, scrape readers)
        self.total_windows = 0          # verify dispatches
        self.total_emitted = 0          # tokens emitted across windows
        self.total_eligible = 0         # active slot-windows × (gamma+1)
        self.total_proposed = 0         # draft tokens scored
        self.total_accepted = 0         # draft tokens accepted
        self.total_declined = 0         # windows the drafter declined
        self.total_suppressed = 0       # windows skipped by the backoff
        self.last_skip_reason: Optional[str] = None
        # real-proposal row mask of the in-flight window (host drafters
        # serialize windows, so one pending mask suffices; device
        # drafters propose for every slot → None = all real)
        self._pending_hits: Optional[Any] = None
        self.last_prefix_reused = 0

    # -- spec windows (engine thread) ------------------------------------

    def step_spec_async(self) -> Optional[Any]:
        """One speculative window over all slots: propose, verify, roll
        back. Returns the [gamma+1, S] emitted-token device array (SKIP =
        nothing for that step/slot), or None when the drafter declined
        (the scheduler falls back to a plain dispatch)."""
        self.last_skip_reason = None
        if self.suppressed_tick():
            self.last_skip_reason = "suppressed"
            return None
        t = self.target
        props = self.drafter.propose(t.state.tokens, t.state.positions)
        if props is None:
            self.total_declined += 1
            self.last_skip_reason = "declined"
            return None
        self._pending_hits = getattr(self.drafter, "last_hits", None)
        if _faults.ACTIVE:
            spec = _faults.apply("spec.draft", key=self.drafter.name)
            if spec is not None:
                # divergent-draft chaos: replace every proposal with
                # deterministic garbage — acceptance collapses, rollback
                # and co-batched streams must stay byte-correct
                props = (np.asarray(props) * 31 + 17) % t.cfg.vocab_size
        return t.verify_async(props)

    def suppressed_tick(self) -> bool:
        """True while the acceptance backoff is suppressing windows; each
        call consumes one cooldown tick. The scheduler calls this BEFORE
        any drain/resync so a suppressed dispatch costs exactly plain
        decode; direct window drivers hit the same check inside
        step_spec_async (never both — a False here means the cooldown is
        already spent)."""
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self.total_suppressed += 1
            return True
        return False

    def has_candidate(self, residents: dict) -> bool:
        """Cheap pre-gate: could the drafter propose for any of these
        slots right now? ``residents`` maps slot → current
        prompt+generation token record (exactly what a resync would seed
        the drafter with). Device drafters always can; host lookup
        drafters peek the records directly — a False lets the scheduler
        skip the pipeline drain AND the per-slot resync entirely, so
        self-drafting costs nothing on workloads it cannot predict."""
        peek = getattr(self.drafter, "has_candidate", None)
        if peek is None:
            return True
        return bool(peek(residents))

    def step_spec(self) -> np.ndarray:
        """Synchronous window (telemetry + tests); the scheduler's hot
        path uses step_spec_async + copy_to_host_async. Raises when the
        drafter declines — direct callers pick the window cadence."""
        emitted = self.step_spec_async()
        if emitted is None:
            raise RuntimeError(
                "speculative window skipped: "
                + ("acceptance backoff is suppressing windows"
                   if self.last_skip_reason == "suppressed"
                   else f"drafter {self.drafter.name!r} declined "
                        "(no proposals)"))
        with self.target.watchdog.guard("device"):
            rows = np.asarray(emitted)  # jaxlint: disable=host-sync-in-hot-path
        self.observe_window(rows)
        return rows

    def observe_window(self, rows: np.ndarray) -> dict:
        """Fold one drained [T, S] window into acceptance telemetry and
        the drafter's per-slot history. An active slot always emits ≥1
        token, so active columns are the ones with any non-SKIP entry.
        Returns this window's counts for the flight ring."""
        T = rows.shape[0]
        gamma = T - 1
        # sentinels are not tokens: SKIP (window ended earlier) and the
        # NaN-guard's NAN_TOKEN (the scheduler fails that request) are
        # both negative — neither counts as emitted nor enters history
        emitted_per = (rows >= 0).sum(axis=0)         # [S]
        active = emitted_per > 0
        emitted = int(emitted_per.sum())
        windows = int(active.sum())
        # each active window's last emitted token is the correction (or
        # the full-acceptance bonus sample) — everything before it is an
        # accepted draft token. Only REAL proposal rows count toward the
        # draft arithmetic: a host drafter pads no-hit slots with
        # guaranteed-reject filler for the static-shape verify, and
        # counting those would dilute accept_rate and trip the backoff
        # against a drafter that is actually working.
        hits, self._pending_hits = self._pending_hits, None
        real = active if hits is None else (active & hits)
        proposed = int(real.sum()) * gamma
        accepted = int(np.maximum(emitted_per - 1, 0)[real].sum())
        self.total_windows += 1
        self.total_emitted += emitted
        self.total_eligible += windows * T
        self.total_proposed += proposed
        self.total_accepted += accepted
        for slot in np.nonzero(active)[0]:
            col = rows[:, slot]
            self.drafter.observe(
                int(slot), [int(x) for x in col[col >= 0]])
        if proposed and self.min_accept > 0:
            self._recent.append((proposed, accepted))
            if len(self._recent) == self._recent.maxlen:
                props = sum(p for p, _ in self._recent)
                accs = sum(a for _, a in self._recent)
                if props and accs / props < self.min_accept:
                    self._cooldown_left = self.cooldown
                    self._recent.clear()
                    log.info(
                        "speculation accept rate %.3f < %.2f over the "
                        "last %d windows; suppressing for %d dispatches",
                        accs / props, self.min_accept,
                        self._recent.maxlen, self.cooldown)
        return {"emitted": emitted, "windows": windows,
                "proposed": proposed, "accepted": accepted}

    def resync_draft(self, slot: int, resident: list[int]) -> None:
        """Rebuild one slot's draft state after non-speculative dispatches
        advanced the target without it (grammar-constrained interludes,
        plain fallbacks, chunked admissions)."""
        self.drafter.resync(slot, resident, self.target.state.positions)

    # -- slot lifecycle (scheduler-facing, mirrors ModelRunner) ----------

    def admit(self, slot: int, prompt: list[int], **kw) -> int:
        """Prefill the target; the first sampled token seeds the drafter.
        Paged targets get the speculation-row lookahead reserved on top
        of any caller reservation (the scheduler's chunked path does the
        same through begin_admit)."""
        if self.paged:
            kw.setdefault("spec_tokens", self.gamma + 1)
        first = self.target.admit(slot, prompt, **kw)
        self.last_prefix_reused = self.target.last_prefix_reused
        self.drafter.admit(slot, list(prompt) or [0], first,
                           self.target.state.positions)
        return first

    def begin_admit(self, slot: int, prompt: list[int], **kw):
        """Chunked paged admission passthrough; the speculation-row
        reservation rides the allocator call (spec_tokens)."""
        kw.setdefault("spec_tokens", self.gamma + 1)
        return self.target.begin_admit(slot, prompt, **kw)

    def acquire_slot(self, slot: Optional[int] = None) -> Optional[int]:
        got = self.target.acquire_slot(slot)
        if got is not None and hasattr(self.drafter, "acquire_slot"):
            self.drafter.acquire_slot(got)
        return got

    def free_slots(self) -> list[int]:
        return self.target.free_slots()

    def release(self, slot: int) -> None:
        self.target.release(slot)
        self.drafter.release(slot)

    def set_bias(self, slot: int, bias_row) -> None:
        self.target.set_bias(slot, bias_row)

    def reusable_prefix(self, slot: int, resident, prompt,
                        valid_n=None) -> int:
        return self.target.reusable_prefix(slot, resident, prompt, valid_n)

    def resident_rows(self, slot: int, default: int) -> int:
        return self.target.resident_rows(slot, default)

    def load_prefix(self, slot: int, arrays: dict, n: int) -> bool:
        return self.target.load_prefix(slot, arrays, n)

    def slot_positions(self) -> np.ndarray:
        return self.target.slot_positions()

    def slot_position(self, slot: int) -> int:
        return self.target.slot_position(slot)

    def reinit(self) -> None:
        """Self-healing rebuild hook: the scheduler re-inits the target
        runner itself; this resets the drafter (draft KV / history) and
        the acceptance-backoff state."""
        self.drafter.reinit()
        self._recent.clear()
        self._cooldown_left = 0

    # -- telemetry --------------------------------------------------------

    @property
    def acceptance_rate(self) -> float:
        """Emitted tokens per active slot-window / (gamma+1): 1.0 = every
        window fully accepted for every active slot (window efficiency —
        the historical series; ``accept_rate`` is the per-draft ratio)."""
        if not self.total_eligible:
            return 0.0
        return self.total_emitted / self.total_eligible

    @property
    def accept_rate(self) -> float:
        """Draft tokens accepted / proposed — the localai_spec_accept_rate
        series."""
        if not self.total_proposed:
            return 0.0
        return self.total_accepted / self.total_proposed

    @property
    def tokens_per_dispatch(self) -> float:
        """Mean emitted tokens per active slot-window — >1 means the
        verify-k dispatch beats single-step decode on dispatch count."""
        if not self.total_eligible:
            return 0.0
        windows = self.total_eligible / (self.gamma + 1)
        return self.total_emitted / windows if windows else 0.0

    def stats(self) -> dict:
        """Window telemetry snapshot (obs /metrics + GetMetrics surface)."""
        return {
            "gamma": self.gamma,
            "windows": self.total_windows,
            "emitted": self.total_emitted,
            "eligible": self.total_eligible,
            "proposed": self.total_proposed,
            "accepted": self.total_accepted,
            "declined": self.total_declined,
            "suppressed": self.total_suppressed,
            "acceptance_rate": self.acceptance_rate,
            "accept_rate": self.accept_rate,
            "tokens_per_dispatch": self.tokens_per_dispatch,
            **self.drafter.stats(),
        }


def build_spec_engine(target: ModelRunner, *,
                      drafter: str = "auto",
                      draft_ref: Optional[str] = None,
                      model_path: str = "models",
                      gamma: Optional[int] = None,
                      dtype: str = "bfloat16") -> SpecEngine:
    """Resolve a drafter and couple it to ``target`` (manager entry).

    ``drafter``: ``"model"`` loads ``draft_ref`` as a co-located draft
    model (contiguous KV, target's mesh/slots); ``"ngram"`` self-drafts
    via prompt lookup; ``"auto"`` picks model when a draft_ref is
    configured, ngram otherwise. Env knobs: ``LOCALAI_SPEC_GAMMA``
    (window size), ``LOCALAI_SPEC_NGRAM_MAX`` (longest lookup n-gram)."""
    import os

    if getattr(target, "pp_enabled", False):
        # the verify forward calls mdl.forward directly — it would GSPMD
        # over pipe-sharded stacked weights, all-gathering the full
        # weight set per window (defeating capacity mode)
        raise ValueError(
            "speculative decoding is not supported with pipeline "
            "parallelism")
    if getattr(target, "ga_n", 1) > 1:
        # self-extend targets carry an UNroped KV cache + identity rope
        # table; the verify forward would compute position-blind
        # attention — reject rather than emit garbage
        raise ValueError(
            "speculative decoding is not supported with self-extend "
            "(grp_attn_n > 1)")
    if gamma is None:
        try:
            gamma = int(os.environ.get("LOCALAI_SPEC_GAMMA", "4") or 4)
        except ValueError:
            gamma = 4
    gamma = max(1, int(gamma))
    kind = drafter
    if kind in ("auto", "", None):
        kind = "model" if draft_ref else "ngram"
    if kind == "ngram":
        try:
            max_n = int(os.environ.get("LOCALAI_SPEC_NGRAM_MAX", "4") or 4)
        except ValueError:
            max_n = 4
        try:
            min_n = int(os.environ.get("LOCALAI_SPEC_NGRAM_MIN", "2") or 2)
        except ValueError:
            min_n = 2
        return SpecEngine(
            target,
            NGramDrafter(target.num_slots, gamma, max_n=max_n,
                         min_n=min_n),
        )
    if kind != "model":
        raise ValueError(f"unknown drafter {drafter!r} "
                         "(want auto | ngram | model)")
    if not draft_ref:
        raise ValueError("drafter 'model' needs a draft_model reference")
    from localai_tpu.models.registry import resolve_model

    draft = resolve_model(draft_ref, model_path=model_path, dtype=dtype)
    if draft.cfg.vocab_size != target.cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft.cfg.vocab_size} != target vocab "
            f"{target.cfg.vocab_size} — speculative decoding needs a "
            "shared tokenizer")
    params = draft.params
    if target.mesh is not None:
        from localai_tpu.parallel import sharding as shd

        params = shd.shard_params(params, draft.cfg, target.mesh)
    runner = ModelRunner(
        draft.cfg, params,
        num_slots=target.num_slots,
        max_ctx=target.max_ctx,
        prefill_buckets=list(target.buckets[:-1]) or None,
        # int4 is a paged-pool-only layout; a contiguous draft cache
        # falls back to the scaled-int8 scheme (same bandwidth class)
        kv_dtype=("int8" if target.kv_dtype == "int4"
                  else target.kv_dtype),
        mesh=target.mesh,
        # the draft serves window scans over slot rows only — contiguous
        paged=False,
    )
    return SpecEngine(target, ModelDrafter(runner, gamma))
