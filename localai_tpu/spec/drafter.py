"""Pluggable draft-token sources for speculative decoding.

A :class:`Drafter` proposes ``gamma`` candidate continuation tokens per
slot each window; the target model verifies the whole window with ONE
batched forward (``ModelRunner.verify_async``) and the engine lane
(:mod:`localai_tpu.spec.engine`) rolls rejected tails back per slot.
Two implementations ship:

* :class:`ModelDrafter` — a co-located small draft model. Its runner is
  built contiguous (a draft never needs paged admission) but shares the
  target's mesh, so under dp×tp serving the draft's weights shard over
  ``model`` and its slot state over ``data`` exactly like the target's.
  Proposals stay on device end to end: the draft window (gamma+1 greedy
  decode steps under ``lax.scan``) chains straight into the verify
  dispatch with no host round-trip, so spec windows pipeline.
* :class:`NGramDrafter` — self-drafting prompt-lookup (Saxena's
  prompt-lookup decoding / llama.cpp's lookup decoding): the most recent
  n-gram at each slot's frontier is searched in the slot's own
  prompt+generation history and the continuation of its previous
  occurrence becomes the draft. No second model is loaded — this is the
  drafter single-model deployments (the reference LocalAI's default
  shape) get speculation from. Host-side by construction, so proposals
  need the previous window drained first (``device_proposals`` False —
  the scheduler serializes spec dispatches for host drafters).

A drafter may return ``None`` from :meth:`propose` to decline a window
(no usable lookup anywhere) — the engine then falls back to plain
multi-step decode for that dispatch, so self-drafting costs nothing on
workloads it cannot predict.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Protocol, runtime_checkable

import jax
import jax.numpy as jnp
import numpy as np


@runtime_checkable
class Drafter(Protocol):
    """The pluggable proposal source the spec engine drives.

    Slot lifecycle mirrors the target runner's: ``admit`` seeds a slot's
    draft state after the target's prefill, ``observe`` feeds drained
    window tokens back (host drafters build history from it),
    ``resync`` rebuilds a slot after non-speculative dispatches advanced
    the target without the drafter, ``release`` drops a slot, and
    ``reinit`` resets everything (self-healing engine rebuild)."""

    name: str
    gamma: int
    # True when propose() returns device arrays computed purely from
    # device state — such drafters tolerate pipelined spec dispatches
    device_proposals: bool

    def propose(self, target_tokens, target_positions): ...
    def admit(self, slot: int, prompt: list[int], first: int,
              target_positions) -> None: ...
    def observe(self, slot: int, emitted: list[int]) -> None: ...
    def resync(self, slot: int, resident: list[int],
               target_positions) -> None: ...
    def release(self, slot: int) -> None: ...
    def reinit(self) -> None: ...
    def stats(self) -> dict: ...


class NGramDrafter:
    """Self-drafting prompt-lookup: predict each slot's continuation from
    its own token history, no draft model loaded.

    For every active slot the longest recent n-gram (``max_n`` down to
    ``min_n`` tokens, ending at the frontier) is searched backwards
    through the slot's prompt+generation history; on a hit, the ``gamma``
    tokens that followed the previous occurrence become the draft. Misses
    propose nothing for that slot (its row is a guaranteed-reject filler
    so the batched verify stays static-shape); when NO slot has a hit the
    whole window is declined and the engine decodes plainly. All state is
    host lists owned by the engine thread — zero device traffic."""

    device_proposals = False

    def __init__(self, num_slots: int, gamma: int = 4, *,
                 max_n: int = 4, min_n: int = 2,
                 max_history: int = 8192):
        # min_n defaults to 2: a 1-gram "hit" fires whenever the frontier
        # token appeared ANYWHERE in history — on non-repetitive traffic
        # that proposes (and pays a verify for) near-random drafts every
        # window; the engine's acceptance backoff is the second line of
        # defense, this keeps the first-order hit rate honest
        self.name = "ngram"
        self.num_slots = num_slots
        self.gamma = int(gamma)
        self.max_n = max(1, int(max_n))
        self.min_n = max(1, min(int(min_n), self.max_n))
        self.max_history = int(max_history)
        self._history: dict[int, list[int]] = {}
        # incremental int64 mirrors of resident records (pre-gate scans)
        self._mirror: dict[int, tuple[Optional[np.ndarray], int]] = {}
        # [S] bool: which rows of the LAST propose() were real lookup
        # hits (None before the first propose)
        self.last_hits: Optional[np.ndarray] = None
        self.lookup_hits = 0
        self.lookup_misses = 0

    # -- proposal ---------------------------------------------------------

    def _lookup(self, arr: np.ndarray) -> Optional[list[int]]:
        """Longest-suffix match over an int64 history array: the
        continuation after the most recent earlier occurrence of the
        frontier n-gram, longest n first. Candidate starts come from one
        vectorized first-token scan per n — this runs on the engine
        thread every window, so a pure-Python O(L·n) scan would be a
        TPOT tax."""
        L = len(arr)
        for n in range(self.max_n, self.min_n - 1, -1):
            if L <= n:
                continue
            pat = arr[L - n:]
            # candidate window starts (the suffix occurrence itself is
            # excluded by the :L-n bound), most recent first
            starts = np.flatnonzero(arr[:L - n] == pat[0])
            for i in starts[::-1]:
                if n == 1 or np.array_equal(arr[i:i + n], pat):
                    cont = arr[i + n:i + n + self.gamma]
                    if len(cont):
                        out = [int(x) for x in cont]
                        while len(out) < self.gamma:  # pad short tails
                            out.append(out[-1])
                        return out
        return None

    def _resident_arr(self, slot: int, r: list) -> np.ndarray:
        """Incremental int64 mirror of a resident record, so the per-
        dispatch pre-gate costs O(new tokens) instead of re-converting
        the whole Python list every engine iteration. Records are
        append-only for a request's lifetime; a shrunk length or a
        mismatched last-mirrored element (slot reuse) rebuilds. A stale
        mirror can only mis-steer the HEURISTIC (one wasted drain or one
        delayed window) — proposals are always verified against true
        device state."""
        n = len(r)
        buf, filled = self._mirror.get(slot, (None, 0))
        if (buf is None or filled > n
                or (filled and int(buf[filled - 1]) != r[filled - 1])):
            buf = np.empty(max(1024, 2 * n), np.int64)
            filled = 0
        elif n > len(buf):
            grown = np.empty(max(2 * n, 2 * len(buf)), np.int64)
            grown[:filled] = buf[:filled]
            buf = grown
        if n > filled:
            buf[filled:n] = r[filled:n]
        self._mirror[slot] = (buf, n)
        lo = max(0, n - self.max_history)
        return buf[lo:n]

    def propose(self, target_tokens, target_positions):
        """[S, gamma] i32 proposals, or None when no slot has a lookup
        hit (the engine falls back to plain decode for this dispatch).
        ``last_hits`` records which slot rows are REAL proposals — the
        rest are guaranteed-reject filler for the static-shape verify,
        and the engine excludes them from the accept-rate arithmetic.
        The device args are unused — history is the source of truth."""
        props = np.zeros((self.num_slots, self.gamma), np.int32)
        hits = np.zeros(self.num_slots, bool)
        for slot, hist in self._history.items():
            cont = self._lookup(np.asarray(hist, np.int64))
            if cont is None:
                self.lookup_misses += 1
                continue
            self.lookup_hits += 1
            props[slot] = cont
            hits[slot] = True
        self.last_hits = hits
        return props if hits.any() else None

    def has_candidate(self, residents: dict) -> bool:
        """Pre-gate for the scheduler (SpecEngine.has_candidate): run the
        lookup over the CURRENT resident records — the same data a
        resync would copy into history — via incrementally-mirrored
        arrays bounded to ``max_history`` (exactly the window propose()
        searches; a wider scan could promise hits propose cannot
        deliver, draining the pipeline for nothing every iteration)."""
        for slot, r in residents.items():
            if r and self._lookup(self._resident_arr(slot, r)) is not None:
                return True
        return False

    # -- slot lifecycle ---------------------------------------------------

    def admit(self, slot: int, prompt: list[int], first: int,
              target_positions) -> None:
        self._history[slot] = (list(prompt) + [int(first)])[-self.max_history:]

    def observe(self, slot: int, emitted: list[int]) -> None:
        hist = self._history.get(slot)
        if hist is None:
            return
        hist.extend(int(t) for t in emitted)
        if len(hist) > self.max_history:
            del hist[:len(hist) - self.max_history]

    def resync(self, slot: int, resident: list[int],
               target_positions) -> None:
        self._history[slot] = list(resident)[-self.max_history:]

    def release(self, slot: int) -> None:
        self._history.pop(slot, None)
        self._mirror.pop(slot, None)

    def reinit(self) -> None:
        self._history.clear()
        self._mirror.clear()
        self.last_hits = None

    def stats(self) -> dict:
        return {"drafter": self.name, "lookup_hits": self.lookup_hits,
                "lookup_misses": self.lookup_misses}


class ModelDrafter:
    """Draft-model proposals: gamma+1 greedy decode steps of a co-located
    small model in ONE compiled dispatch.

    The +1 step writes the last proposal's KV so the draft cache has no
    hole when every token is accepted; its sampled token is discarded.
    The draft state's frontier is re-synced from the TARGET's post-verify
    token/position arrays at the start of each draft window (regular jit
    inputs, never donated — so the target is free to donate its own state
    into the verify program). Rejected draft rows are garbage above the
    frontier, overwritten before anything attends to them — the same
    rollback-free invariant the contiguous engine has always used."""

    device_proposals = True

    def __init__(self, runner, gamma: int = 4):
        # `runner` is a contiguous ModelRunner for the draft model (same
        # vocab, same slot count as the target; build_spec_engine checks)
        from localai_tpu.obs import compile as obs_compile

        self.name = "model"
        self.runner = runner
        self.gamma = int(gamma)
        self._draft = obs_compile.watch(
            jax.jit(self._draft_fn, donate_argnums=(1, 2)), "draft_window"
        )

    def _draft_fn(self, params, kv, state, tokens, positions):
        """Resync the draft frontier from the target's, then decode
        gamma+1 greedy steps under lax.scan; returns [S, gamma]
        proposals."""
        state = dataclasses.replace(
            state, tokens=tokens, positions=positions)

        def body(carry, _):
            kv, st = carry
            kv, st, tok = self.runner._decode_fn(params, kv, st)
            return (kv, st), tok

        (kv, state), toks = jax.lax.scan(
            body, (kv, state), None, length=self.gamma + 1
        )
        return kv, state, toks.T[:, :self.gamma]

    def propose(self, target_tokens, target_positions):
        r = self.runner
        r.kv, r.state, props = self._draft(
            r.params, r.kv, r.state, target_tokens, target_positions
        )
        return props

    def admit(self, slot: int, prompt: list[int], first: int,
              target_positions) -> None:
        """Prefill the draft; the target's first sampled token seeds the
        stream (the draft's own first sample is discarded), and the
        frontier copies the target's device-side (no host sync)."""
        r = self.runner
        r.admit(slot, list(prompt), temperature=0.0)
        r.state = dataclasses.replace(
            r.state,
            tokens=r.state.tokens.at[slot].set(jnp.int32(int(first))),
            positions=r.state.positions.at[slot].set(
                target_positions[slot]),
        )

    def observe(self, slot: int, emitted: list[int]) -> None:
        pass  # device state is the source of truth

    def resync(self, slot: int, resident: list[int],
               target_positions) -> None:
        """Rebuild one slot's draft KV after non-speculative dispatches
        advanced the target without it. ``resident`` is the scheduler's
        prompt+generated record; its last element is the next token to
        feed."""
        r = self.runner
        prompt = list(resident[:-1]) or [0]
        r.admit(slot, prompt, temperature=0.0)
        r.state = dataclasses.replace(
            r.state,
            tokens=r.state.tokens.at[slot].set(jnp.int32(int(resident[-1]))),
            positions=r.state.positions.at[slot].set(
                target_positions[slot]),
        )

    def acquire_slot(self, slot: int) -> None:
        self.runner.acquire_slot(slot)

    def release(self, slot: int) -> None:
        self.runner.release(slot)

    def reinit(self) -> None:
        self.runner.reinit()

    def stats(self) -> dict:
        return {"drafter": self.name,
                "draft_model_layers": self.runner.cfg.num_layers}
