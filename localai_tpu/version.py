__version__ = "0.1.0"


def printable_version() -> str:
    """Human-readable version banner (parity: internal/version.go PrintableVersion)."""
    return f"localai-tpu {__version__}"
