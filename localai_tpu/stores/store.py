"""In-memory vector store with jitted top-K similarity search.

TPU-era redesign of the reference's ``local-store`` backend
(/root/reference/backend/go/stores/store.go:101-507): where the Go store
keeps columnar float32 keys with insertion sort and a hand-rolled cosine
loop (store.go:323-375,426-473 normalized fast path), here the keys live as
one device matrix and Find is a single jitted matmul + ``lax.top_k`` — the
shape vector search wants on an MXU.

Semantics parity:
  * Set upserts by exact key bytes; Get/Delete address by exact key.
  * Find returns (keys, values, cosine similarities) of the top-K.
  * The normalized fast path is implicit: stored keys and queries are
    L2-normalized once at insert/query time, so dot == cosine.

The device matrix is padded to the next power of two so repeated inserts
reuse a handful of compiled programs instead of recompiling per size.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("k",))
def _topk_cosine(matrix: jax.Array, norms: jax.Array, query: jax.Array,
                 valid: jax.Array, k: int):
    """matrix [N, D] (unnormalized), norms [N], query [D] → (scores, idx)."""
    qn = query / jnp.maximum(jnp.linalg.norm(query), 1e-12)
    sims = matrix @ qn / jnp.maximum(norms, 1e-12)
    sims = jnp.where(valid, sims, -jnp.inf)
    return jax.lax.top_k(sims, k)


class VectorStore:
    """Thread-safe store: host dict for exact addressing, device matrix
    for similarity search."""

    def __init__(self, dim: Optional[int] = None):
        self.dim = dim
        self._lock = threading.Lock()
        self._index: dict[bytes, int] = {}   # key bytes → row
        self._keys: list[np.ndarray] = []    # row → key vector
        self._values: list[bytes] = []       # row → payload
        self._free: list[int] = []
        self._matrix: Optional[jax.Array] = None   # [cap, D]
        self._norms: Optional[jax.Array] = None    # [cap]
        self._valid: Optional[jax.Array] = None    # [cap] bool
        self._cap = 0
        self._dirty = True

    # -- internal ----------------------------------------------------------

    @staticmethod
    def _key_bytes(vec: np.ndarray) -> bytes:
        return np.ascontiguousarray(vec, dtype=np.float32).tobytes()

    def _check_dim(self, vec: np.ndarray) -> np.ndarray:  # jaxlint: guarded-by(_lock)
        v = np.asarray(vec, np.float32).reshape(-1)
        if self.dim is None:
            self.dim = v.shape[0]
        elif v.shape[0] != self.dim:
            raise ValueError(
                f"key dim {v.shape[0]} != store dim {self.dim}"
            )
        return v

    def _sync_device(self) -> None:  # jaxlint: guarded-by(_lock)
        """Rebuild the device matrix if rows changed (power-of-two cap)."""
        if not self._dirty:
            return
        n = len(self._keys)
        cap = 1
        while cap < max(n, 1):
            cap *= 2
        host = np.zeros((cap, self.dim or 1), np.float32)
        valid = np.zeros(cap, bool)
        for i, kv in enumerate(self._keys):
            if kv is not None:
                host[i] = kv
                valid[i] = True
        self._matrix = jnp.asarray(host)
        self._norms = jnp.linalg.norm(self._matrix, axis=1)
        self._valid = jnp.asarray(valid)
        self._cap = cap
        self._dirty = False

    # -- API ---------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def set(self, keys: Sequence[Sequence[float]],
            values: Sequence[bytes]) -> None:
        if len(keys) != len(values):
            raise ValueError("keys and values must be the same length")
        with self._lock:
            for vec, val in zip(keys, values):
                v = self._check_dim(np.asarray(vec))
                kb = self._key_bytes(v)
                row = self._index.get(kb)
                if row is None:
                    if self._free:
                        row = self._free.pop()
                        self._keys[row] = v
                        self._values[row] = val
                    else:
                        row = len(self._keys)
                        self._keys.append(v)
                        self._values.append(val)
                    self._index[kb] = row
                    self._dirty = True  # value-only upserts don't touch keys
                else:
                    self._values[row] = val

    def _row_of(self, vec: np.ndarray) -> Optional[int]:  # jaxlint: guarded-by(_lock)
        """Exact-key lookup that never latches/asserts dimensions — reads
        against an empty or differently-sized store just miss."""
        v = np.asarray(vec, np.float32).reshape(-1)
        if self.dim is None or v.shape[0] != self.dim:
            return None
        return self._index.get(self._key_bytes(v))

    def get(self, keys: Sequence[Sequence[float]]
            ) -> tuple[list[list[float]], list[Optional[bytes]]]:
        out_k, out_v = [], []
        with self._lock:
            for vec in keys:
                v = np.asarray(vec, np.float32).reshape(-1)
                row = self._row_of(v)
                out_k.append([float(x) for x in v])
                out_v.append(self._values[row] if row is not None else None)
        return out_k, out_v

    def delete(self, keys: Sequence[Sequence[float]]) -> int:
        removed = 0
        with self._lock:
            for vec in keys:
                v = np.asarray(vec, np.float32).reshape(-1)
                row = self._row_of(v)
                if row is not None:
                    self._index.pop(self._key_bytes(v), None)
                if row is None:
                    continue
                self._keys[row] = None  # type: ignore[call-overload]
                self._values[row] = b""
                self._free.append(row)
                removed += 1
                self._dirty = True
        return removed

    def find(self, key: Sequence[float], top_k: int
             ) -> tuple[list[list[float]], list[bytes], list[float]]:
        with self._lock:
            if not self._index:
                return [], [], []
            q = self._check_dim(np.asarray(key))
            self._sync_device()
            k = min(max(top_k, 1), len(self._index))
            # round the device-side k to a power of two capped at cap, so
            # distinct client top_k values share compiled programs; the
            # host filter below trims to the exact k
            k_dev = 1
            while k_dev < k:
                k_dev *= 2
            k_dev = min(k_dev, self._cap)
            scores, idx = _topk_cosine(
                self._matrix, self._norms, jnp.asarray(q), self._valid,
                k_dev,
            )
            scores = np.asarray(scores)
            idx = np.asarray(idx)
            keys_out, vals_out, sims_out = [], [], []
            for s, i in zip(scores, idx):
                if not np.isfinite(s) or len(keys_out) >= k:
                    continue
                keys_out.append([float(x) for x in self._keys[int(i)]])
                vals_out.append(self._values[int(i)])
                sims_out.append(float(s))
            return keys_out, vals_out, sims_out


class StoreRegistry:
    """Named stores (the API server can host several)."""

    def __init__(self) -> None:
        self._stores: dict[str, VectorStore] = {}
        self._lock = threading.Lock()

    def get(self, name: str = "default") -> VectorStore:
        with self._lock:
            st = self._stores.get(name)
            if st is None:
                st = self._stores[name] = VectorStore()
            return st

    def drop(self, name: str) -> bool:
        with self._lock:
            return self._stores.pop(name, None) is not None
