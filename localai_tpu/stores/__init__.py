"""Vector stores: jitted cosine top-K over device-resident key matrices.

Parity: the reference's local-store backend + Stores RPCs
(/root/reference/backend/go/stores/store.go, backend/backend.proto
StoresSet/Get/Find/Delete) and the /stores/* HTTP API.
"""

from localai_tpu.stores.store import StoreRegistry, VectorStore
