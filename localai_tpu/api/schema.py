"""OpenAI-compatible wire schema (requests as pydantic, responses as
helper-built dicts).

Parity: /root/reference/core/schema/openai.go (OpenAIRequest:157,
OpenAIResponse:38, Message:69 — string-or-multipart content, tool calls),
prediction.go, and the LocalAI request types (core/schema/localai.go).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Optional, Union

from pydantic import BaseModel, ConfigDict, Field


class FunctionDef(BaseModel):
    model_config = ConfigDict(extra="allow")

    name: str = ""
    description: str = ""
    parameters: Optional[dict[str, Any]] = None


class ToolDef(BaseModel):
    model_config = ConfigDict(extra="allow")

    type: str = "function"
    function: Optional[FunctionDef] = None


class FunctionCall(BaseModel):
    model_config = ConfigDict(extra="allow")

    name: str = ""
    arguments: str = ""


class ToolCall(BaseModel):
    model_config = ConfigDict(extra="allow")

    id: str = ""
    index: Optional[int] = None
    type: str = "function"
    function: FunctionCall = Field(default_factory=FunctionCall)


class Message(BaseModel):
    """Chat message; content may be a string or multipart list
    (text / image_url / audio / video parts — schema/openai.go:69)."""

    model_config = ConfigDict(extra="allow")

    role: str = "user"
    name: Optional[str] = None
    content: Optional[Union[str, list[dict[str, Any]]]] = None
    tool_calls: Optional[list[ToolCall]] = None
    function_call: Optional[Union[FunctionCall, dict]] = None

    def text_content(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        parts = []
        for part in self.content:
            if part.get("type") == "text" or "text" in part:
                parts.append(str(part.get("text", "")))
        return "".join(parts)

    def media_parts(self, kind: str) -> list[str]:
        """URLs/base64 payloads of image_url/audio_url/video_url parts."""
        if not isinstance(self.content, list):
            return []
        out = []
        key = f"{kind}_url"
        for part in self.content:
            if part.get("type") == key or key in part:
                val = part.get(key)
                if isinstance(val, dict):
                    val = val.get("url")
                if val:
                    out.append(str(val))
        return out


class OpenAIRequest(BaseModel):
    """The one merged request shape every OpenAI endpoint reads
    (parity: schema/openai.go:157 — a single struct serves chat,
    completions, edits, embeddings, images, audio)."""

    model_config = ConfigDict(extra="allow", populate_by_name=True)

    model: str = ""
    # chat / completion / edit
    messages: list[Message] = Field(default_factory=list)
    prompt: Optional[Union[str, list[str]]] = None
    instruction: str = ""
    suffix: str = ""
    # embeddings
    input: Optional[Union[str, list[Any]]] = None
    # sampling
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    max_tokens: Optional[int] = None
    n: Optional[int] = None
    seed: Optional[int] = None
    stop: Optional[Union[str, list[str]]] = None
    logit_bias: Optional[dict[str, float]] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repeat_penalty: Optional[float] = None
    ignore_eos: bool = False
    echo: bool = False
    stream: bool = False
    # tools
    tools: Optional[list[ToolDef]] = None
    tool_choice: Optional[Union[str, dict[str, Any]]] = None
    functions: Optional[list[FunctionDef]] = None
    function_call: Optional[Union[str, dict[str, Any]]] = None
    grammar: Optional[str] = None
    response_format: Optional[Union[str, dict[str, Any]]] = None
    # images (parity: schema/openai.go Size/File/Step fields consumed by
    # ImageEndpoint, core/http/endpoints/openai/image.go:139-202)
    size: str = ""
    file: str = ""                     # img2img init: base64 or URL
    mode: int = 0                      # accepted for reference compat only:
                                       # txt2img vs img2img is keyed off
                                       # `file` here, not this selector
    step: int = 0
    # misc
    user: str = ""
    language: Optional[str] = None
    backend: Optional[str] = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        if isinstance(self.stop, str):
            return [self.stop]
        return [s for s in self.stop if isinstance(s, str)]

    def tool_definitions(self) -> list[dict]:
        """tools ∪ legacy functions, as plain function dicts."""
        out: list[dict] = []
        for t in self.tools or []:
            if t.function is not None:
                out.append(t.function.model_dump(exclude_none=True))
        for f in self.functions or []:
            out.append(f.model_dump(exclude_none=True))
        return out

    def tool_choice_name(self) -> Optional[str]:
        """Requested function name, or None; "none" disables tools."""
        for choice in (self.tool_choice, self.function_call):
            if choice is None:
                continue
            if isinstance(choice, str):
                if choice in ("none", "auto", "required"):
                    return None
                return choice
            if isinstance(choice, dict):
                fn = choice.get("function", choice)
                name = fn.get("name")
                if name:
                    return str(name)
        return None

    def tools_disabled(self) -> bool:
        return self.tool_choice == "none" or self.function_call == "none"


# ---------------------------------------------------------------------------
# Response builders (OpenAIResponse parity, schema/openai.go:38)


def _now() -> int:
    return int(time.time())


def new_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


def usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def chat_response(rid: str, model: str, choices: list[dict],
                  usage_dict: dict) -> dict:
    return {
        "id": rid,
        "object": "chat.completion",
        "created": _now(),
        "model": model,
        "choices": choices,
        "usage": usage_dict,
    }


def chat_chunk(rid: str, model: str, delta: dict, *, index: int = 0,
               finish_reason: Optional[str] = None,
               usage_dict: Optional[dict] = None) -> dict:
    out = {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": _now(),
        "model": model,
        "choices": [{
            "index": index,
            "delta": delta,
            "finish_reason": finish_reason,
        }],
    }
    if usage_dict is not None:
        out["usage"] = usage_dict
    return out


def completion_response(rid: str, model: str, choices: list[dict],
                        usage_dict: dict, *, object_name: str =
                        "text_completion") -> dict:
    return {
        "id": rid,
        "object": object_name,
        "created": _now(),
        "model": model,
        "choices": choices,
        "usage": usage_dict,
    }


def embeddings_response(model: str, vectors: list[list[float]],
                        prompt_tokens: int) -> dict:
    return {
        "object": "list",
        "model": model,
        "data": [
            {"object": "embedding", "index": i, "embedding": v}
            for i, v in enumerate(vectors)
        ],
        "usage": usage(prompt_tokens, 0),
    }


def models_response(names: list[str]) -> dict:
    return {
        "object": "list",
        "data": [
            {"id": n, "object": "model", "owned_by": "localai-tpu"}
            for n in names
        ],
    }


def error_body(message: str, *, kind: str = "invalid_request_error",
               code: Optional[int] = None) -> dict:
    err: dict[str, Any] = {"message": message, "type": kind}
    if code is not None:
        err["code"] = code
    return {"error": err}
