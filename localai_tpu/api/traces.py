"""Tracing endpoints: recent span trees + per-request timelines.

``GET /v1/traces`` — newest-first span trees from the ring-buffer trace
store (``?limit=N``, ``?kind=request|http``, ``?model=name``).

``GET /debug/timeline/{request_id}`` — every trace matching one trace id
or engine request id (the HTTP span plus each engine request it spawned,
e.g. n>1 fan-out), merged into one flat, time-ordered timeline with
offsets relative to the earliest span — the "where did my latency go"
view for a single request.
"""

from __future__ import annotations

from aiohttp import web

from localai_tpu.obs.trace import STORE, mono_to_wall


async def list_traces(request: web.Request) -> web.Response:
    try:
        limit = max(1, min(int(request.query.get("limit", 50)), 500))
    except ValueError:
        raise web.HTTPBadRequest(text="limit must be an integer")
    kind = request.query.get("kind") or None
    model = request.query.get("model") or None
    traces = STORE.recent(limit=limit, kind=kind)
    if model:
        traces = [t for t in traces if t.model == model]
    return web.json_response({
        "object": "list",
        "traces": [t.to_dict() for t in traces],
    })


async def timeline(request: web.Request) -> web.Response:
    rid = request.match_info["request_id"]
    hits = STORE.find(rid)
    if not hits:
        raise web.HTTPNotFound(
            text=f"no trace recorded for {rid!r} (traces are kept in a "
                 f"bounded ring; see /v1/traces for what is retained)"
        )
    origin = min(t.t0 for t in hits)
    events = []
    for t in hits:
        for s in t.spans():
            events.append({
                "source": t.request_id,
                "kind": t.kind,
                "name": s.name,
                "offset_ms": round((s.t0 - origin) * 1e3, 3),
                "duration_ms": (None if s.t1 is None
                                else round((s.t1 - s.t0) * 1e3, 3)),
                "attrs": dict(s.attrs),
            })
    events.sort(key=lambda e: e["offset_ms"])
    return web.json_response({
        "request_id": rid,
        "start_unix": round(mono_to_wall(origin), 6),
        "traces": [t.to_dict() for t in hits],
        "timeline": events,
    })


def routes() -> list[web.RouteDef]:
    return [
        web.get("/v1/traces", list_traces),
        web.get("/debug/timeline/{request_id}", timeline),
    ]
