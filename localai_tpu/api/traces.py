"""Tracing endpoints: recent span trees + per-request timelines.

``GET /v1/traces`` — newest-first span trees from the ring-buffer trace
store (``?limit=N``, ``?kind=request|http``, ``?model=name``).

``GET /v1/traces/{trace_id}`` — ONE stitched waterfall for one trace id:
the front door's spans plus every fleet replica's harvested half
(GetTelemetry), remote span trees skew-anchored to the local dispatch
RPC span and tagged ``replica=`` (obs.fleetview). The harvest runs off
the event loop with the fleet RPC deadline — a wedged replica degrades
to an ``unreachable`` pane, never a hung endpoint.

``GET /debug/timeline/{request_id}`` — every trace matching one trace id
or engine request id (the HTTP span plus each engine request it spawned,
e.g. n>1 fan-out), merged into one flat, time-ordered timeline with
offsets relative to the earliest span — the "where did my latency go"
view for a single request. When the trace crossed replicas, the response
additionally carries the stitched fleet waterfall under ``fleet``.
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from localai_tpu.obs import fleetview
from localai_tpu.obs.trace import STORE, mono_to_wall


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


async def list_traces(request: web.Request) -> web.Response:
    try:
        limit = max(1, min(int(request.query.get("limit", 50)), 500))
    except ValueError:
        raise web.HTTPBadRequest(text="limit must be an integer")
    kind = request.query.get("kind") or None
    model = request.query.get("model") or None
    traces = STORE.recent(limit=limit, kind=kind)
    if model:
        traces = [t for t in traces if t.model == model]
    return web.json_response({
        "object": "list",
        "traces": [t.to_dict() for t in traces],
    })


async def _stitched(request: web.Request, tid: str,
                    local: list[dict]) -> dict:
    """Harvest every fleet-served model's replicas named by this trace's
    spans and stitch one waterfall — one bounded GetTelemetry per named
    replica, on the executor (never the event loop)."""
    state = _state(request)
    loop = asyncio.get_running_loop()

    def build() -> dict:
        harvested: dict[str, dict] = {}
        for sm in state.manager.loaded_snapshot().values():
            if getattr(sm, "pool", None) is not None:
                harvested.update(
                    fleetview.harvest_for_trace(sm, tid, local))
        return fleetview.stitch(tid, local, harvested)

    return await loop.run_in_executor(state.executor, build)


async def trace_detail(request: web.Request) -> web.Response:
    tid = request.match_info["trace_id"]
    hits = STORE.find(tid)
    if not hits:
        raise web.HTTPNotFound(
            text=f"no trace recorded for {tid!r} (traces are kept in a "
                 f"bounded ring; see /v1/traces for what is retained)"
        )
    local = [t.to_dict() for t in hits]
    # STORE.find also matches engine request ids ("model-N") — those are
    # per-process counters, NOT safe to harvest by (a worker's "model-N"
    # is a different request). Resolve to the matched traces' real trace
    # id before pulling the remote half.
    tids = {t.trace_id for t in hits}
    harvest_tid = tid if tid in tids else (
        next(iter(tids)) if len(tids) == 1 else None)
    if harvest_tid is None:
        return web.json_response(fleetview.stitch(tid, local, {}))
    return web.json_response(await _stitched(request, harvest_tid, local))


async def timeline(request: web.Request) -> web.Response:
    rid = request.match_info["request_id"]
    hits = STORE.find(rid)
    if not hits:
        raise web.HTTPNotFound(
            text=f"no trace recorded for {rid!r} (traces are kept in a "
                 f"bounded ring; see /v1/traces for what is retained)"
        )
    origin = min(t.t0 for t in hits)
    events = []
    for t in hits:
        for s in t.spans():
            events.append({
                "source": t.request_id,
                "kind": t.kind,
                "name": s.name,
                "offset_ms": round((s.t0 - origin) * 1e3, 3),
                "duration_ms": (None if s.t1 is None
                                else round((s.t1 - s.t0) * 1e3, 3)),
                "attrs": dict(s.attrs),
            })
    events.sort(key=lambda e: e["offset_ms"])
    local = [t.to_dict() for t in hits]
    body = {
        "request_id": rid,
        "start_unix": round(mono_to_wall(origin), 6),
        "traces": local,
        "timeline": events,
    }
    # the fleet half rides along when the trace crossed replicas: the
    # stitched waterfall carries front-door AND replica-side spans in one
    # skew-anchored sequence (local-only traces add nothing and skip the
    # harvest entirely). The harvest key must be a genuine TRACE id —
    # {request_id} also matches engine request ids ("model-N"), which are
    # per-process counters: harvesting by one would pull a STRANGER's
    # "model-N" spans off the worker and stitch them into this timeline.
    tids = {t.trace_id for t in hits}
    harvest_tid = rid if rid in tids else (
        next(iter(tids)) if len(tids) == 1 else None)
    if harvest_tid is not None and fleetview.replica_ids_for_trace(local):
        stitched = await _stitched(request, harvest_tid, local)
        body["fleet"] = {
            "replicas": stitched["replicas"],
            "waterfall": stitched["waterfall"],
        }
    return web.json_response(body)


def routes() -> list[web.RouteDef]:
    return [
        web.get("/v1/traces", list_traces),
        web.get("/v1/traces/{trace_id}", trace_detail),
        web.get("/debug/timeline/{request_id}", timeline),
    ]
