"""Jina-compatible rerank endpoint.

Parity: /root/reference/core/http/endpoints/jina/rerank.go +
core/backend/rerank.go — POST /v1/rerank {model, query, documents, top_n}
→ scored documents. Cross-encoder models (``backend: reranker`` or a
bert-class checkpoint — models/reranker.py, the analogue of
backend/python/rerankers/) score (query ⊕ doc) jointly in one batched
forward; any other model falls back to cosine of mean-pooled embeddings
through the LLM engine.
"""

from __future__ import annotations

import logging

import numpy as np
from aiohttp import web

from localai_tpu.api import schema as sc
from localai_tpu.config.model_config import Usecase

log = logging.getLogger(__name__)


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


async def rerank(request: web.Request) -> web.Response:
    from localai_tpu.api.openai import _default_model, _in_executor, _serving

    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    query = body.get("query") or ""
    documents = [str(d) for d in body.get("documents") or []]
    if not query or not documents:
        raise web.HTTPBadRequest(text="need query and documents")
    try:
        top_n = int(body.get("top_n") or len(documents))
    except (TypeError, ValueError):
        raise web.HTTPBadRequest(text="top_n must be an integer")
    if top_n < 1:
        raise web.HTTPBadRequest(text="top_n must be >= 1")

    req = sc.OpenAIRequest(model=body.get("model") or "")
    req.model = _default_model(request, req.model)
    # SLO admission control: rerank scores ride the same engine capacity
    # as generation — refuse under overload with the same preserved
    # Retry-After instead of queueing into a latency spiral
    from localai_tpu.api import inference as inf

    inf.shed_check(req.model)
    state = _state(request)
    mcfg = state.loader.get(req.model)
    if mcfg is not None and state.manager.is_reranker(mcfg):
        # joint (query ⊕ doc) scoring — order- and interaction-aware
        rm = await _in_executor(request, state.manager.get_reranker,
                                req.model)
        raw, total_tokens = await _in_executor(
            request, rm.score, query, documents
        )
        return _rerank_response(req.model, documents,
                                [float(s) for s in raw],
                                total_tokens, top_n)
    sm, _cfg = await _serving(request, req, Usecase.RERANK)

    def score_all():
        q_toks = sm.tokenizer.encode(query, add_bos=True)
        q_vec = np.asarray(sm.runner.embed(q_toks))
        q_vec = q_vec / max(float(np.linalg.norm(q_vec)), 1e-12)
        scores = []
        total_tokens = len(q_toks)
        for doc in documents:
            d_toks = sm.tokenizer.encode(doc, add_bos=True)
            total_tokens += len(d_toks)
            d_vec = np.asarray(sm.runner.embed(d_toks))
            d_vec = d_vec / max(float(np.linalg.norm(d_vec)), 1e-12)
            scores.append(float(q_vec @ d_vec))
        return scores, total_tokens

    scores, total_tokens = await _in_executor(request, score_all)
    return _rerank_response(req.model, documents, scores, total_tokens,
                            top_n)


def _rerank_response(model: str, documents: list[str], scores: list[float],
                     total_tokens: int, top_n: int) -> web.Response:
    order = sorted(range(len(documents)), key=lambda i: -scores[i])[:top_n]
    return web.json_response({
        "model": model,
        "usage": {"total_tokens": total_tokens,
                  "prompt_tokens": total_tokens},
        "results": [
            {
                "index": i,
                "document": {"text": documents[i]},
                "relevance_score": scores[i],
            }
            for i in order
        ],
    })


def routes() -> list[web.RouteDef]:
    return [
        web.post("/v1/rerank", rerank),
        web.post("/rerank", rerank),
    ]
