"""Audio endpoints: transcription (OpenAI), TTS (OpenAI/LocalAI), and
Elevenlabs-compatible routes.

Parity:
  * POST /v1/audio/transcriptions — multipart upload → whisper engine
    (/root/reference/core/http/endpoints/openai/transcription.go)
  * POST /v1/audio/speech + POST /tts — TTS
    (endpoints/localai/tts.go, routes/openai.go)
  * POST /v1/text-to-speech/{voice_id}, /v1/sound-generation —
    Elevenlabs surface (endpoints/elevenlabs/*.go, routes/elevenlabs.go)
"""

from __future__ import annotations

import logging

from aiohttp import web

from localai_tpu.config.model_config import Usecase

log = logging.getLogger(__name__)


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


def _whisper_for(state, name: str):
    """name → whisper AudioServingModel through the ModelManager, so STT
    models get the same watchdog/eviction/monitor coverage as every other
    loaded model (no private AppState caches)."""
    try:
        return state.manager.get_whisper(name)
    except FileNotFoundError as e:
        raise web.HTTPNotFound(text=str(e))
    except KeyError:
        # bare refs keep working without a YAML: a debug: preset or an
        # on-disk checkpoint dir named directly registers a transient
        # config (previous behavior, now under lifecycle management)
        from pathlib import Path

        from localai_tpu.config.model_config import ModelConfig

        resolvable = name.startswith("debug:") or any(
            (cand / "config.json").exists()
            for cand in (Path(name), Path(state.config.model_path) / name)
        )
        if not resolvable:
            raise web.HTTPNotFound(text=f"model {name!r} not configured")
        state.loader.register(ModelConfig(
            name=name, model=name, backend="whisper",
            known_usecases=[Usecase.TRANSCRIPT],
        ))
        try:
            return state.manager.get_whisper(name)
        except FileNotFoundError as e:
            raise web.HTTPNotFound(text=str(e))


def _transcript_model(request: web.Request, name: str) -> str:
    state = _state(request)
    if name:
        return name
    for cfg in state.loader.all():
        if cfg.has_usecase(Usecase.TRANSCRIPT):
            return cfg.name
    raise web.HTTPNotFound(
        text="no transcription model configured (backend: whisper)"
    )


async def transcribe(request: web.Request) -> web.Response:
    """POST /v1/audio/transcriptions (multipart: file, model, language,
    translate, response_format)."""
    from localai_tpu.api.openai import _in_executor
    from localai_tpu.audio import read_wav

    if not (request.content_type or "").startswith("multipart/"):
        raise web.HTTPBadRequest(text="expected multipart/form-data")
    reader = await request.multipart()
    audio_bytes = b""
    fields: dict[str, str] = {}
    async for part in reader:
        if part.name == "file":
            audio_bytes = await part.read(decode=False)
        else:
            fields[part.name or ""] = (await part.text())
    if not audio_bytes:
        raise web.HTTPBadRequest(text="missing file field")

    name = _transcript_model(request, fields.get("model", ""))
    state = _state(request)

    def run():
        sm = _whisper_for(state, name)
        audio = read_wav(audio_bytes)
        return sm.run(
            "transcribe", audio,
            language=fields.get("language") or None,
            translate=fields.get("translate", "") in ("1", "true"),
        )

    try:
        result = await _in_executor(request, run)
    except ValueError as e:
        raise web.HTTPBadRequest(text=str(e))

    fmt = fields.get("response_format", "json")
    if fmt == "text":
        return web.Response(text=result["text"] + "\n")
    if fmt == "verbose_json":
        return web.json_response({
            "task": "transcribe",
            "duration": result["segments"][-1]["end"]
            if result["segments"] else 0.0,
            "text": result["text"],
            "segments": result["segments"],
        })
    return web.json_response({"text": result["text"],
                              "segments": result["segments"]})


def _reference_voice(state, model_name: str, voice: str):
    """Resolve a reference-voice recording for cloning (vall-e-x
    ``audio_path`` parity, backend_config.go:19-26): the model's TTS
    section points at a wav file, or a directory holding one wav per
    voice name ({voice}.wav). Returns float32 @16 kHz or None."""
    from pathlib import Path

    mcfg = state.loader.get(model_name) if model_name else None
    tts_cfg = getattr(mcfg, "tts", None) if mcfg is not None else None
    ap = getattr(tts_cfg, "audio_path", None) if tts_cfg is not None else None
    if not ap:
        return None
    base = Path(ap)
    if not base.is_absolute():
        base = Path(state.config.model_path) / base
    if base.is_dir():
        from localai_tpu.utils.paths import verify_path

        try:
            # the voice name is caller-supplied — confine it to audio_path
            cand = verify_path(f"{voice}.wav", base)
        except ValueError:
            return None
    else:
        cand = base
    if not cand.is_file():
        return None
    from localai_tpu.audio.wav import read_wav

    try:
        return read_wav(cand.read_bytes())
    except Exception:  # noqa: BLE001 — bad reference ≠ failed request
        return None


def _tts_params(state, model_name: str) -> tuple[str, float]:
    """Resolve default voice/speed from the named TTS config, if any."""
    voice, speed = "alloy", 1.0
    mcfg = state.loader.get(model_name) if model_name else None
    if mcfg is not None:
        tts_cfg = getattr(mcfg, "tts", None)
        if tts_cfg is not None and getattr(tts_cfg, "voice", ""):
            voice = tts_cfg.voice
    return voice, speed


def _vits_for(state, name: str):
    """name → VITS AudioServingModel through the ModelManager when the
    config points at a vits checkpoint; None → parametric fallback. Runs
    in the executor (weight loads block for seconds)."""
    if not name:
        return None
    mcfg = state.loader.get(name)
    if mcfg is None:
        return None
    ref = mcfg.model or name
    if ref.startswith("debug:"):
        return None  # debug TTS rides the parametric synth
    if mcfg.backend != "vits":
        # `backend: tts` and bare configs: neural only when a vits
        # checkpoint actually exists — the parametric synth stays the
        # fallback (tts.py docstring contract)
        from localai_tpu.models.detect import detect_backend

        if detect_backend(ref, state.config.model_path) != "vits":
            return None
    try:
        return state.manager.get_vits(name)
    except FileNotFoundError as e:
        raise web.HTTPNotFound(text=str(e))


async def _speak(request: web.Request, text: str, voice: str,
                 speed: float, model_name: str = "") -> web.Response:
    from localai_tpu.api.openai import _in_executor
    from localai_tpu.audio import write_wav
    from localai_tpu.audio import tts as ttsmod

    if not text:
        raise web.HTTPBadRequest(text="empty input text")
    state = _state(request)

    def run():
        # model resolution + (first-use) weight load happen HERE, on the
        # executor — a multi-second vits load must not block the loop
        ref_audio = _reference_voice(state, model_name, voice)
        sm = _vits_for(state, model_name)
        if sm is not None:
            # neural path (VITS voice checkpoint); `voice` selects the
            # speaker for multispeaker models. Snapshot the model ref
            # before reading cfg — a concurrent eviction nulls sm.model,
            # and run() re-raises that case as its designed error.
            model = sm.model
            if model is None:
                raise RuntimeError(f"vits model {sm.name} was evicted")
            cfg = model.cfg
            spk = None
            spk_emb = None
            if ref_audio is not None and cfg.speaker_embedding_size:
                # voice cloning: reference recording → identity embedding
                # → continuous conditioning (audio.speaker)
                from localai_tpu.audio.speaker import get_speaker_encoder

                enc = get_speaker_encoder()
                spk_emb = enc.project(enc.embed(ref_audio),
                                      cfg.speaker_embedding_size)
            elif voice.isdigit():
                spk = int(voice)
            wav = sm.run(
                "synthesize", text, speaker_id=spk,
                speaker_embedding=spk_emb,
                speaking_rate=cfg.speaking_rate * speed,
            )
            return write_wav(wav, rate=cfg.sampling_rate)
        return write_wav(ttsmod.synthesize(text, voice=voice, speed=speed,
                                           ref_audio=ref_audio))

    data = await _in_executor(request, run)
    return web.Response(body=data, content_type="audio/wav")


async def speech(request: web.Request) -> web.Response:
    """POST /v1/audio/speech (OpenAI) and POST /tts (LocalAI)."""
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    state = _state(request)
    text = body.get("input") or body.get("text") or ""
    voice, speed = _tts_params(state, body.get("model") or "")
    voice = body.get("voice") or voice
    try:
        speed = float(body.get("speed") or speed)
    except (TypeError, ValueError):
        raise web.HTTPBadRequest(text="speed must be a number")
    return await _speak(request, text, voice, speed,
                        model_name=body.get("model") or "")


async def elevenlabs_tts(request: web.Request) -> web.Response:
    """POST /v1/text-to-speech/{voice_id} (Elevenlabs parity)."""
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    voice = request.match_info.get("voice_id", "alloy")
    return await _speak(request, body.get("text") or "", voice, 1.0)


async def sound_generation(request: web.Request) -> web.Response:
    """POST /v1/sound-generation (Elevenlabs parity; the reference fans
    out to transformers-musicgen)."""
    from localai_tpu.api.openai import _in_executor
    from localai_tpu.audio import write_wav
    from localai_tpu.audio import tts as ttsmod

    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    text = body.get("text") or body.get("input") or ""
    if not text:
        raise web.HTTPBadRequest(text="empty input text")
    try:
        duration = float(body.get("duration_seconds") or 3.0)
        temperature = float(body.get("temperature") or 1.0)
    except (TypeError, ValueError):
        raise web.HTTPBadRequest(text="duration/temperature must be numbers")

    def run():
        return write_wav(ttsmod.generate_sound(text, duration, temperature))

    data = await _in_executor(request, run)
    return web.Response(body=data, content_type="audio/wav")


def routes() -> list[web.RouteDef]:
    return [
        web.post("/v1/audio/transcriptions", transcribe),
        web.post("/v1/audio/speech", speech),
        web.post("/tts", speech),
        web.post("/v1/text-to-speech/{voice_id}", elevenlabs_tts),
        web.post("/v1/sound-generation", sound_generation),
        web.post("/sound-generation", sound_generation),
    ]
