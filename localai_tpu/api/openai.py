"""OpenAI-compatible endpoints: chat, completions, edits, embeddings,
models, with SSE streaming and tool-call handling.

Parity: /root/reference/core/http/endpoints/openai/
(chat.go:27-608, completion.go, edit.go, embeddings.go, list.go,
request.go readRequest/mergeRequestWithConfig).
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from aiohttp import web

from localai_tpu.api import inference as inf
from localai_tpu.api import schema as sc
from localai_tpu.api.streams import (
    SSE_DONE,
    SSE_HEADERS,
    aiter_handle,
    mark_first_write,
    sse_event,
)
from localai_tpu.config.model_config import Usecase
from localai_tpu.templates.chat import (
    build_chat_prompt,
    build_completion_prompt,
    build_edit_prompt,
)

log = logging.getLogger(__name__)


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


async def _read_request(request: web.Request) -> sc.OpenAIRequest:
    """Body → OpenAIRequest with model-name fallback chain: body.model →
    path param → first available config (parity: request.go:25 +
    ctx/fiber.go:18-47)."""
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    try:
        req = sc.OpenAIRequest.model_validate(body)
    except Exception as e:  # pydantic ValidationError → client error, not 500
        raise web.HTTPBadRequest(text=f"invalid request: {e}") from None
    if not req.model:
        req.model = request.match_info.get("model", "")
    req.model = _default_model(request, req.model)
    return req


def _default_model(request: web.Request, model: str) -> str:
    """Model-name fallback: explicit name, else first configured model
    (parity: ModelFromContext, ctx/fiber.go:18-47). Shared with non-OpenAI
    endpoints (rerank, tts, ...)."""
    if model:
        return model
    names = _state(request).loader.names()
    if not names:
        raise web.HTTPNotFound(
            text="no models configured; install one first"
        )
    return names[0]


async def _serving(request: web.Request, req: sc.OpenAIRequest,
                   usecase: Optional[Usecase] = None):
    state = _state(request)
    mcfg = state.loader.get(req.model)
    if mcfg is None:
        raise web.HTTPNotFound(
            text=f"model {req.model!r} not found; available: "
                 f"{state.loader.names()}"
        )
    if usecase is not None and not mcfg.has_usecase(usecase):
        raise web.HTTPBadRequest(
            text=f"model {req.model!r} does not support {usecase.value}"
        )
    try:
        # lazy weight load + jit can take minutes — keep it off the loop
        return await _in_executor(request, state.manager.get, req.model), mcfg
    except FileNotFoundError as e:
        raise web.HTTPInternalServerError(text=f"model load failed: {e}")


async def _in_executor(request: web.Request, fn, *args):
    import asyncio

    return await asyncio.get_running_loop().run_in_executor(
        _state(request).executor, fn, *args
    )


async def _await_handles(request: web.Request, handles,
                         timeout: Optional[float] = None):
    """Wait for generations, cancelling them all if the client goes away
    (otherwise orphaned work would hold decode slots to max_tokens).
    ``timeout=None`` resolves the configurable per-request deadline
    (AppConfig.request_deadline_s / LOCALAI_REQUEST_DEADLINE_S); expiry
    cancels every handle — the slots free on the next engine step — and
    surfaces 504, not an orphaned generation.
    A handle that finished with reason "error" and produced nothing is a
    backend failure — surface 502, not a successful empty completion."""
    if timeout is None:
        timeout = inf.request_deadline_s(_state(request).config)
    try:
        for h in handles:
            await _in_executor(request, h.result, timeout)
    except BaseException as e:
        for h in handles:
            h.cancel()
        if isinstance(e, TimeoutError):
            raise web.HTTPGatewayTimeout(
                text=f"generation exceeded the {timeout:.0f}s request "
                     "deadline and was cancelled"
            ) from e
        raise
    for h in handles:
        if h.finish_reason == "error" and not h.text:
            raise web.HTTPBadGateway(
                text="generation failed in the backend (see server logs)"
            )


# ---------------------------------------------------------------------------
# /v1/chat/completions


async def chat(request: web.Request) -> web.StreamResponse:
    req = await _read_request(request)
    sm, base_cfg = await _serving(request, req, Usecase.CHAT)
    # SLO burn-rate admission control: shed BEFORE any prompt build or
    # constraint compile — a 429 must cost the overloaded engine nothing
    inf.shed_check(req.model, sm.scheduler)
    cfg = inf.merge_request(base_cfg, req)

    try:
        tctx = await _in_executor(request, inf.prepare_tools, sm, cfg, req)
    except inf.ToolGrammarError as e:
        raise web.HTTPBadRequest(text=str(e)) from e
    rf_constraint = None
    if tctx is None:
        rf_constraint = await _in_executor(
            request, inf.response_format_constraint, sm, req
        )

    try:
        messages, mm_embeds = await _in_executor(
            request, inf.prepare_multimodal, sm, cfg, req
        )
    except Exception as e:  # noqa: BLE001 — bad image refs → 400
        from localai_tpu.utils.media import MediaError

        if isinstance(e, MediaError):
            raise web.HTTPBadRequest(text=str(e)) from e
        raise
    # guessed/explicit chat_template covers plain chat only: tool requests
    # stay on build_chat_prompt, which renders function schemas and
    # tool-call/tool-result turns the family templates don't model
    if cfg.template.use_tokenizer_template or (
            cfg.template.chat_template and tctx is None):
        from localai_tpu.templates.chat import apply_tokenizer_template

        prompt = apply_tokenizer_template(
            sm.tokenizer, messages,
            chat_template=cfg.template.chat_template,
        )
    else:
        prompt = build_chat_prompt(
            sm.templates, cfg, messages,
            functions=tctx.functions if tctx else None,
            use_function_template=tctx is not None,
            grammar_active=tctx is not None and tctx.constraint is not None,
        )
    rid = sc.new_id("chatcmpl")
    # correlation id: client header, else the request id (chat.go:164-169);
    # trace id: the obs middleware's, so engine spans group under the HTTP
    # span at /debug/timeline/{trace_id}
    cid = inf.correlation_id(request) or rid
    tid = inf.trace_id(request) or cid

    constraint = tctx.constraint if tctx else rf_constraint
    gr = inf.build_gen_request(
        sm, cfg, req, prompt, constraint=constraint, mm_embeds=mm_embeds,
        correlation_id=cid, trace_id=tid,
    )

    async def extra_choice_request(i: int):
        """Choice i>0 needs a FRESH constraint (FSM state is per-request)
        — the one shared rebuild path for stream and non-stream n>1."""
        c = None
        if tctx is not None:
            c = (await _in_executor(
                request, inf.prepare_tools, sm, cfg, req)).constraint
        elif rf_constraint is not None:
            c = await _in_executor(
                request, inf.response_format_constraint, sm, req)
        return inf.build_gen_request(
            sm, cfg, req, prompt, constraint=c, seed_offset=i,
            mm_embeds=mm_embeds, correlation_id=cid, trace_id=tid,
        )

    if req.stream:
        n = max(1, req.n or 1)
        if n > 1 and tctx is None:
            # every choice streams concurrently on its own index (tool
            # calls still buffer whole, so they stay single-choice)
            extra = [await extra_choice_request(i) for i in range(1, n)]
            return await _chat_stream_n(request, req, sm, [gr] + extra,
                                        rid, cid)
        return await _chat_stream(request, req, sm, cfg, gr, rid, tctx,
                                  cid=cid)

    n = max(1, req.n or 1)
    handles = []
    for i in range(n):
        gr_i = gr if i == 0 else await extra_choice_request(i)
        handles.append(sm.scheduler.submit(gr_i))
    await _await_handles(request, handles)
    choices = []
    total_completion = 0
    prompt_tokens = 0
    for i, h in enumerate(handles):
        text = inf.finetune_result(cfg, prompt, h.text)
        prompt_tokens = h.prompt_tokens
        total_completion += h.completion_tokens
        message: dict[str, Any] = {"role": "assistant"}
        finish = h.finish_reason or "stop"
        if tctx is not None:
            content, tool_calls = inf.parse_tool_calls(text, tctx)
            message["content"] = content or None
            if tool_calls:
                message["tool_calls"] = tool_calls
                finish = "tool_calls"
        else:
            message["content"] = text
        choices.append({
            "index": i,
            "message": message,
            "finish_reason": finish,
        })
    return web.json_response(sc.chat_response(
        rid, req.model, choices, sc.usage(prompt_tokens, total_completion)
    ), headers={"X-Correlation-ID": cid})


def _sse_headers(request, cid: str) -> dict:
    """SSE headers + tracing echo. Streaming responses send headers at
    prepare(), before the outer trace middleware could add X-Trace-ID —
    so the echo must be baked in here or a generated trace id would be
    undiscoverable for exactly the latency-sensitive streaming case."""
    headers = dict(SSE_HEADERS)
    if cid:
        headers["X-Correlation-ID"] = cid
    tid = inf.trace_id(request)
    if tid:
        headers["X-Trace-ID"] = tid
    return headers


async def _chat_stream(request, req, sm, cfg, gr, rid, tctx, *, cid=""
                       ) -> web.StreamResponse:
    """SSE streaming. Plain chat streams deltas as they decode; with tools
    the text must be parsed whole, so deltas buffer and the final frames
    carry tool_calls (parity: chat.go:107-154,463-508)."""
    headers = _sse_headers(request, cid)
    resp = web.StreamResponse(headers=headers)
    await resp.prepare(request)
    await resp.write(sse_event(sc.chat_chunk(
        rid, req.model, {"role": "assistant", "content": ""}
    )))
    handle = sm.scheduler.submit(gr)
    buffered: list[str] = []
    finish = "stop"
    try:
        async for item in aiter_handle(handle):
            if item.finish_reason is not None:
                finish = item.finish_reason
                break
            if not item.delta:
                continue
            if tctx is not None:
                buffered.append(item.delta)
            else:
                await resp.write(sse_event(sc.chat_chunk(
                    rid, req.model, {"content": item.delta}
                )))
                mark_first_write(handle)
    except BaseException:
        # client went away mid-stream — free the decode slot immediately
        handle.cancel()
        raise
    if tctx is not None:
        text = inf.finetune_result(cfg, "", "".join(buffered))
        content, tool_calls = inf.parse_tool_calls(text, tctx)
        if tool_calls:
            finish = "tool_calls"
            for tc in tool_calls:
                await resp.write(sse_event(sc.chat_chunk(
                    rid, req.model, {"tool_calls": [tc]}
                )))
        elif content:
            await resp.write(sse_event(sc.chat_chunk(
                rid, req.model, {"content": content}
            )))
    await resp.write(sse_event(sc.chat_chunk(
        rid, req.model, {}, finish_reason=finish,
        usage_dict=sc.usage(handle.prompt_tokens, handle.completion_tokens),
    )))
    await resp.write(SSE_DONE)
    await resp.write_eof()
    return resp


async def _chat_stream_n(request, req, sm, grs, rid, cid
                         ) -> web.StreamResponse:
    """n>1 plain-chat streaming: all choices decode concurrently through
    the batching engine, interleaved on the one SSE stream by index."""
    import asyncio

    resp = web.StreamResponse(headers=_sse_headers(request, cid))
    await resp.prepare(request)
    handles = [sm.scheduler.submit(gr) for gr in grs]
    write_lock = asyncio.Lock()
    for i in range(len(handles)):
        await resp.write(sse_event(sc.chat_chunk(
            rid, req.model, {"role": "assistant", "content": ""}, index=i
        )))

    async def pump(idx: int, handle) -> None:
        finish = "stop"
        async for item in aiter_handle(handle):
            if item.finish_reason is not None:
                finish = item.finish_reason
                break
            if item.delta:
                async with write_lock:
                    await resp.write(sse_event(sc.chat_chunk(
                        rid, req.model, {"content": item.delta},
                        index=idx,
                    )))
                mark_first_write(handle)
        async with write_lock:
            await resp.write(sse_event(sc.chat_chunk(
                rid, req.model, {}, finish_reason=finish, index=idx,
            )))

    tasks = [asyncio.ensure_future(pump(i, h))
             for i, h in enumerate(handles)]
    try:
        await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        for h in handles:
            h.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
    # ONE usage frame for the whole request (prompt tokens counted once —
    # per-choice usage would n-fold-overcount for metering clients)
    usage_frame = sc.chat_chunk(rid, req.model, {})
    usage_frame["choices"] = []
    usage_frame["usage"] = sc.usage(
        handles[0].prompt_tokens,
        sum(h.completion_tokens for h in handles),
    )
    await resp.write(sse_event(usage_frame))
    await resp.write(SSE_DONE)
    await resp.write_eof()
    return resp


# ---------------------------------------------------------------------------
# /v1/completions  /v1/edits


async def completions(request: web.Request) -> web.StreamResponse:
    req = await _read_request(request)
    sm, base_cfg = await _serving(request, req, Usecase.COMPLETION)
    inf.shed_check(req.model, sm.scheduler)
    cfg = inf.merge_request(base_cfg, req)
    rid = sc.new_id("cmpl")
    cid = inf.correlation_id(request) or rid
    tid = inf.trace_id(request) or cid

    prompts: list[str]
    if isinstance(req.prompt, list):
        prompts = [str(p) for p in req.prompt] or [""]
    else:
        prompts = [str(req.prompt or "")]
    templated = [
        build_completion_prompt(sm.templates, cfg, p) for p in prompts
    ]

    if req.stream:
        return await _completions_stream(
            request, req, sm, cfg, templated, rid, cid, tid
        )

    choices = []
    prompt_total = 0
    completion_total = 0
    idx = 0
    for raw, prompt in zip(prompts, templated):
        n = max(1, req.n or 1)
        handles = [
            sm.scheduler.submit(inf.build_gen_request(
                sm, cfg, req, prompt, seed_offset=i, correlation_id=cid,
                trace_id=tid))
            for i in range(n)
        ]
        await _await_handles(request, handles)
        for h in handles:
            text = inf.finetune_result(cfg, raw, h.text, echo=req.echo)
            prompt_total += h.prompt_tokens
            completion_total += h.completion_tokens
            choices.append({
                "index": idx,
                "text": text,
                "finish_reason": h.finish_reason or "stop",
            })
            idx += 1
    return web.json_response(sc.completion_response(
        rid, req.model, choices, sc.usage(prompt_total, completion_total)
    ), headers={"X-Correlation-ID": cid})


async def _completions_stream(request, req, sm, cfg, templated, rid, cid,
                              tid="") -> web.StreamResponse:
    """SSE streaming over EVERY prompt in the list × n choices — each
    choice index streams concurrently through the continuous-batching
    engine (a list prompt must not silently degrade to its first element,
    and stream/non-stream modes must agree on choice indexing)."""
    import asyncio

    resp = web.StreamResponse(headers=_sse_headers(request, cid))
    await resp.prepare(request)
    n = max(1, req.n or 1)
    # choice index p*n + i — identical to the non-stream loop below
    handles = [
        sm.scheduler.submit(inf.build_gen_request(
            sm, cfg, req, prompt, seed_offset=i, correlation_id=cid,
            trace_id=tid))
        for prompt in templated
        for i in range(n)
    ]
    write_lock = asyncio.Lock()

    async def pump(idx: int, handle) -> None:
        finish = "stop"
        async for item in aiter_handle(handle):
            if item.finish_reason is not None:
                finish = item.finish_reason
                break
            if item.delta:
                async with write_lock:
                    await resp.write(sse_event(sc.completion_response(
                        rid, req.model,
                        [{"index": idx, "text": item.delta,
                          "finish_reason": None}],
                        sc.usage(handle.prompt_tokens,
                                 handle.completion_tokens),
                    )))
                mark_first_write(handle)
        async with write_lock:
            await resp.write(sse_event(sc.completion_response(
                rid, req.model, [{"index": idx, "text": "",
                                  "finish_reason": finish}],
                sc.usage(handle.prompt_tokens, handle.completion_tokens),
            )))

    # explicit tasks (not bare gather) so one failing pump (e.g. client
    # disconnect mid-write) cancels its siblings instead of leaving them
    # writing to a dead response as orphaned tasks; TaskGroup is 3.11+ and
    # the package supports 3.10
    tasks = [asyncio.ensure_future(pump(i, h))
             for i, h in enumerate(handles)]
    try:
        await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        for h in handles:
            h.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
    await resp.write(SSE_DONE)
    await resp.write_eof()
    return resp


async def edits(request: web.Request) -> web.Response:
    req = await _read_request(request)
    sm, base_cfg = await _serving(request, req, Usecase.EDIT)
    inf.shed_check(req.model, sm.scheduler)
    cfg = inf.merge_request(base_cfg, req)
    rid = sc.new_id("edit")
    cid = inf.correlation_id(request) or rid
    tid = inf.trace_id(request) or cid
    inputs: list[str]
    if isinstance(req.prompt, list):
        inputs = [str(p) for p in req.prompt] or [""]
    else:
        inputs = [str(req.prompt or "")]
    choices = []
    ptotal = ctotal = 0
    for i, text_in in enumerate(inputs):
        prompt = build_edit_prompt(sm.templates, cfg, text_in,
                                   req.instruction)
        h = sm.scheduler.submit(inf.build_gen_request(
            sm, cfg, req, prompt, correlation_id=cid, trace_id=tid))
        await _await_handles(request, [h])
        ptotal += h.prompt_tokens
        ctotal += h.completion_tokens
        choices.append({
            "index": i,
            "text": inf.finetune_result(cfg, prompt, h.text),
            "finish_reason": h.finish_reason or "stop",
        })
    return web.json_response(sc.completion_response(
        rid, req.model, choices, sc.usage(ptotal, ctotal),
        object_name="edit",
    ), headers={"X-Correlation-ID": cid})


# ---------------------------------------------------------------------------
# /v1/embeddings


async def embeddings(request: web.Request) -> web.Response:
    req = await _read_request(request)
    # SLO admission control covers embeddings too (they ride the same
    # engine/executor capacity as generation); checked before any model
    # load so a 429 costs the overloaded process nothing. Retry-After
    # survives the error middleware's JSON re-wrap.
    inf.shed_check(req.model)

    inputs: list[Any]
    if req.input is None:
        inputs = [""]
    elif isinstance(req.input, str):
        inputs = [req.input]
    else:
        inputs = list(req.input) or [""]
        if inputs and all(isinstance(x, int) for x in inputs):
            inputs = [inputs]  # one tokenized input

    # bert-class sentence encoders embed in one batched forward (parity:
    # the sentencetransformers backend); other models mean-pool through
    # the LLM engine below
    state = _state(request)
    mcfg = state.loader.get(req.model)
    if mcfg is not None and state.manager.is_embedder(mcfg):
        if not all(isinstance(t, str) for t in inputs):
            # pre-tokenized input carries the LLM tokenizer's ids — a
            # bert sentence encoder has a different vocab; embedding the
            # repr-string would silently return meaningless vectors
            raise web.HTTPBadRequest(
                text="token-array input is not supported for "
                     "sentence-encoder backends; send text"
            )
        em = await _in_executor(request, state.manager.get_embedder,
                                req.model)
        vecs, ptokens = await _in_executor(request, em.embed, inputs)
        return web.json_response(sc.embeddings_response(
            req.model, [[float(x) for x in v] for v in vecs], ptokens
        ))

    sm, base_cfg = await _serving(request, req, Usecase.EMBEDDINGS)

    def embed_all() -> tuple[list[list[float]], int]:
        vecs = []
        ptokens = 0
        for item in inputs:
            if isinstance(item, list):
                toks = [int(t) for t in item]
            else:
                toks = sm.tokenizer.encode(str(item), add_bos=True)
            ptokens += len(toks)
            vecs.append([float(x) for x in sm.runner.embed(toks)])
        return vecs, ptokens

    vectors, prompt_tokens = await _in_executor(request, embed_all)
    return web.json_response(
        sc.embeddings_response(req.model, vectors, prompt_tokens)
    )


# ---------------------------------------------------------------------------
# /v1/models


async def list_models(request: web.Request) -> web.Response:
    state = _state(request)
    names = state.loader.names()
    # ?filter=<regex> and loose-file policy parity
    # (core/services/list_models.go:17-49)
    flt = request.query.get("filter")
    if flt:
        import re

        try:
            rx = re.compile(flt)
            names = [n for n in names if rx.search(n)]
        except re.error:
            pass
    return web.json_response(sc.models_response(names))


def routes() -> list[web.RouteDef]:
    """Route table (parity: core/http/routes/openai.go:18-84 incl. the
    legacy unversioned aliases)."""
    out = []
    for path, handler in [
        ("/v1/chat/completions", chat),
        ("/chat/completions", chat),
        ("/v1/completions", completions),
        ("/completions", completions),
        ("/v1/edits", edits),
        ("/edits", edits),
        ("/v1/embeddings", embeddings),
        ("/embeddings", embeddings),
    ]:
        out.append(web.post(path, handler))
    out.append(web.get("/v1/models", list_models))
    out.append(web.get("/models", list_models))
    return out
