"""Compatibility shim: the metric registry moved to ``localai_tpu.obs``
(the observability subsystem owns telemetry; the API layer only scrapes
it). Import from ``localai_tpu.obs.metrics`` in new code."""

from localai_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_label_value,
    update_engine_gauges,
)

__all__ = ["REGISTRY", "Counter", "Gauge", "Histogram", "Registry",
           "escape_label_value", "update_engine_gauges"]
