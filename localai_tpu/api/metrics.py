"""Minimal OpenMetrics/Prometheus registry.

Parity: the reference's OTel meter + Prometheus exporter with one
``api_call`` histogram labeled by method/path
(/root/reference/core/services/metrics.go:13-45, recorded by middleware
app.go:117-122, scraped at GET /metrics routes/localai.go:45). No
prometheus_client in this image, so the text exposition is hand-rolled —
it is a stable, tiny format.
"""

from __future__ import annotations

import threading
from typing import Iterable


_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
            30.0, 60.0)


class Histogram:
    def __init__(self, name: str, help_text: str,
                 buckets: Iterable[float] = _BUCKETS):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._series: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * (len(self.buckets) + 1), 0.0, 0]  # counts, sum, n
                self._series[key] = s
            counts, _, _ = s
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            s[1] += value
            s[2] += 1

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, (counts, total, n) in sorted(self._series.items()):
                base = ",".join(f'{k}="{v}"' for k, v in key)
                cum = 0
                for i, ub in enumerate(self.buckets):
                    cum += counts[i]
                    lbl = f"{base},le=\"{ub}\"" if base else f'le="{ub}"'
                    lines.append(f"{self.name}_bucket{{{lbl}}} {cum}")
                cum += counts[-1]
                lbl = f"{base},le=\"+Inf\"" if base else 'le="+Inf"'
                lines.append(f"{self.name}_bucket{{{lbl}}} {cum}")
                suffix = f"{{{base}}}" if base else ""
                lines.append(f"{self.name}_sum{suffix} {total}")
                lines.append(f"{self.name}_count{suffix} {n}")
        return "\n".join(lines)


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def set_total(self, value: float, **labels: str) -> None:
        """Sync the series to an externally tracked monotone total."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._series[key] = max(self._series.get(key, 0.0), value)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        with self._lock:
            for key, val in sorted(self._series.items()):
                base = ",".join(f'{k}="{v}"' for k, v in key)
                suffix = f"{{{base}}}" if base else ""
                lines.append(f"{self.name}{suffix} {val}")
        return "\n".join(lines)


class Gauge(Counter):
    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._series[key] = value

    def render(self) -> str:
        return super().render().replace(" counter", " gauge", 1)


class Registry:
    """The process-wide metric set."""

    def __init__(self) -> None:
        self.api_call = Histogram(
            "localai_api_call_seconds", "API call duration by method/path"
        )
        self.tokens_generated = Counter(
            "localai_tokens_generated_total", "Completion tokens emitted"
        )
        self.tokens_prompt = Counter(
            "localai_prompt_tokens_total", "Prompt tokens processed"
        )
        self.active_slots = Gauge(
            "localai_active_slots", "Occupied decode slots per model"
        )

    def render(self) -> str:
        parts = [
            self.api_call.render(),
            self.tokens_generated.render(),
            self.tokens_prompt.render(),
            self.active_slots.render(),
        ]
        return "\n".join(parts) + "\n"


REGISTRY = Registry()
