"""Image generation endpoint: POST /v1/images/generations + the
/generated-images/ static file route.

Parity: ImageEndpoint (/root/reference/core/http/endpoints/openai/
image.go:67-242) — "positive|negative" prompt splitting, n copies per
prompt, size "WxH", step/seed/cfg from the model's diffusers config with
request overrides, img2img init from a base64 or URL `file`, and
b64_json vs url response formats (url files land in image_path and are
served at /generated-images/<name>). The compute path is the TPU-native
latent-diffusion pipeline (localai_tpu.image) instead of the reference's
diffusers/NCNN workers.
"""

from __future__ import annotations

import base64
import binascii
import io
import logging
import time
import uuid
from pathlib import Path

import numpy as np
from aiohttp import web

from localai_tpu.api import openai as oai
from localai_tpu.api import schema as sc
from localai_tpu.config.model_config import Usecase

log = logging.getLogger(__name__)

def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


def _image_model(state, name: str):
    """name → ImageServingModel via ModelManager: image pipelines get the
    same lifecycle management as LLMs — idle watchdog, eviction,
    /backend/monitor, single_active_backend (VERDICT r2 weak #5: the old
    private cache bypassed all of it)."""
    try:
        return state.manager.get_image(name)
    except KeyError as e:
        raise web.HTTPNotFound(text=str(e))
    except FileNotFoundError as e:
        raise web.HTTPNotFound(text=str(e))


def _parse_size(size: str) -> tuple[int, int]:
    if not size:
        return 512, 512
    parts = size.lower().split("x")
    try:
        w, h = int(parts[0]), int(parts[1])
    except (ValueError, IndexError):
        raise web.HTTPBadRequest(text="invalid value for 'size'")
    if w <= 0 or h <= 0 or w > 2048 or h > 2048:
        raise web.HTTPBadRequest(text="invalid value for 'size' (max 2048)")
    return w, h


async def _init_image(request: web.Request, file_ref: str):
    """`file` → decoded RGB array. base64 data always works; http(s) URLs
    are fetched over the network (parity: downloadFile, image.go:27-45)."""
    from PIL import Image

    if file_ref.startswith(("http://", "https://")):
        # one-shot session per fetch: img2img URL inits are rare enough that
        # connection reuse isn't worth a pooled session on AppState
        import aiohttp

        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.get(file_ref) as resp:
                    resp.raise_for_status()
                    data = await resp.read()
        except Exception as e:  # noqa: BLE001
            raise web.HTTPBadRequest(text=f"failed downloading file: {e}")
    else:
        try:
            data = base64.b64decode(file_ref, validate=True)
        except (binascii.Error, ValueError):
            raise web.HTTPBadRequest(text="file is neither a URL nor base64")
    try:
        # PIL decode of an arbitrary-size upload takes tens of ms —
        # executor-side, never on the event loop
        return await oai._in_executor(request, _decode_rgb, data)
    except Exception as e:  # noqa: BLE001
        raise web.HTTPBadRequest(text=f"cannot decode init image: {e}")


def _decode_rgb(data: bytes) -> np.ndarray:
    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB")
    return np.asarray(img, np.uint8)


def _encode_png(arr: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def _finalize_png(img: np.ndarray, width: int, height: int) -> bytes:
    """Resize (the pipeline buckets latent sizes to 64-multiples; return
    exactly what the client asked for) + PNG-encode, executor-side."""
    if img.shape[:2] != (height, width):
        from PIL import Image

        img = np.asarray(
            Image.fromarray(img).resize((width, height)), np.uint8
        )
    return _encode_png(img)


def _store_png(png: bytes, image_path: str) -> str:
    name = f"{uuid.uuid4().hex}.png"
    out = Path(image_path)
    out.mkdir(parents=True, exist_ok=True)
    (out / name).write_bytes(png)
    return name


async def generations(request: web.Request) -> web.Response:
    state = _state(request)
    req = await oai._read_request(request)
    mcfg = state.loader.get(req.model)
    if mcfg is None:
        raise web.HTTPNotFound(
            text=f"model {req.model!r} not found; available: "
                 f"{state.loader.names()}"
        )
    if not mcfg.has_usecase(Usecase.IMAGE):
        raise web.HTTPBadRequest(
            text=f"model {req.model!r} does not support image generation"
        )
    width, height = _parse_size(req.size)
    prompts = req.prompt if isinstance(req.prompt, list) else [req.prompt or ""]
    n = req.n or mcfg.parameters.n or 1
    b64 = (req.response_format or "") == "b64_json" or (
        isinstance(req.response_format, dict)
        and req.response_format.get("type") == "b64_json"
    )
    init = await _init_image(request, req.file) if req.file else None
    steps = req.step or mcfg.diffusers.steps or 0
    seed = req.seed if req.seed is not None else mcfg.parameters.seed

    sm = await oai._in_executor(request, _image_model, state, req.model)

    items = []
    with sm.in_use():  # busy across the whole batch: no eviction mid-request
        for prompt in prompts:
            pos, _, neg = (prompt or "").partition("|")
            for j in range(n):
                # distinct images per copy: offset the seed like a new draw
                s = None if seed is None else int(seed) + j
                # with a ControlNet attached, the request's image guides
                # (control) instead of seeding img2img (backend.py parity:
                # the controlnet pipelines take the image as control input)
                has_cn = getattr(sm.pipeline, "controlnet_params",
                                 None) is not None
                result = await oai._in_executor(
                    request,
                    lambda: sm.generate(
                        pos, negative_prompt=neg, width=width, height=height,
                        steps=steps or None, seed=s,
                        init_image=None if has_cn else init,
                        control_image=init if has_cn else None,
                        control_scale=mcfg.diffusers.control_scale,
                    ),
                )
                # resize + PNG encode are CPU-bound milliseconds per
                # image; like the generate call above they run on the
                # API executor, not the event loop
                png = await oai._in_executor(
                    request, _finalize_png, result.image, width, height)
                if b64:
                    items.append(
                        {"b64_json": base64.b64encode(png).decode()}
                    )
                else:
                    name = await oai._in_executor(
                        request, _store_png, png, state.config.image_path)
                    base = f"{request.scheme}://{request.host}"
                    items.append(
                        {"url": f"{base}/generated-images/{name}"}
                    )

    return web.json_response({
        "id": uuid.uuid4().hex,
        "created": int(time.time()),
        "data": items,
    })


async def serve_generated(request: web.Request) -> web.Response:
    """GET /generated-images/{name} — path-guarded static file serving."""
    state = _state(request)
    name = request.match_info["name"]
    root = Path(state.config.image_path).resolve()
    target = (root / name).resolve()
    if root not in target.parents or not target.is_file():
        raise web.HTTPNotFound(text="image not found")
    return web.FileResponse(target)


def routes() -> list[web.RouteDef]:
    return [
        web.post("/v1/images/generations", generations),
        web.get("/generated-images/{name}", serve_generated),
    ]
