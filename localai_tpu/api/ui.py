"""Web UI: server-rendered pages over the existing JSON APIs.

Parity: /root/reference/core/http/routes/ui.go (432 LoC) +
core/http/views/*.html + elements/gallery.go — home with model status,
gallery browser with live install-job progress, chat with SSE streaming,
text2image, and tts playground. The reference renders HTMX templates
pulling CDN assets; this environment is zero-egress, so every page here is
a single self-contained document (inline CSS + vanilla JS over fetch/SSE)
served from the same process. API keys: pages are readable without a key
(they hold no data), while every JS call attaches the key the operator
saves in the header field (localStorage) — the JSON APIs stay protected.
"""

from __future__ import annotations

import asyncio
import html
import json

from aiohttp import web

CSS = """
:root { --bg:#0f1217; --panel:#171c24; --line:#2a3240; --fg:#e6e9ee;
  --dim:#8b95a5; --acc:#4f9cf7; --ok:#38b26f; --warn:#d9923b; }
* { box-sizing:border-box; }
body { margin:0; background:var(--bg); color:var(--fg);
  font:15px/1.5 system-ui, sans-serif; }
a { color:var(--acc); text-decoration:none; }
header { display:flex; gap:1.2rem; align-items:center;
  padding:.7rem 1.2rem; border-bottom:1px solid var(--line);
  background:var(--panel); flex-wrap:wrap; }
header .brand { font-weight:700; }
header nav { display:flex; gap:.9rem; }
header input { margin-left:auto; }
main { max-width:980px; margin:1.4rem auto; padding:0 1rem; }
.card { background:var(--panel); border:1px solid var(--line);
  border-radius:10px; padding:1rem 1.2rem; margin-bottom:1rem; }
table { width:100%; border-collapse:collapse; }
td, th { text-align:left; padding:.45rem .5rem;
  border-bottom:1px solid var(--line); }
.badge { font-size:.78em; padding:.1rem .5rem; border-radius:999px;
  border:1px solid var(--line); color:var(--dim); }
.badge.loaded { color:var(--ok); border-color:var(--ok); }
button, input, textarea, select { background:#0c0f14; color:var(--fg);
  border:1px solid var(--line); border-radius:7px; padding:.45rem .7rem;
  font:inherit; }
button { cursor:pointer; background:var(--acc); color:#fff;
  border-color:transparent; }
button.sub { background:transparent; color:var(--acc);
  border-color:var(--line); }
progress { width:100%; height:8px; }
#log { white-space:pre-wrap; }
.msg { padding:.55rem .8rem; border-radius:9px; margin:.4rem 0;
  max-width:85%; white-space:pre-wrap; }
.msg.user { background:#23344e; margin-left:auto; }
.msg.assistant { background:#1d242f; }
.row { display:flex; gap:.6rem; align-items:center; }
.row > * { flex:1; }
.row > button { flex:0; }
.dim { color:var(--dim); }
img.out { max-width:100%; border-radius:10px; margin-top:.8rem; }
"""

JS_COMMON = """
function authHeaders(extra) {
  const h = Object.assign({'Content-Type': 'application/json'}, extra||{});
  const k = localStorage.getItem('apiKey');
  if (k) h['Authorization'] = 'Bearer ' + k;
  return h;
}
function saveKey(el) { localStorage.setItem('apiKey', el.value); }
function initKey() {
  const el = document.getElementById('apikey');
  if (el) el.value = localStorage.getItem('apiKey') || '';
}
document.addEventListener('DOMContentLoaded', initKey);
"""


def _page(title: str, body: str, script: str = "") -> web.Response:
    doc = f"""<!doctype html>
<html><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{html.escape(title)} — LocalAI-TPU</title>
<style>{CSS}</style></head>
<body>
<header>
  <span class="brand">LocalAI-TPU</span>
  <nav>
    <a href="/">Home</a>
    <a href="/browse">Models</a>
    <a href="/chat/">Chat</a>
    <a href="/talk/">Talk</a>
    <a href="/text2image/">Image</a>
    <a href="/tts/">TTS</a>
    <a href="/swarm">Swarm</a>
    <a href="/slo">SLO</a>
    <a href="/fleet">Fleet</a>
    <a href="/usage">Usage</a>
    <a href="/batches">Batches</a>
  </nav>
  <input id="apikey" placeholder="API key (if set)"
         onchange="saveKey(this)" size="18">
</header>
<main>{body}</main>
<script>{JS_COMMON}{script}</script>
</body></html>"""
    return web.Response(text=doc, content_type="text/html")


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


def _model_names(request: web.Request, usecase=None) -> list[str]:
    state = _state(request)
    names = []
    for n in state.loader.names():
        cfg = state.loader.get(n)
        if usecase is None or (cfg is not None and cfg.has_usecase(usecase)):
            names.append(n)
    return names


def _model_select(names: list[str], selected: str = "") -> str:
    opts = "".join(
        f'<option value="{html.escape(n)}"'
        f'{" selected" if n == selected else ""}>{html.escape(n)}</option>'
        for n in names
    )
    return f'<select id="model">{opts}</select>'


# ---------------------------------------------------------------------------
# home


async def home(request: web.Request) -> web.Response:
    """GET / for browsers (parity: WelcomeEndpoint + index.html —
    installed models with load state and per-usecase links)."""
    state = _state(request)
    loaded = set(state.manager.loaded_names())
    rows = []
    from localai_tpu.config.model_config import Usecase

    for name in state.loader.names():
        cfg = state.loader.get(name)
        status = ('<span class="badge loaded">loaded</span>'
                  if name in loaded else '<span class="badge">idle</span>')
        links = []
        if cfg is not None and cfg.has_usecase(Usecase.CHAT):
            links.append(f'<a href="/chat/{html.escape(name)}">chat</a>')
        if cfg is not None and cfg.has_usecase(Usecase.IMAGE):
            links.append(
                f'<a href="/text2image/{html.escape(name)}">image</a>')
        if cfg is not None and cfg.has_usecase(Usecase.TTS):
            links.append(f'<a href="/tts/{html.escape(name)}">tts</a>')
        rows.append(
            f"<tr><td>{html.escape(name)}</td><td>{status}</td>"
            f"<td>{' · '.join(links)}</td></tr>"
        )
    body = f"""
<div class="card"><h2>Installed models</h2>
<table><tr><th>Model</th><th>State</th><th></th></tr>
{''.join(rows) or '<tr><td colspan=3 class="dim">none installed — '
 '<a href="/browse">browse the gallery</a></td></tr>'}</table></div>
<div class="card dim">OpenAI-compatible API at <code>/v1</code> ·
<a href="/metrics">metrics</a> · <a href="/system">system</a></div>"""
    return _page("Home", body)


# ---------------------------------------------------------------------------
# gallery browser


async def browse(request: web.Request) -> web.Response:
    """GET /browse (parity: routes/ui.go:124-303 + elements/gallery.go —
    searchable gallery, install with live job progress, delete)."""
    body = """
<div class="card">
  <h2>Model gallery</h2>
  <div class="row">
    <input id="q" placeholder="search models…" oninput="render()">
  </div>
  <div id="list" class="dim">loading…</div>
</div>"""
    script = """
// gallery entries are THIRD-PARTY data (fetched index YAMLs): build the
// table with textContent/dataset, never innerHTML interpolation — a
// crafted name/description must not script-inject into the operator's
// browser (which holds the API key in localStorage)
let MODELS = [];
async function load() {
  try {
    const r = await fetch('/models/available', {headers: authHeaders()});
    MODELS = await r.json();
  } catch (e) { MODELS = []; }
  render();
}
function render() {
  const q = (document.getElementById('q').value || '').toLowerCase();
  const list = document.getElementById('list');
  list.textContent = '';
  const table = document.createElement('table');
  let shown = 0;
  MODELS.forEach((m, i) => {
    if (q && !(m.name + ' ' + (m.description||''))
        .toLowerCase().includes(q)) return;
    shown++;
    const tr = table.insertRow();
    const td = tr.insertCell();
    const b = document.createElement('b');
    b.textContent = m.name;
    const desc = document.createElement('span');
    desc.className = 'dim';
    desc.textContent = m.description || '';
    const job = document.createElement('div');
    job.id = 'job-' + i;
    td.append(b, document.createElement('br'), desc, job);
    const act = tr.insertCell();
    const btn = document.createElement('button');
    if (m.installed) {
      btn.className = 'sub'; btn.textContent = 'delete';
      btn.onclick = () => del(m.name);
    } else {
      btn.textContent = 'install';
      btn.onclick = () => install(m.name, i);
    }
    act.appendChild(btn);
  });
  if (shown) list.appendChild(table);
  else list.textContent = 'no models match';
}
function showErr(slot, text) {
  slot.textContent = '';
  const e = document.createElement('span');
  e.style.color = 'var(--warn)';
  e.textContent = text;
  slot.appendChild(e);
}
async function install(id, i) {
  const slot = document.getElementById('job-' + i);
  slot.innerHTML = '<progress max="100" value="0"></progress>';
  const r = await fetch('/models/apply', {method: 'POST',
    headers: authHeaders(), body: JSON.stringify({id})});
  const body = await r.json().catch(() => ({}));
  const uuid = body.uuid;
  if (!r.ok || !uuid) {
    showErr(slot, (body.error && body.error.message) ||
            ('install failed (' + r.status + ')'));
    return;
  }
  const timer = setInterval(async () => {
    const s = await (await fetch('/models/jobs/' + uuid,
                                 {headers: authHeaders()})).json();
    slot.querySelector('progress').value = s.progress || 0;
    if (s.processed) {
      clearInterval(timer);
      slot.textContent = '';
      if (s.error) {
        showErr(slot, s.error);
      } else {
        const ok = document.createElement('span');
        ok.className = 'badge loaded';
        ok.textContent = 'installed';
        slot.appendChild(ok);
        load();
      }
    }
  }, 700);
}
async function del(name) {
  await fetch('/models/delete/' + encodeURIComponent(name),
              {method: 'POST', headers: authHeaders()});
  load();
}
load();
"""
    return _page("Models", body, script)


# ---------------------------------------------------------------------------
# chat


async def chat_page(request: web.Request) -> web.Response:
    """GET /chat/[model] (parity: ui.go:305-359 + chat.html — streaming
    chat over /v1/chat/completions SSE)."""
    from localai_tpu.config.model_config import Usecase

    names = _model_names(request, Usecase.CHAT)
    selected = request.match_info.get("model", "")
    body = f"""
<div class="card">
  <div class="row"><h2 style="flex:1">Chat</h2>{_model_select(names, selected)}</div>
  <div id="msgs"></div>
  <div class="row">
    <textarea id="inp" rows="2" placeholder="say something…"
      onkeydown="if(event.key==='Enter'&&!event.shiftKey){{event.preventDefault();send();}}"></textarea>
    <button onclick="send()">Send</button>
  </div>
</div>"""
    script = """
const HISTORY = [];
function bubble(cls, text) {
  const d = document.createElement('div');
  d.className = 'msg ' + cls; d.textContent = text;
  document.getElementById('msgs').appendChild(d);
  d.scrollIntoView(); return d;
}
async function send() {
  const inp = document.getElementById('inp');
  const text = inp.value.trim();
  if (!text) return;
  inp.value = '';
  HISTORY.push({role: 'user', content: text});
  bubble('user', text);
  const out = bubble('assistant', '…');
  const resp = await fetch('/v1/chat/completions', {method: 'POST',
    headers: authHeaders(),
    body: JSON.stringify({model: document.getElementById('model').value,
      messages: HISTORY, stream: true})});
  if (!resp.ok) { out.textContent = 'error: ' + await resp.text(); return; }
  const reader = resp.body.getReader();
  const dec = new TextDecoder();
  let acc = '', buf = '';
  for (;;) {
    const {done, value} = await reader.read();
    if (done) break;
    buf += dec.decode(value, {stream: true});
    const frames = buf.split('\\n\\n'); buf = frames.pop();
    for (const f of frames) {
      const line = f.split('\\n').find(l => l.startsWith('data: '));
      if (!line || line === 'data: [DONE]') continue;
      const delta = JSON.parse(line.slice(6)).choices[0].delta;
      if (delta && delta.content) {
        acc += delta.content; out.textContent = acc;
      }
    }
  }
  HISTORY.push({role: 'assistant', content: acc});
}
"""
    return _page("Chat", body, script)


# ---------------------------------------------------------------------------
# text2image


async def text2image_page(request: web.Request) -> web.Response:
    """GET /text2image/[model] (parity: ui.go:361-395 + text2image.html)."""
    from localai_tpu.config.model_config import Usecase

    names = _model_names(request, Usecase.IMAGE)
    selected = request.match_info.get("model", "")
    body = f"""
<div class="card">
  <div class="row"><h2 style="flex:1">Generate image</h2>{_model_select(names, selected)}</div>
  <div class="row">
    <input id="prompt" placeholder="a photo of…">
    <button id="go" onclick="gen()">Generate</button>
  </div>
  <div id="out" class="dim"></div>
</div>"""
    script = """
async function gen() {
  const out = document.getElementById('out');
  const btn = document.getElementById('go');
  btn.disabled = true; out.textContent = 'generating…';
  try {
    const r = await fetch('/v1/images/generations', {method: 'POST',
      headers: authHeaders(),
      body: JSON.stringify({model: document.getElementById('model').value,
        prompt: document.getElementById('prompt').value,
        response_format: 'b64_json'})});
    const body = await r.json();
    if (!r.ok) throw new Error(JSON.stringify(body.error || body));
    out.innerHTML = body.data.map(d =>
      `<img class="out" src="data:image/png;base64,${d.b64_json}">`).join('');
  } catch (e) { out.textContent = 'error: ' + e.message; }
  btn.disabled = false;
}
"""
    return _page("Text to image", body, script)


# ---------------------------------------------------------------------------
# tts


async def tts_page(request: web.Request) -> web.Response:
    """GET /tts/[model] (parity: ui.go:397-430 + tts.html)."""
    from localai_tpu.config.model_config import Usecase

    names = _model_names(request, Usecase.TTS) or _model_names(request)
    selected = request.match_info.get("model", "")
    body = f"""
<div class="card">
  <div class="row"><h2 style="flex:1">Text to speech</h2>{_model_select(names, selected)}</div>
  <div class="row">
    <input id="text" placeholder="text to speak…">
    <button onclick="speak()">Speak</button>
  </div>
  <div id="out"></div>
</div>"""
    script = """
async function speak() {
  const out = document.getElementById('out');
  out.textContent = 'synthesizing…';
  const r = await fetch('/tts', {method: 'POST', headers: authHeaders(),
    body: JSON.stringify({model: document.getElementById('model').value,
      input: document.getElementById('text').value})});
  if (!r.ok) { out.textContent = 'error: ' + await r.text(); return; }
  const url = URL.createObjectURL(await r.blob());
  out.innerHTML = `<audio controls autoplay src="${url}"></audio>`;
}
"""
    return _page("TTS", body, script)


# ---------------------------------------------------------------------------
# talk (voice chat)


async def talk_page(request: web.Request) -> web.Response:
    """GET /talk/[model] — the voice-chat loop (parity:
    /root/reference/core/http/views/talk.html): mic → WAV (encoded
    client-side — the transcription endpoint speaks WAV, not webm) →
    /v1/audio/transcriptions → /v1/chat/completions →
    /v1/audio/speech → playback."""
    from localai_tpu.config.model_config import Usecase

    chat_models = _model_names(request, Usecase.CHAT) \
        or _model_names(request)
    stt = _model_names(request, Usecase.TRANSCRIPT)
    tts = _model_names(request, Usecase.TTS)
    selected = request.match_info.get("model", "")

    def select(id_, names):
        opts = "".join(
            f'<option value="{html.escape(n)}"'
            f'{" selected" if n == selected else ""}>'
            f'{html.escape(n)}</option>'
            for n in names) or "<option value=''>(default)</option>"
        return f'<select id="{id_}">{opts}</select>'

    body = f"""
<div class="card">
  <div class="row"><h2 style="flex:1">Talk</h2>
    <label>chat {select("model", chat_models)}</label>
    <label>stt {select("sttmodel", stt)}</label>
    <label>tts {select("ttsmodel", tts)}</label>
  </div>
  <div class="row">
    <button id="rec" onclick="toggleRec()">● Record</button>
    <span id="status">idle</span>
  </div>
  <div id="log"></div>
  <div id="out"></div>
</div>"""
    script = """
let ctx, source, proc, stream, chunks = [], recording = false, history = [];
function logLine(who, text) {
  const d = document.createElement('div');
  d.textContent = who + ': ' + text;
  document.getElementById('log').appendChild(d);
}
function wavBlob(buffers, rate) {
  let n = 0; buffers.forEach(b => n += b.length);
  const pcm = new Int16Array(n); let off = 0;
  buffers.forEach(b => { for (let i = 0; i < b.length; i++)
    pcm[off++] = Math.max(-1, Math.min(1, b[i])) * 32767; });
  const buf = new ArrayBuffer(44 + pcm.length * 2);
  const v = new DataView(buf);
  const ws = (o, s) => { for (let i = 0; i < s.length; i++)
    v.setUint8(o + i, s.charCodeAt(i)); };
  ws(0, 'RIFF'); v.setUint32(4, 36 + pcm.length * 2, true); ws(8, 'WAVE');
  ws(12, 'fmt '); v.setUint32(16, 16, true); v.setUint16(20, 1, true);
  v.setUint16(22, 1, true); v.setUint32(24, rate, true);
  v.setUint32(28, rate * 2, true); v.setUint16(32, 2, true);
  v.setUint16(34, 16, true); ws(36, 'data');
  v.setUint32(40, pcm.length * 2, true);
  new Int16Array(buf, 44).set(pcm);
  return new Blob([buf], {type: 'audio/wav'});
}
async function toggleRec() {
  const btn = document.getElementById('rec');
  const status = document.getElementById('status');
  if (!recording) {
    stream = await navigator.mediaDevices.getUserMedia({audio: true});
    ctx = new AudioContext();
    source = ctx.createMediaStreamSource(stream);
    proc = ctx.createScriptProcessor(4096, 1, 1);
    chunks = [];
    proc.onaudioprocess = e =>
      chunks.push(new Float32Array(e.inputBuffer.getChannelData(0)));
    source.connect(proc); proc.connect(ctx.destination);
    recording = true; btn.textContent = '■ Stop'; status.textContent =
      'recording…';
    return;
  }
  recording = false; btn.textContent = '● Record';
  proc.disconnect(); source.disconnect();
  stream.getTracks().forEach(t => t.stop());  // release the microphone
  const rate = ctx.sampleRate; ctx.close();
  status.textContent = 'transcribing…';
  const fd = new FormData();
  fd.append('file', wavBlob(chunks, rate), 'talk.wav');
  fd.append('model', document.getElementById('sttmodel').value);
  // multipart: the browser must set its own boundary content-type
  const auth = {}; const k = localStorage.getItem('apiKey');
  if (k) auth['Authorization'] = 'Bearer ' + k;
  const tr = await fetch('/v1/audio/transcriptions',
    {method: 'POST', headers: auth, body: fd});
  if (!tr.ok) { status.textContent = 'stt error: ' + await tr.text();
    return; }
  const text = (await tr.json()).text;
  logLine('you', text);
  history.push({role: 'user', content: text});
  status.textContent = 'thinking…';
  const cr = await fetch('/v1/chat/completions', {method: 'POST',
    headers: authHeaders(),
    body: JSON.stringify({model: document.getElementById('model').value,
      messages: history})});
  if (!cr.ok) { status.textContent = 'chat error: ' + await cr.text();
    return; }
  const reply = (await cr.json()).choices[0].message.content;
  history.push({role: 'assistant', content: reply});
  logLine('assistant', reply);
  status.textContent = 'speaking…';
  const sr = await fetch('/v1/audio/speech', {method: 'POST',
    headers: authHeaders(),
    body: JSON.stringify({model: document.getElementById('ttsmodel').value,
      input: reply})});
  if (!sr.ok) { status.textContent = 'tts error: ' + await sr.text();
    return; }
  const url = URL.createObjectURL(await sr.blob());
  document.getElementById('out').innerHTML =
    `<audio controls autoplay src="${url}"></audio>`;
  status.textContent = 'idle';
}
"""
    return _page("Talk", body, script)


# ---------------------------------------------------------------------------
# swarm (federation status)


async def swarm_page(request: web.Request) -> web.Response:
    """GET /swarm[?router=URL] — federation-nodes dashboard (parity:
    /root/reference/core/http/views/p2p.html + routes/ui.go:432). The node
    table comes from the router's /federated/nodes registry, fetched
    server-side (/swarm/nodes) so the browser needs no cross-origin
    access."""
    router = request.query.get("router", "http://127.0.0.1:8080")
    body = f"""
<div class="card">
  <div class="row"><h2 style="flex:1">Federation swarm</h2>
    <input id="router" value="{html.escape(router)}" size="28">
    <button onclick="refresh()">Refresh</button>
  </div>
  <div id="nodes">loading…</div>
</div>"""
    script = """
function esc(v) {  // router-supplied fields are untrusted — escape all
  const d = document.createElement('div');
  d.textContent = String(v);
  return d.innerHTML;
}
async function refresh() {
  const out = document.getElementById('nodes');
  const router = encodeURIComponent(document.getElementById('router').value);
  const r = await fetch('/swarm/nodes?router=' + router,
    {headers: authHeaders()});
  if (!r.ok) { out.textContent = 'error: ' + await r.text(); return; }
  const data = await r.json();
  const rows = (data.nodes || []).map(n =>
    `<tr><td>${esc(n.id)}</td><td>${esc(n.address)}</td>` +
    `<td>${n.online ? 'online' : 'OFFLINE'}</td>` +
    `<td>${esc(n.requests)}</td><td>${esc(n.failures)}</td></tr>`).join('');
  out.innerHTML = `<p>${esc(data.online ?? 0)}/${(data.nodes || []).length}` +
    ` nodes online</p><table><tr><th>id</th><th>address</th><th>state</th>` +
    `<th>requests</th><th>failures</th></tr>${rows}</table>`;
}
refresh();
"""
    return _page("Swarm", body, script)


def _norm_router(url: str):
    """(scheme, host, port, path) canonical form for allowlist comparison:
    scheme/host lowercased, default ports made explicit, trailing slash
    dropped — so ``HTTP://Router:80/`` and ``http://router`` compare equal
    (ADVICE r5 #3: exact-string comparison rejected benign variants of the
    configured router). None for anything that is not plain http(s) or
    carries userinfo."""
    from urllib.parse import urlsplit

    try:
        parts = urlsplit(url)
    except ValueError:
        return None
    scheme = (parts.scheme or "").lower()
    if scheme not in ("http", "https"):
        return None
    if parts.username is not None or parts.password is not None:
        return None
    try:
        port = parts.port
    except ValueError:
        return None
    host = (parts.hostname or "").lower()
    return (scheme, host, port or (443 if scheme == "https" else 80),
            parts.path.rstrip("/"))


async def swarm_nodes(request: web.Request) -> web.Response:
    """GET /swarm/nodes?router=URL — server-side registry fetch.

    The target is restricted to the configured allowlist
    (federated_router / swarm_routers, compared in canonical
    scheme/host/port form) so an API-key holder can't use the server as an
    internal-network probe (ADVICE r4). The only exemption is loopback AT
    THIS SERVER'S OWN PORT — the colocated-router case — not loopback at
    large, which would let a key holder sweep every local service's ports
    (ADVICE r5 #3)."""
    from localai_tpu.federation.explorer import fetch_nodes

    router = request.query.get("router", "http://127.0.0.1:8080")
    if not router.startswith(("http://", "https://")):
        raise web.HTTPBadRequest(text="router must be an http(s) URL")
    if "?" in router or "#" in router:
        # a query/fragment would neutralize the appended /federated/nodes
        # suffix and turn the proxy into a generic URL fetcher
        raise web.HTTPBadRequest(text="router URL must not carry a query")
    target = _norm_router(router)
    if target is None:
        # userinfo would desynchronize any naive host check from where
        # urlopen actually connects; same for malformed URLs
        raise web.HTTPBadRequest(
            text="malformed router URL (no userinfo, http(s) only)")
    cfg = getattr(_state(request), "config", None)
    allowed = {
        _norm_router(r.strip()) for r in (
            getattr(cfg, "federated_router", ""),
            getattr(cfg, "swarm_routers", "") or "",
        ) for r in r.split(",") if r.strip()
    } - {None}
    own_port = target[1] in ("127.0.0.1", "localhost", "::1") and (
        target[2] == getattr(cfg, "port", None))
    if target not in allowed and not own_port:
        raise web.HTTPForbidden(
            text="router not in the configured allowlist "
                 "(federated_router / swarm_routers)")
    loop = asyncio.get_running_loop()
    try:
        data = await loop.run_in_executor(None, fetch_nodes, router)
    except Exception as e:  # noqa: BLE001 — router down renders as such
        raise web.HTTPBadGateway(text=f"router unreachable: {e}")
    return web.json_response(data)


# ---------------------------------------------------------------------------
# SLO observatory + flight recorder


async def slo_page(request: web.Request) -> web.Response:
    """GET /slo — live serving-health panel over the JSON APIs: per-model
    sliding-window latency percentiles + burn rates (/v1/slo) and the
    engine flight recorder's dispatch timeline (/debug/flight). Pure
    read-side polling; the page holds no data of its own."""
    body = """
<div class="card">
  <div class="row"><h2 style="flex:1">SLO observatory</h2>
    <span id="shed" class="badge">…</span></div>
  <div id="slo" class="dim">loading…</div>
</div>
<div class="card">
  <h2>Flight recorder</h2>
  <div id="flight" class="dim">loading…</div>
</div>
<div class="card">
  <h2>Dispatch anatomy</h2>
  <div class="dim" style="margin-bottom:6px">
    windowed wall-time shares per model: gap / sched / launch / sync /
    unattributed (obs.anatomy — bubble is an estimator)</div>
  <div id="anatomy" class="dim">loading…</div>
</div>"""
    script = """
function fmt(v, d) {
  return (v === null || v === undefined) ? '—' : Number(v).toFixed(d ?? 1);
}
function table(out, headers, rows) {  // textContent only: API data is
  out.textContent = '';               // untrusted for innerHTML
  const t = document.createElement('table');
  const hr = t.insertRow();
  headers.forEach(h => {
    const th = document.createElement('th');
    th.textContent = h; hr.appendChild(th);
  });
  rows.forEach(r => {
    const tr = t.insertRow();
    r.forEach(v => tr.insertCell().textContent = v);
  });
  out.appendChild(t);
  if (!rows.length) out.textContent = 'no data yet';
}
async function refresh() {
  try {
    const s = await (await fetch('/v1/slo', {headers: authHeaders()})).json();
    const models = s.models || {};
    const shedding = Object.values(models).some(m => m.shedding);
    const badge = document.getElementById('shed');
    badge.textContent = shedding ? 'SHEDDING' : 'healthy';
    badge.className = 'badge' + (shedding ? '' : ' loaded');
    const rows = [];
    for (const [name, m] of Object.entries(models)) {
      for (const [w, a] of Object.entries(m.windows || {})) {
        rows.push([name, w, a.count,
                   fmt(a.ttft_ms && a.ttft_ms.p95),
                   fmt(a.tpot_ms && a.tpot_ms.p95, 2),
                   fmt(a.e2e_ms && a.e2e_ms.p95),
                   fmt(a.burn_rate, 2),
                   m.shedding ? 'shedding (' + m.shed_total + ' shed)'
                              : 'ok']);
      }
    }
    table(document.getElementById('slo'),
          ['model', 'window', 'n', 'ttft p95 ms', 'tpot p95 ms',
           'e2e p95 ms', 'burn', 'state'], rows);
  } catch (e) {
    document.getElementById('slo').textContent = 'error: ' + e.message;
  }
  try {
    const f = await (await fetch('/debug/flight?limit=64',
                                 {headers: authHeaders()})).json();
    const rows = [];
    for (const [name, m] of Object.entries(f.models || {})) {
      const last = m.records[m.records.length - 1] || {};
      rows.push([name, m.dispatches, m.tokens_total,
                 fmt(m.percentiles.step_ms_p50, 2),
                 fmt(m.percentiles.step_ms_p99, 2),
                 fmt(last.occupancy, 2),
                 last.queue_depth ?? '—',
                 fmt(last.kv_utilization, 2),
                 last.spec_accept == null ? '—'
                                          : fmt(last.spec_accept, 2)]);
    }
    table(document.getElementById('flight'),
          ['model', 'dispatches', 'tokens', 'step p50 ms', 'step p99 ms',
           'occupancy', 'queue', 'kv util', 'spec accept'], rows);
  } catch (e) {
    document.getElementById('flight').textContent = 'error: ' + e.message;
  }
  try {
    const a = await (await fetch('/debug/anatomy',
                                 {headers: authHeaders()})).json();
    const out = document.getElementById('anatomy');
    out.textContent = '';
    const colors = {gap: '#888', sched: '#d90', launch: '#38c',
                    sync: '#2a6', unattributed: '#444'};
    let any = false;
    for (const [name, m] of Object.entries(a.models || {})) {
      if (!m.samples) continue;
      any = true;
      const row = document.createElement('div');
      row.style.margin = '6px 0';
      const label = document.createElement('div');
      label.textContent = name + ' — host overhead ' +
        fmt(m.host_overhead_fraction, 3) + ' · bubble ' +
        fmt(m.device_bubble_fraction, 3) + ' · ' + m.samples +
        ' dispatches / ' + fmt(m.dispatch_ms_total, 0) + ' ms';
      row.appendChild(label);
      const bar = document.createElement('div');
      bar.style.cssText =
        'display:flex;height:14px;border-radius:3px;overflow:hidden;' +
        'background:#222;margin-top:2px';
      const shares = Object.assign({}, m.phase_share || {});
      shares.unattributed = m.unattributed_share;
      for (const [ph, share] of Object.entries(shares)) {
        if (!share) continue;
        const seg = document.createElement('div');
        seg.style.width = (share * 100).toFixed(1) + '%';
        seg.style.background = colors[ph] || '#666';
        seg.title = ph + ' ' + (share * 100).toFixed(1) + '%';
        bar.appendChild(seg);
      }
      row.appendChild(bar);
      const legend = document.createElement('div');
      legend.className = 'dim';
      legend.textContent = Object.entries(shares)
        .filter(([, v]) => v != null)
        .map(([ph, v]) => ph + ' ' + (v * 100).toFixed(1) + '%')
        .join(' · ');
      row.appendChild(legend);
      out.appendChild(row);
    }
    if (!any) out.textContent = 'no dispatches in window yet';
  } catch (e) {
    document.getElementById('anatomy').textContent = 'error: ' + e.message;
  }
}
refresh();
setInterval(refresh, 2000);
"""
    return _page("SLO", body, script)


# ---------------------------------------------------------------------------
# fleet router


async def fleet_page(request: web.Request) -> web.Response:
    """GET /fleet — replica-fleet panel over GET /v1/fleet: per-replica
    lifecycle state, dial health, routing mix (affinity / least-loaded /
    failover + route-around), and disaggregated prefix-transfer stats.
    Read-side polling only."""
    body = """
<div class="card">
  <div class="row"><h2 style="flex:1">Fleet</h2>
    <span id="fhealth" class="badge">…</span></div>
  <div id="replicas" class="dim">loading…</div>
</div>
<div class="card">
  <h2>Routing</h2>
  <div id="routing" class="dim">loading…</div>
  <p class="dim">Placement: prompt-prefix affinity (token-chain block hash
  → consistent-hash ring) with least-loaded fallback; shed replicas are
  routed around; a replica dying mid-request fails over.</p>
</div>"""
    script = """
function table(out, headers, rows) {  // textContent only: API data is
  out.textContent = '';               // untrusted for innerHTML
  const t = document.createElement('table');
  const hr = t.insertRow();
  headers.forEach(h => {
    const th = document.createElement('th');
    th.textContent = h; hr.appendChild(th);
  });
  rows.forEach(r => {
    const tr = t.insertRow();
    r.forEach(v => tr.insertCell().textContent = v);
  });
  out.appendChild(t);
  if (!rows.length) out.textContent = 'no fleet-served models';
}
async function refresh() {
  try {
    const d = await (await fetch('/v1/fleet',
                                 {headers: authHeaders()})).json();
    const models = d.models || {};
    const reps = [], routing = [];
    let dead = 0, healthy = 0;
    for (const [name, m] of Object.entries(models)) {
      if (!m.fleet) continue;
      (m.replicas || []).forEach(r => {
        if (r.state === 'healthy') healthy++; else dead++;
        const shed = (m.shedding || {})[r.id];
        reps.push([r.id, r.role, r.state + (shed ? ' (shedding)' : ''),
                   r.inflight, r.dispatched, r.errors,
                   r.dial_seconds === null ? '—' : r.dial_seconds + 's',
                   r.checked_age_s === null ? '—' : r.checked_age_s + 's']);
      });
      const rt = (m.router || {}).routed || {};
      routing.push([name, rt.affinity || 0, rt.least_loaded || 0,
                    rt.failover || 0, (m.router || {}).routed_around || 0,
                    m.respawns || 0, m.prefix_transfers || 0,
                    m.prefix_transfer_bytes || 0, m.disagg_fallbacks || 0]);
    }
    const badge = document.getElementById('fhealth');
    badge.textContent = dead ? (dead + ' degraded') :
                        (healthy ? healthy + ' healthy' : 'no fleet');
    badge.className = 'badge' + (dead ? '' : ' loaded');
    table(document.getElementById('replicas'),
          ['replica', 'role', 'state', 'inflight', 'dispatched', 'errors',
           'dial', 'checked'], reps);
    table(document.getElementById('routing'),
          ['model', 'affinity', 'least-loaded', 'failover', 'routed around',
           'respawns', 'prefix transfers', 'transfer bytes',
           'disagg fallbacks'], routing);
  } catch (e) {
    document.getElementById('replicas').textContent = 'error: ' + e.message;
  }
}
refresh();
setInterval(refresh, 2000);
"""
    return _page("Fleet", body, script)


# ---------------------------------------------------------------------------
# usage accounting


async def usage_page(request: web.Request) -> web.Response:
    """GET /usage — usage & goodput panel over GET /v1/usage: per-tenant
    cost rows (delivered tokens, dispatch ms, queue wait, KV-block-
    seconds by model/lane), the goodput ratio, and the waste
    decomposition by reason. Tenants are hashed buckets — no key
    material ever reaches this page. Read-side polling only."""
    body = """
<div class="card">
  <div class="row"><h2 style="flex:1">Usage</h2>
    <span id="goodput" class="badge">…</span></div>
  <div id="tenants" class="dim">loading…</div>
</div>
<div class="card">
  <h2>Waste decomposition</h2>
  <div id="waste" class="dim">loading…</div>
  <p class="dim">Goodput = tokens delivered on natural completions
  (stop/length). Waste classes: speculation-rejected draft tokens,
  failover/migration re-prefills, shed admissions, cancelled and
  NaN-quarantined requests.</p>
</div>"""
    script = """
function fmt(v, d) {
  return (v === null || v === undefined) ? '—' : Number(v).toFixed(d ?? 1);
}
function table(out, headers, rows, empty) {  // textContent only: API
  out.textContent = '';                      // data is untrusted
  const t = document.createElement('table');
  const hr = t.insertRow();
  headers.forEach(h => {
    const th = document.createElement('th');
    th.textContent = h; hr.appendChild(th);
  });
  rows.forEach(r => {
    const tr = t.insertRow();
    r.forEach(v => tr.insertCell().textContent = v);
  });
  out.appendChild(t);
  if (!rows.length) out.textContent = empty || 'no data yet';
}
async function refresh() {
  try {
    const d = await (await fetch('/v1/usage',
                                 {headers: authHeaders()})).json();
    const badge = document.getElementById('goodput');
    const g = d.goodput || {};
    badge.textContent = 'goodput ' + fmt(100 * (g.goodput_ratio ?? 1)) + '%';
    badge.className = 'badge' +
      ((g.goodput_ratio ?? 1) >= 0.9 ? ' loaded' : '');
    const rows = (d.data || []).map(p =>
      [p.tenant, p.model + '/' + p.lane, p.requests, p.delivered_tokens,
       p.prompt_tokens, fmt(p.dispatch_ms, 0), fmt(p.queue_wait_ms, 0),
       fmt(p.kv_block_seconds, 1), p.waste_tokens]);
    table(document.getElementById('tenants'),
          ['tenant', 'model/lane', 'req', 'delivered', 'prompt',
           'dispatch ms', 'queue ms', 'kv blk·s', 'wasted'], rows,
          'no attributed requests yet');
    const wrows = (d.waste || []).map(c =>
      [c.reason, c.model, c.tokens, c.requests]);
    table(document.getElementById('waste'),
          ['reason', 'model', 'tokens', 'requests'], wrows,
          'no waste recorded');
  } catch (e) {
    document.getElementById('tenants').textContent = 'error: ' + e.message;
  }
}
refresh();
setInterval(refresh, 2000);
"""
    return _page("Usage", body, script)


# ---------------------------------------------------------------------------
# offline batch jobs


async def batches_page(request: web.Request) -> web.Response:
    """GET /batches — offline batch-job panel over GET /v1/batches: job
    list with live progress counts and lifecycle state. Read-side polling
    only (job creation goes through the JSON API with an uploaded file)."""
    body = """
<div class="card">
  <div class="row"><h2 style="flex:1">Batch jobs</h2>
    <span id="lane" class="badge">…</span></div>
  <div id="jobs" class="dim">loading…</div>
  <p class="dim">Submit jobs with <code>POST /v1/files</code>
  (purpose=batch) + <code>POST /v1/batches</code>; download results from
  <code>/v1/files/{output_file_id}/content</code>.</p>
</div>"""
    script = """
function table(out, headers, rows) {  // textContent only: API data is
  out.textContent = '';               // untrusted for innerHTML
  const t = document.createElement('table');
  const hr = t.insertRow();
  headers.forEach(h => {
    const th = document.createElement('th');
    th.textContent = h; hr.appendChild(th);
  });
  rows.forEach(r => {
    const tr = t.insertRow();
    r.forEach(v => tr.insertCell().textContent = v);
  });
  out.appendChild(t);
  if (!rows.length) out.textContent = 'no batch jobs yet';
}
async function refresh() {
  try {
    const d = await (await fetch('/v1/batches',
                                 {headers: authHeaders()})).json();
    const jobs = d.data || [];
    const active = jobs.some(j => j.status === 'in_progress');
    const badge = document.getElementById('lane');
    badge.textContent = active ? 'RUNNING' : 'idle';
    badge.className = 'badge' + (active ? ' loaded' : '');
    const rows = jobs.map(j => {
      const c = j.request_counts || {};
      const done = (c.completed || 0) + (c.failed || 0);
      const pct = c.total ? Math.round(100 * done / c.total) : 0;
      return [j.id, j.endpoint, j.status,
              done + '/' + (c.total || 0) + ' (' + pct + '%)',
              c.failed || 0,
              j.output_file_id || '—',
              new Date((j.created_at || 0) * 1000).toLocaleString()];
    });
    table(document.getElementById('jobs'),
          ['id', 'endpoint', 'status', 'progress', 'failed',
           'output file', 'created'], rows);
  } catch (e) {
    document.getElementById('jobs').textContent = 'error: ' + e.message;
  }
}
refresh();
setInterval(refresh, 2000);
"""
    return _page("Batches", body, script)


# ---------------------------------------------------------------------------
# wiring


# page prefixes GETtable without an API key (imported by the server's
# auth middleware — single source of truth for the exemption)
UI_PREFIXES = ("/browse", "/chat/", "/text2image/", "/tts/", "/talk/")
# exact-match key-free pages (prefix matching would also exempt JSON
# sub-routes like /swarm/nodes, which must stay API-key-protected — that
# endpoint performs server-side fetches of the operator-named router)
UI_EXACT = ("/swarm", "/slo", "/batches", "/fleet", "/usage")


def wants_html(request: web.Request) -> bool:
    return "text/html" in request.headers.get("Accept", "")


def routes() -> list[web.RouteDef]:
    return [
        web.get("/browse", browse),
        web.get("/chat/", chat_page),
        web.get("/chat/{model}", chat_page),
        web.get("/text2image/", text2image_page),
        web.get("/text2image/{model}", text2image_page),
        web.get("/tts/", tts_page),
        web.get("/tts/{model}", tts_page),
        web.get("/talk/", talk_page),
        web.get("/talk/{model}", talk_page),
        web.get("/swarm", swarm_page),
        web.get("/swarm/nodes", swarm_nodes),
        web.get("/slo", slo_page),
        web.get("/batches", batches_page),
        web.get("/fleet", fleet_page),
        web.get("/usage", usage_page),
    ]
