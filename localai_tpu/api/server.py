"""HTTP application wiring: middlewares (auth, metrics, errors, CORS) +
route registration + lifecycle.

Parity: /root/reference/core/http/app.go:52-186 — fiber app with error
handling (optional opaque errors), request logging, recover, metrics
middleware, key-auth with exemptions, CORS, route registration — rebuilt
on aiohttp (FastAPI/uvicorn are not in this image; aiohttp is, and SSE
streaming maps directly onto StreamResponse).
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import secrets
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from aiohttp import web

from localai_tpu.api import localai as localai_routes
from localai_tpu.api import openai as openai_routes
from localai_tpu.api.metrics import REGISTRY
from localai_tpu.api.schema import error_body
from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader
from localai_tpu.models.manager import ModelManager
from localai_tpu.obs import logging as obs_logging
from localai_tpu.obs import trace as obs_trace

log = logging.getLogger(__name__)

STATE_KEY = web.AppKey("state", object)
# per-request trace id, set by trace_middleware (a plain str key: aiohttp
# Requests are MutableMappings; handlers read it via request.get())
TRACE_KEY = "trace_id"
# per-request tenant bucket (obs.ledger.derive_tenant output — hashed
# key / anonymous; NEVER the raw key), set by auth_middleware
TENANT_KEY = "tenant"
# observability/probe endpoints whose HTTP spans are pure scrape noise:
# they still get a trace id, but are not recorded into the trace store
# (a 15s Prometheus scrape would otherwise dominate the http ring)
TRACE_SKIP = {"/metrics", "/healthz", "/readyz", "/v1/traces", "/v1/slo",
              "/debug/devices", "/debug/programs", "/debug/stacks",
              "/debug/flight", "/debug/fleet/flight", "/debug/profiles",
              "/debug/kv", "/debug/faults"}
TRACE_SKIP_PREFIXES = ("/debug/timeline/", "/v1/traces/")

# paths reachable without an API key (parity: auth exemption filter,
# core/http/middleware/auth.go:17+)
# /swagger docs expose only the route list, which the exempt "/" JSON
# welcome already lists; the explorer page fetches doc.json without auth
AUTH_EXEMPT = {"/", "/healthz", "/readyz", "/version", "/swagger",
               "/swagger/doc.json"}
# UI documents are key-free to GET (they hold no data; their JS calls the
# protected JSON APIs with the key the operator enters in the page header)
from localai_tpu.api.ui import UI_EXACT, UI_PREFIXES  # noqa: E402


class ContextExecutor(ThreadPoolExecutor):
    """ThreadPoolExecutor that copies the caller's contextvars into the
    worker thread. ``loop.run_in_executor`` does NOT do this, so without
    it every log line from a blocking engine wait (lazy model load, the
    generation join) would lose the request's bound trace id
    (obs.logging) and break the JSON-log ↔ trace join."""

    def submit(self, fn, /, *args, **kwargs):
        ctx = contextvars.copy_context()
        return super().submit(lambda: ctx.run(fn, *args, **kwargs))


class AppState:
    """Shared handler state (the reference passes (cl, ml, appConfig)
    closures into every endpoint — app.go:159-165)."""

    def __init__(self, app_config: Optional[AppConfig] = None,
                 loader: Optional[ConfigLoader] = None,
                 manager: Optional[ModelManager] = None):
        from localai_tpu.gallery import Gallery

        self.config = app_config or AppConfig()
        self.loader = loader or ConfigLoader(self.config.model_path)
        self.manager = manager or ModelManager(self.config, self.loader)
        # deterministic fault injection (localai_tpu.faults): arm any
        # LOCALAI_FAULT_* specs once at boot — the registry is never
        # consulted from a request path while nothing is armed
        from localai_tpu import faults

        faults.install_from_env()
        # SLO observatory targets from app config (env-overridable via
        # LOCALAI_SLO_* through AppConfig.from_env; all-zero = shedding
        # disabled). Wired here so every server entry path — serve(),
        # tests, embedded — configures the process-wide tracker once.
        from localai_tpu.obs import slo as obs_slo

        obs_slo.SLO.configure(
            targets=obs_slo.targets_from_config(self.config),
            burn_threshold=self.config.slo_burn_threshold,
        )
        # anomaly-triggered profiler capture (obs.profiler): armed only
        # when LOCALAI_PROFILE_ON_ANOMALY=1 — hooks watchdog stalls, SLO
        # shed onsets, and the per-engine flight rings; profiles land
        # under <backend-assets>/profiles with a manifest
        # (GET /debug/profiles)
        from localai_tpu.obs import profiler as obs_profiler

        obs_profiler.install_from_env(
            str(self.config.backend_assets_path or "."))
        # multi-resolution metrics history (obs.history): re-onboard the
        # last snapshot and start the periodic writer thread when
        # LOCALAI_HISTORY_DIR is set — the series survive restarts
        from localai_tpu.obs import history as obs_history

        obs_history.install_from_env()
        self.galleries: list[Gallery] = [
            Gallery(name=g.get("name", ""), url=g.get("url", ""))
            for g in self.config.galleries
        ]
        self._gallery_service = None
        from localai_tpu.stores import StoreRegistry

        self.stores = StoreRegistry()
        # blocking engine waits run here, off the event loop (contextvar-
        # propagating: executor-side log lines keep the request trace id)
        self.executor = ContextExecutor(
            max_workers=32, thread_name_prefix="api-wait"
        )
        # dynamic config: api_keys.json / external_backends.json hot-reload
        # (parity: core/startup/config_file_watcher.go)
        from localai_tpu.config.watcher import (
            ConfigWatcher,
            attach_standard_handlers,
        )

        self.watcher = ConfigWatcher(self.config.config_path)
        attach_standard_handlers(self.watcher, self)
        self.watcher.start()
        # unified /v1/files registry + assistants persistence, reloaded at
        # boot (parity: app.go:152-154 LoadConfig of assistants.json/
        # uploadedFiles.json) — one FileRegistry serves assistants
        # attachments, batch inputs, and batch result downloads
        from localai_tpu.api.assistants import AssistantStore
        from localai_tpu.batch import BatchStore, FileRegistry

        self.files = FileRegistry(self.config.upload_path)
        self.assistants = AssistantStore(
            self.config.config_path, self.config.upload_path,
            registry=self.files,
        )
        # offline batch subsystem: durable job store now, executor thread
        # lazily (batch_service) — but jobs that survived a restart resume
        # without waiting for an API call
        self.batches = BatchStore(
            self.config.upload_path, self.files,
            expiry_h=self.config.batch_expiry_h,
        )
        self._batch_service = None
        if self.batches.runnable() is not None:
            self.batch_service.wake()

    @property
    def batch_service(self):
        """Lazily started batch executor (the background-lane drain
        thread); first access starts it."""
        if self._batch_service is None:
            from localai_tpu.batch import BatchExecutor

            def serving_for(name: str):
                mcfg = self.loader.get(name)
                if mcfg is None:
                    raise ValueError(f"model {name!r} not found")
                return self.manager.get(name), mcfg

            self._batch_service = BatchExecutor(
                self.batches, serving_for,
                concurrency=self.config.batch_concurrency,
                deadline_s=self.config.request_deadline_s,
            )
            self._batch_service.start()
        return self._batch_service

    @property
    def gallery_service(self):
        """Lazily started job runner (parity: gallery service start,
        core/http/app.go:141-150)."""
        if self._gallery_service is None:
            from localai_tpu.gallery import GalleryService

            self._gallery_service = GalleryService(
                self.config.model_path, self.galleries,
                on_installed=lambda p: self.loader.load_single(
                    p, context_size=self.config.context_size
                ),
                on_deleted=self.loader.remove,
            )
        return self._gallery_service

    def add_gallery(self, gallery) -> None:
        self.galleries.append(gallery)
        if self._gallery_service is not None:
            self._gallery_service.galleries = list(self.galleries)

    def remove_gallery(self, name: str) -> bool:
        before = len(self.galleries)
        self.galleries = [g for g in self.galleries if g.name != name]
        if self._gallery_service is not None:
            self._gallery_service.galleries = list(self.galleries)
        return len(self.galleries) < before

    def shutdown(self) -> None:
        self.watcher.stop()
        if self._batch_service is not None:
            # stop BEFORE the engines go down: an in_progress job stays
            # durable and resumes from its output file on next boot
            self._batch_service.stop()
        self.manager.shutdown_all()
        if self._gallery_service is not None:
            self._gallery_service.shutdown()
        self.executor.shutdown(wait=False, cancel_futures=True)


@web.middleware
async def error_middleware(request: web.Request, handler):
    state = request.app[STATE_KEY]
    try:
        return await handler(request)
    except web.HTTPException as e:
        if e.status >= 400:
            msg = e.text or e.reason or "error"
            resp = web.json_response(
                error_body(msg, code=e.status), status=e.status
            )
            # the JSON re-wrap must not strip semantic headers the
            # handler set on the exception (Retry-After on a shed 429,
            # Allow on a 405, ...) — only the body-describing ones are
            # superseded by the JSON wrapper
            for k, v in e.headers.items():
                if k.lower() not in ("content-type", "content-length"):
                    resp.headers[k] = v
            return resp
        raise
    except Exception as e:  # noqa: BLE001 — recover middleware parity
        log.exception("unhandled error on %s %s", request.method,
                      request.path)
        msg = ("internal error" if state.config.opaque_errors
               else f"{type(e).__name__}: {e}")
        return web.json_response(
            error_body(msg, kind="internal_error", code=500), status=500
        )


def _canonical_path(request: web.Request) -> str:
    # the matched route pattern, not the raw URL — raw paths are
    # attacker-controlled and would grow the registry without bound
    resource = getattr(request.match_info.route, "resource", None)
    return getattr(resource, "canonical", None) or "(unmatched)"


@web.middleware
async def metrics_middleware(request: web.Request, handler):
    t0 = time.monotonic()
    try:
        return await handler(request)
    finally:
        REGISTRY.api_call.observe(
            time.monotonic() - t0,
            method=request.method, path=_canonical_path(request),
        )


@web.middleware
async def trace_middleware(request: web.Request, handler):
    """Tag every request with a trace id (client-supplied X-Trace-ID /
    X-Correlation-ID, else generated) and record its HTTP span into the
    trace store — the root the engine's request spans group under."""
    tid = (request.headers.get("X-Trace-ID")
           or request.headers.get("X-Correlation-ID")
           or obs_trace.new_trace_id())
    request[TRACE_KEY] = tid
    # bind for structured logging: every log line emitted from this
    # request's context (handlers run as one asyncio task; contextvars
    # isolate concurrent requests) carries the trace id in JSON mode
    log_token = obs_logging.bind_trace_id(tid)
    t0 = time.monotonic()
    status = 500
    try:
        resp = await handler(request)
        status = resp.status
        if not resp.prepared:  # streaming handlers already sent headers
            resp.headers["X-Trace-ID"] = tid
        return resp
    except web.HTTPException as e:
        status = e.status
        raise
    finally:
        obs_logging.unbind_trace_id(log_token)
        if (request.path not in TRACE_SKIP
                and not request.path.startswith(TRACE_SKIP_PREFIXES)):
            tr = obs_trace.RequestTrace(
                tid, f"http-{id(request):x}", kind="http",
                method=request.method, path=_canonical_path(request),
                status=status,
            )
            tr.t0 = t0
            span = tr.begin("http", method=request.method,
                            path=_canonical_path(request), status=status)
            span.t0 = t0  # the span covers the whole handler, not just now
            tr.end("http")
            obs_trace.STORE.record(tr)


@web.middleware
async def auth_middleware(request: web.Request, handler):
    """Key auth + tenant derivation (obs.ledger): the ledger's tenant
    bucket is stamped HERE — a contextvar the ContextExecutor propagates
    into engine waits (build_gen_request resolves it), plus a request
    key for handlers. Always derive_tenant()'s output, never the raw
    key: auth-off/exempt traffic lands in the ``anonymous`` bucket."""
    from localai_tpu.obs import ledger as obs_ledger

    state = request.app[STATE_KEY]
    keys = state.config.api_keys

    def _admit(tenant: str):
        request[TENANT_KEY] = tenant
        obs_ledger.set_current_tenant(tenant)
        return handler(request)

    if not keys or request.path in AUTH_EXEMPT:
        return await _admit(obs_ledger.ANONYMOUS)
    if (request.method == "GET" and not state.config.disable_webui
            and (request.path.startswith(UI_PREFIXES)
                 or request.path in UI_EXACT)):
        return await _admit(obs_ledger.ANONYMOUS)
    header = request.headers.get("Authorization", "")
    token = header.removeprefix("Bearer ").strip()
    if token and any(secrets.compare_digest(token, k) for k in keys):
        return await _admit(obs_ledger.derive_tenant(token))
    return web.json_response(
        error_body("invalid or missing API key",
                   kind="authentication_error", code=401),
        status=401,
    )


@web.middleware
async def cors_middleware(request: web.Request, handler):
    state = request.app[STATE_KEY]
    if not state.config.cors:
        return await handler(request)
    if request.method == "OPTIONS":
        resp: web.StreamResponse = web.Response(status=204)
    else:
        resp = await handler(request)
    resp.headers["Access-Control-Allow-Origin"] = (
        state.config.cors_allow_origins or "*"
    )
    resp.headers["Access-Control-Allow-Methods"] = "GET, POST, DELETE, OPTIONS"
    resp.headers["Access-Control-Allow-Headers"] = "Authorization, Content-Type"
    return resp


async def welcome(request: web.Request) -> web.Response:
    state = request.app[STATE_KEY]
    if not state.config.disable_webui:
        from localai_tpu.api import ui

        # browsers get the UI home; API clients keep the JSON welcome
        if ui.wants_html(request):
            return await ui.home(request)
    return web.json_response({
        "message": "LocalAI-TPU",
        "models": state.loader.names(),
        "endpoints": sorted({
            r.resource.canonical
            for r in request.app.router.routes()
            if r.resource is not None
        }),
    })


def create_app(state: Optional[AppState] = None) -> web.Application:
    state = state or AppState()
    app = web.Application(middlewares=[
        trace_middleware, cors_middleware, error_middleware, auth_middleware,
        metrics_middleware,
    ], client_max_size=64 * 1024 * 1024)
    app[STATE_KEY] = state
    from localai_tpu.api import assistants as assistant_routes
    from localai_tpu.api import audio as audio_routes
    from localai_tpu.api import batches as batch_routes
    from localai_tpu.api import gallery as gallery_routes
    from localai_tpu.api import images as image_routes
    from localai_tpu.api import jina as jina_routes
    from localai_tpu.api import stores as stores_routes

    app.add_routes([web.get("/", welcome)])
    app.add_routes(openai_routes.routes())
    app.add_routes(localai_routes.routes())
    app.add_routes(gallery_routes.routes())
    app.add_routes(stores_routes.routes())
    app.add_routes(jina_routes.routes())
    app.add_routes(audio_routes.routes())
    app.add_routes(image_routes.routes())
    app.add_routes(assistant_routes.routes())
    app.add_routes(batch_routes.routes())
    if not state.config.disable_webui:
        from localai_tpu.api import ui as ui_routes

        app.add_routes(ui_routes.routes())
    from localai_tpu.api import debug as debug_routes
    from localai_tpu.api import openapi as openapi_routes
    from localai_tpu.api import traces as traces_routes

    app.add_routes(openapi_routes.routes())
    app.add_routes(traces_routes.routes())
    app.add_routes(debug_routes.routes())

    async def on_cleanup(_app):
        # shutdown joins engine threads and workers — seconds of wall
        # time; run it off-loop so in-flight connection teardown (and a
        # loopsan watching the dispatch) never sees the stall. Not on
        # state.executor: shutdown() tears that executor down.
        await asyncio.get_running_loop().run_in_executor(
            None, state.shutdown)

    app.on_cleanup.append(on_cleanup)
    return app


def serve(app_config: Optional[AppConfig] = None) -> None:
    """Blocking server entry (parity: appHTTP.Listen, run.go:199)."""
    cfg = app_config or AppConfig()
    if cfg.coordinator_address and cfg.num_processes > 1:
        # multi-host leader: join the jax.distributed group BEFORE any
        # jax use so jax.devices() spans every host (parallel/multihost)
        from localai_tpu.parallel.multihost import initialize

        initialize(cfg.coordinator_address, cfg.num_processes,
                   cfg.process_id)
    if cfg.mirror_port:
        # open the follower command channel NOW: followers connect at
        # boot, long before the first request lazily loads a model
        from localai_tpu.parallel.multihost import get_leader

        get_leader(cfg.mirror_port, cfg.mirror_followers,
                   token=cfg.peer_token)
    cfg.ensure_dirs()
    loader = ConfigLoader(cfg.model_path)
    loader.load_from_path(context_size=cfg.context_size)
    state = AppState(cfg, loader)
    # preload = make the model configured (embedded short names, gallery
    # refs — parity: pkgStartup.InstallModels, pkg/startup/model_preload.go)
    for name in cfg.preload_models:
        if loader.exists(name):
            continue
        try:
            from localai_tpu.gallery import install_model, resolve_ref

            m = resolve_ref(state.galleries, name)
            if m is None:
                log.warning("preload: unknown model ref %r", name)
                continue
            path = install_model(m, cfg.model_path,
                                 install_name="" if m.url else name)
            loader.load_single(path, context_size=cfg.context_size)
        except Exception as e:  # noqa: BLE001
            log.warning("preload of %s failed: %s", name, e)
    # load_to_memory = eager engine load (parity: LoadToMemory,
    # startup.go:148-176)
    for name in cfg.load_to_memory or cfg.preload_models:
        try:
            state.manager.get(name)
        except Exception as e:  # noqa: BLE001
            log.warning("eager load of %s failed: %s", name, e)
    if cfg.federated and cfg.federated_router:
        # join a federation: announce our address to the router (parity:
        # the p2p node advertising its service tunnel, federated_server.go)
        import socket

        from localai_tpu.federation import announce

        own = cfg.federated_advertise or (
            f"http://{socket.gethostname()}:{cfg.port}"
        )
        announce(cfg.federated_router, own, cfg.peer_token)
    log.info("serving on %s:%d (%d models configured)",
             cfg.address, cfg.port, len(loader.names()))
    web.run_app(create_app(state), host=cfg.address, port=cfg.port,
                print=None, access_log=None)
