"""OpenAI Assistants + Files APIs with JSON persistence.

Parity: /root/reference/core/http/endpoints/openai/assistant.go (assistant
CRUD + assistant-file attachments, persisted as ``assistants.json`` /
``assistantsFile.json`` in the configs dir) and files.go (multipart upload
into the upload dir). The reference keeps these in package-level globals;
here they live in an AssistantStore owned by AppState, with a lock and
atomic saves.

File persistence itself (``uploadedFiles.json`` + content under the
upload dir) moved to the unified :class:`localai_tpu.batch.store.
FileRegistry` — ``/v1/files`` is ONE registry with a ``purpose`` field
(``assistants`` | ``batch`` | ``batch_output``) shared by assistants
attachments, batch-job inputs, and batch result downloads. The
AssistantStore delegates to a shared instance."""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Optional

from aiohttp import web

from localai_tpu.api.schema import error_body
from localai_tpu.batch.store import (
    FileRegistry,
    _atomic_save,
    _id_num,
    _load,
)

log = logging.getLogger(__name__)

ASSISTANTS_FILE = "assistants.json"
ASSISTANT_FILES_FILE = "assistantsFile.json"

# request-shape limits (assistant.go:29-36)
MAX_INSTRUCTIONS = 32768
MAX_DESCRIPTION = 512
MAX_NAME = 256
MAX_TOOLS = 128
MAX_FILE_IDS = 20
TOOL_TYPES = {"code_interpreter", "retrieval", "function"}


class AssistantStore:
    """Assistants and assistant-file attachments, persisted as JSON and
    reloaded at construction (boot). Uploaded-file metadata lives in the
    shared :class:`FileRegistry` (``registry``)."""

    def __init__(self, configs_dir, upload_dir,
                 registry: Optional[FileRegistry] = None):
        from pathlib import Path

        self.configs_dir = Path(configs_dir)
        self.registry = registry or FileRegistry(upload_dir)
        self.upload_dir = self.registry.upload_dir
        self._lock = threading.Lock()
        self.assistants: list[dict] = self._load(
            self.configs_dir / ASSISTANTS_FILE
        )
        self.assistant_files: list[dict] = self._load(
            self.configs_dir / ASSISTANT_FILES_FILE
        )
        # id counter continues past the largest persisted id, so restarts
        # never mint colliding ids (the reference restarts from 0 and WOULD
        # collide — assistant.go:124; deliberate divergence). File ids are
        # minted by the registry.
        self._next_id = 1 + max(
            [_id_num(a["id"], "asst_") for a in self.assistants] + [0]
        )

    @property
    def files(self) -> list[dict]:
        """The unified registry's metadata list (read-side compat)."""
        return self.registry.files

    # JSON persistence shares the batch store's helpers (one copy of the
    # load / atomic tmp+rename save / id-suffix-parse logic)
    _load = staticmethod(_load)

    def _save(self, path, data: list[dict]) -> None:
        _atomic_save(path, data)

    def save_assistants(self) -> None:
        self._save(self.configs_dir / ASSISTANTS_FILE, self.assistants)

    def save_assistant_files(self) -> None:
        self._save(self.configs_dir / ASSISTANT_FILES_FILE,
                   self.assistant_files)

    def next_id(self) -> int:
        with self._lock:
            n = self._next_id
            self._next_id += 1
            return n

    # -- lookups -----------------------------------------------------------

    def assistant(self, aid: str) -> Optional[dict]:
        return next((a for a in self.assistants if a["id"] == aid), None)

    def file(self, fid: str) -> Optional[dict]:
        return self.registry.get(fid)


def _store(request: web.Request) -> AssistantStore:
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY].assistants


def _bad(msg: str) -> web.Response:
    return web.json_response(error_body(msg, code=400), status=400)


def _not_found(msg: str) -> web.Response:
    return web.json_response(error_body(msg, code=404), status=404)


def _validate_assistant_request(state, body: dict) -> Optional[str]:
    """Shape limits + model existence (assistant.go:84-99,418-447)."""
    if not isinstance(body, dict):
        return "body must be a JSON object"
    model = body.get("model", "")
    if not model:
        return "model is required"
    if model not in state.loader.names():
        return f"Model {model} not found"
    if len(body.get("name") or "") > MAX_NAME:
        return "name exceeds maximum length"
    if len(body.get("description") or "") > MAX_DESCRIPTION:
        return "description exceeds maximum length"
    if len(body.get("instructions") or "") > MAX_INSTRUCTIONS:
        return "instructions exceed maximum length"
    tools = body.get("tools") or []
    if len(tools) > MAX_TOOLS:
        return "too many tools"
    for t in tools:
        if not isinstance(t, dict) or t.get("type") not in TOOL_TYPES:
            return f"invalid tool: {t!r}"
    if len(body.get("file_ids") or []) > MAX_FILE_IDS:
        return "too many file_ids"
    return None


def _assistant_from_request(store: AssistantStore, body: dict) -> dict:
    return {
        "id": f"asst_{store.next_id()}",
        "object": "assistant",
        "created": int(time.time()),
        "model": body.get("model", ""),
        "name": body.get("name", ""),
        "description": body.get("description", ""),
        "instructions": body.get("instructions", ""),
        "tools": body.get("tools") or [],
        "file_ids": body.get("file_ids") or [],
        "metadata": body.get("metadata") or {},
    }


# ---------------------------------------------------------------------------
# /v1/assistants


async def create_assistant(request: web.Request) -> web.Response:
    from localai_tpu.api.server import STATE_KEY

    state = request.app[STATE_KEY]
    store = _store(request)
    try:
        body = await request.json()
    except Exception:
        return _bad("Cannot parse JSON")
    err = _validate_assistant_request(state, body)
    if err:
        return _bad(err)
    assistant = _assistant_from_request(store, body)
    with store._lock:
        store.assistants.append(assistant)
        store.save_assistants()
    return web.json_response(assistant)


async def list_assistants(request: web.Request) -> web.Response:
    store = _store(request)
    out = list(store.assistants)
    order = request.query.get("order", "desc")
    out.sort(key=lambda a: a.get("created", 0), reverse=(order != "asc"))
    # cursors accept either the bare number or the full 'asst_N' id the
    # API hands out (OpenAI clients paginate with the latter)
    after = request.query.get("after", "").removeprefix("asst_")
    before = request.query.get("before", "").removeprefix("asst_")
    if after.isdigit():
        out = [a for a in out if _id_num(a["id"], "asst_") > int(after)]
    if before.isdigit():
        out = [a for a in out if _id_num(a["id"], "asst_") < int(before)]
    try:
        limit = int(request.query.get("limit", "20"))
    except ValueError:
        return _bad("Invalid limit query value")
    return web.json_response(out[:limit])


async def get_assistant(request: web.Request) -> web.Response:
    a = _store(request).assistant(request.match_info["assistant_id"])
    if a is None:
        return _not_found("Unable to find assistant")
    return web.json_response(a)


async def modify_assistant(request: web.Request) -> web.Response:
    from localai_tpu.api.server import STATE_KEY

    state = request.app[STATE_KEY]
    store = _store(request)
    try:
        body = await request.json()
    except Exception:
        return _bad("Cannot parse JSON")
    err = _validate_assistant_request(state, body)
    if err:
        return _bad(err)
    aid = request.match_info["assistant_id"]
    # built before taking the lock: _assistant_from_request mints an id
    # under the same (non-reentrant) lock
    updated = _assistant_from_request(store, body)
    with store._lock:
        for i, a in enumerate(store.assistants):
            if a["id"] == aid:
                # modify keeps the identity, replaces the definition
                # (assistant.go:410-447)
                updated["id"] = aid
                updated["created"] = a.get("created", updated["created"])
                store.assistants[i] = updated
                store.save_assistants()
                return web.json_response(updated)
    return _not_found(f"Unable to find assistant with id: {aid}")


async def delete_assistant(request: web.Request) -> web.Response:
    store = _store(request)
    aid = request.match_info["assistant_id"]
    with store._lock:
        for i, a in enumerate(store.assistants):
            if a["id"] == aid:
                del store.assistants[i]
                store.assistant_files = [
                    af for af in store.assistant_files
                    if af["assistant_id"] != aid
                ]
                store.save_assistants()
                store.save_assistant_files()
                return web.json_response({
                    "id": aid, "object": "assistant.deleted",
                    "deleted": True,
                })
    return web.json_response(
        {"id": aid, "object": "assistant.deleted", "deleted": False},
        status=404,
    )


# ---------------------------------------------------------------------------
# /v1/assistants/{assistant_id}/files


async def create_assistant_file(request: web.Request) -> web.Response:
    store = _store(request)
    aid = request.match_info["assistant_id"]
    try:
        body = await request.json()
    except Exception:
        return _bad("Cannot parse JSON")
    fid = (body or {}).get("file_id", "")
    a = store.assistant(aid)
    if a is None:
        return _not_found(f"Unable to find assistant with id: {aid}")
    if store.file(fid) is None:
        return _not_found(f"Unable to find file_id with id: {fid}")
    af = {
        "id": fid,
        "object": "assistant.file",
        "created_at": int(time.time()),
        "assistant_id": aid,
    }
    with store._lock:
        if fid not in a["file_ids"]:
            a["file_ids"].append(fid)
        store.assistant_files.append(af)
        store.save_assistants()
        store.save_assistant_files()
    return web.json_response(af)


async def list_assistant_files(request: web.Request) -> web.Response:
    store = _store(request)
    aid = request.match_info["assistant_id"]
    if store.assistant(aid) is None:
        return _not_found(f"Unable to find assistant with id: {aid}")
    data = [af for af in store.assistant_files
            if af["assistant_id"] == aid]
    try:
        limit = int(request.query.get("limit", "20"))
    except ValueError:
        return _bad("Invalid limit query value")
    return web.json_response({
        "object": "list", "data": data[:limit],
    })


async def get_assistant_file(request: web.Request) -> web.Response:
    store = _store(request)
    aid = request.match_info["assistant_id"]
    fid = request.match_info["file_id"]
    for af in store.assistant_files:
        if af["assistant_id"] == aid and af["id"] == fid:
            return web.json_response(af)
    return _not_found(
        f"Unable to find assistant file with id {fid} on assistant {aid}"
    )


async def delete_assistant_file(request: web.Request) -> web.Response:
    store = _store(request)
    aid = request.match_info["assistant_id"]
    fid = request.match_info["file_id"]
    with store._lock:
        for i, af in enumerate(store.assistant_files):
            if af["assistant_id"] == aid and af["id"] == fid:
                del store.assistant_files[i]
                a = store.assistant(aid)
                if a and fid in a.get("file_ids", []):
                    a["file_ids"].remove(fid)
                    store.save_assistants()
                store.save_assistant_files()
                return web.json_response({
                    "id": fid, "object": "assistant.file.deleted",
                    "deleted": True,
                })
    return web.json_response(
        {"id": fid, "object": "assistant.file.deleted", "deleted": False},
        status=404,
    )


# ---------------------------------------------------------------------------
# /v1/files


def _registry(request: web.Request) -> FileRegistry:
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY].files


async def upload_file(request: web.Request) -> web.Response:
    from localai_tpu.api.server import STATE_KEY

    state = request.app[STATE_KEY]
    reader = await request.multipart()
    filename = None
    content = None
    purpose = ""
    async for part in reader:
        if part.name == "file":
            filename = part.filename or "upload"
            content = await part.read(decode=False)
        elif part.name == "purpose":
            purpose = (await part.text()).strip()
    if content is None:
        return _bad("file form field is required")
    if not purpose:
        return _bad("Purpose is not defined")
    limit = state.config.upload_limit_mb * 1024 * 1024
    if len(content) > limit:
        return _bad(
            f"File size {len(content)} exceeds upload limit {limit}"
        )
    try:
        f = _registry(request).register_bytes(filename, content, purpose)
    except ValueError as e:
        return _bad(str(e))
    return web.json_response(f)


async def list_files(request: web.Request) -> web.Response:
    data = _registry(request).list(request.query.get("purpose", ""))
    return web.json_response({"object": "list", "data": data})


def _file_or_404(request: web.Request) -> tuple[Optional[dict], Any]:
    fid = request.match_info["file_id"]
    f = _registry(request).get(fid)
    if f is None:
        return None, _not_found(f"unable to find file id {fid}")
    return f, None


async def get_file(request: web.Request) -> web.Response:
    f, err = _file_or_404(request)
    return err if f is None else web.json_response(f)


async def get_file_content(request: web.Request) -> web.Response:
    f, err = _file_or_404(request)
    if f is None:
        return err
    try:
        path = _registry(request).content_path(f["id"])
        # uploaded files can be MBs: read them executor-side, never on
        # the event loop
        loop = asyncio.get_running_loop()
        body = await loop.run_in_executor(None, path.read_bytes)
        return web.Response(body=body)
    except (OSError, ValueError) as e:
        return web.json_response(error_body(str(e), code=500), status=500)


async def delete_file(request: web.Request) -> web.Response:
    f, err = _file_or_404(request)
    if f is None:
        return err
    _registry(request).delete(f["id"])
    return web.json_response({
        "id": f["id"], "object": "file", "deleted": True,
    })


def routes() -> list[web.RouteDef]:
    """Route table (parity: routes/openai.go:25-56 incl. unversioned
    aliases)."""
    out = []
    for base in ("/v1", ""):
        out += [
            web.get(f"{base}/assistants", list_assistants),
            web.post(f"{base}/assistants", create_assistant),
            web.get(f"{base}/assistants/{{assistant_id}}", get_assistant),
            web.post(f"{base}/assistants/{{assistant_id}}",
                     modify_assistant),
            web.delete(f"{base}/assistants/{{assistant_id}}",
                       delete_assistant),
            web.get(f"{base}/assistants/{{assistant_id}}/files",
                    list_assistant_files),
            web.post(f"{base}/assistants/{{assistant_id}}/files",
                     create_assistant_file),
            web.get(
                f"{base}/assistants/{{assistant_id}}/files/{{file_id}}",
                get_assistant_file,
            ),
            web.delete(
                f"{base}/assistants/{{assistant_id}}/files/{{file_id}}",
                delete_assistant_file,
            ),
            web.get(f"{base}/files", list_files),
            web.post(f"{base}/files", upload_file),
            web.get(f"{base}/files/{{file_id}}", get_file),
            web.get(f"{base}/files/{{file_id}}/content", get_file_content),
            web.delete(f"{base}/files/{{file_id}}", delete_file),
        ]
    return out
