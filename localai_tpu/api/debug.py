"""Introspection endpoints: device health, program costs, thread stacks.

``GET /debug/devices`` — per-device liveness + memory: a timeout-guarded
jit probe (``?probe=0`` skips the device dispatch, ``?probe_timeout=S``
bounds it), ``memory_stats()`` where the backend has an allocator, a
live-array HBM census attributed to KV cache vs weights vs other, and the
stall watchdog's channel table. The "is my TPU actually alive and what is
eating its HBM" view.

``GET /debug/programs`` — the compiled-program cost catalog: per watched
jit entry, XLA ``cost_analysis``/``memory_analysis`` (FLOPs, bytes
accessed, temp/output sizes) joined with the scheduler's measured
per-dispatch latency into achieved GFLOP/s, GB/s, and fractions of the
device roofline — the direct answer to "where does the decode bandwidth
go". The first call lazily re-lowers each program from its recorded
abstract signature (``?harvest=0`` lists without compiling).

``GET /debug/stacks`` — every live thread's stack, on demand (the same
payload the watchdog dumps on a stall, for when an operator wants it
BEFORE the deadline).

``GET /debug/flight`` — the engine flight recorder: per-model rings of
per-dispatch records (step times, occupancy, queue depth, KV utilization,
tokens, preemptions, speculative acceptance) with windowed step-time
percentiles. ``?since=<monotonic ts>`` returns only records newer than
the given timestamp (pollers pass the ``ts`` of the last record they
saw); ``?limit=N`` bounds the newest records returned. The "what was the
engine doing for the last N seconds" view — reading it never touches a
device.

``GET /debug/anatomy`` — the dispatch-anatomy breakdown (obs.anatomy):
per-model windowed gap/sched/launch/sync phase percentiles and totals
from the flight ring's phase columns, the derived
``host_overhead_fraction`` / ``device_bubble_fraction``, per-phase wall
shares (stacked-bar ready), and the unattributed remainder.
``?window=S`` sets the window (default 60 s; ``window=0`` reads the
whole ring). The "where did the dispatch time go" view — host-side
reads only, zero device syncs.

``GET /debug/fleet/flight`` — the fleet-wide flight view: every replica's
ring harvested over GetTelemetry (off the event loop, fleet RPC deadline)
and merged into one table with a ``replica`` column plus per-replica
step-time percentiles (obs.fleetview). A wedged replica degrades to an
``unreachable`` pane — the endpoint itself always answers.

``GET /debug/profiles`` — the anomaly-capture manifest (obs.profiler):
every auto-captured jax.profiler trace with its trigger (stall /
slo_shed / step_p99_regression), triggering trace id, reason, and
artifact path, plus the manager's rate-limit state (cooldown, per-hour
budget, skip counts).

``GET /debug/history`` / ``/debug/history/{series}`` — the persistent
multi-resolution metrics history (obs.history): 1 s / 10 s / 5 m rings of
every engine and usage series, queryable per resolution with ``?res=``
and ``?since=``. The "what did occupancy look like an hour ago" view —
survives restarts via the snapshot dir (``LOCALAI_HISTORY_DIR``).

``GET /debug/kv`` — per-model paged block-pool audit: allocator stats,
live tables, and the result of ``BlockAllocator.check_invariants()``
(block conservation + refcount sanity). Any violation is a leak.

``/debug/faults`` — the fault-injection registry (localai_tpu.faults):
``GET`` lists armed specs with hit/fire counts plus the self-healing
supervisor state per model; ``POST {"site", "mode", "after", "times",
"match", "delay_s"}`` arms one; ``DELETE`` (``?site=`` to scope) clears.
Chaos tooling only — nothing is armed (and the hot path pays one boolean
read) unless an operator or ``LOCALAI_FAULT_*`` arms it.
"""

from __future__ import annotations

import asyncio
import time

from aiohttp import web

from localai_tpu import faults
from localai_tpu.obs import compile as obs_compile
from localai_tpu.obs import device as obs_device
from localai_tpu.obs import watchdog as obs_watchdog


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


def _runners(state) -> list:
    out = []
    for sm in state.manager.loaded_snapshot().values():
        runner = getattr(sm, "runner", None)
        if runner is not None:
            out.append(runner)
    return out


async def devices(request: web.Request) -> web.Response:
    state = _state(request)
    want_probe = request.query.get("probe", "1") != "0"
    try:
        probe_timeout = float(request.query.get("probe_timeout", 5.0))
    except ValueError:
        raise web.HTTPBadRequest(text="probe_timeout must be a number")
    if not probe_timeout > 0:  # rejects 0, negatives, and NaN
        raise web.HTTPBadRequest(text="probe_timeout must be positive")
    # hard cap: the probe join blocks one shared api-wait executor thread;
    # an unbounded (or inf) timeout against a wedged device would let a
    # key holder pin the pool one request at a time
    probe_timeout = min(probe_timeout, 120.0)
    loop = asyncio.get_running_loop()

    def build() -> dict:
        runners = _runners(state)
        report: dict = {
            "devices": obs_device.device_memory(),
            "census": obs_device.hbm_census(
                obs_device.known_arrays(runners)),
            "watchdog": obs_watchdog.WATCHDOG.status(),
            "roofline": obs_device.roofline(),
        }
        if want_probe:
            # the probe itself is timeout-guarded; a wedged device costs
            # this handler probe_timeout seconds, not forever
            report["probe"] = obs_device.probe_device(
                timeout=probe_timeout).to_dict()
        return report

    return web.json_response(
        await loop.run_in_executor(state.executor, build))


async def programs(request: web.Request) -> web.Response:
    state = _state(request)
    harvest = request.query.get("harvest", "1") != "0"
    loop = asyncio.get_running_loop()

    def build() -> dict:
        # feed the catalog the live schedulers' measured step EMAs so a
        # report right after boot still joins a latency (the drain-time
        # note_latency feed is authoritative once traffic flows)
        for sm in state.manager.loaded_snapshot().values():
            sched = getattr(sm, "scheduler", None)
            ema = getattr(sched, "_step_ema", None)
            steps = getattr(sched, "last_dispatch_steps", 0)
            if ema and steps:
                prog = "decode" if steps == 1 else "decode_n"
                obs_compile.note_latency(prog, ema * steps, steps=steps)
        rl = obs_device.roofline()
        return {
            "roofline": rl,
            "programs": obs_compile.CATALOG.report(
                roofline=rl, harvest=harvest),
        }

    return web.json_response(
        await loop.run_in_executor(state.executor, build))


async def stacks(request: web.Request) -> web.Response:
    return web.json_response({"threads": obs_watchdog.dump_stacks()})


async def flight(request: web.Request) -> web.Response:
    state = _state(request)
    try:
        since = float(request.query.get("since", 0.0))
    except ValueError:
        raise web.HTTPBadRequest(
            text="since must be a number (a record's monotonic ts)")
    try:
        limit = int(request.query.get("limit", 256))
    except ValueError:
        raise web.HTTPBadRequest(text="limit must be an integer")
    limit = max(1, min(limit, 4096))
    models = {}
    for name, sm in state.manager.loaded_snapshot().items():
        rec = getattr(getattr(sm, "scheduler", None), "flight", None)
        if rec is None:
            continue  # worker-backed / non-LLM serving models have no ring
        models[name] = {
            "records": rec.snapshot(since=since, limit=limit),
            "percentiles": rec.percentiles(),
            "dispatches": rec.count,
            "tokens_total": rec.total_tokens,
            "capacity": rec.capacity,
        }
    return web.json_response({
        # the clock records are stamped with, so pollers can window
        "now_monotonic": round(time.monotonic(), 6),
        "models": models,
    })


async def anatomy(request: web.Request) -> web.Response:
    from localai_tpu.obs import anatomy as obs_anatomy

    state = _state(request)
    try:
        window = float(request.query.get(
            "window", obs_anatomy.DEFAULT_WINDOW_S))
    except ValueError:
        raise web.HTTPBadRequest(text="window must be a number (seconds)")
    window_s = window if window > 0 else None  # 0 = whole ring
    models = {}
    for name, sm in state.manager.loaded_snapshot().items():
        rec = getattr(getattr(sm, "scheduler", None), "flight", None)
        if rec is None:
            continue  # worker-backed / non-LLM serving models have no ring
        models[name] = obs_anatomy.breakdown(rec, window_s=window_s)
    return web.json_response({
        "now_monotonic": round(time.monotonic(), 6),
        "phases": list(obs_anatomy.PHASES),
        "models": models,
    })


async def fleet_flight(request: web.Request) -> web.Response:
    from localai_tpu.obs import fleetview

    state = _state(request)
    try:
        since = float(request.query.get("since", 0.0))
    except ValueError:
        raise web.HTTPBadRequest(
            text="since must be a number (a record's monotonic ts)")
    try:
        limit = int(request.query.get("limit", 256))
    except ValueError:
        raise web.HTTPBadRequest(text="limit must be an integer")
    limit = max(1, min(limit, 4096))
    loop = asyncio.get_running_loop()

    def build() -> dict:
        # one bounded GetTelemetry per replica, NEVER on the event loop:
        # a wedged replica costs its pane one fleet RPC deadline, not the
        # endpoint
        models = {}
        for name, sm in state.manager.loaded_snapshot().items():
            if getattr(sm, "pool", None) is None:
                continue
            models[name] = fleetview.fleet_flight(
                sm, since=since, limit=limit)
        return models

    return web.json_response({
        "now_monotonic": round(time.monotonic(), 6),
        "models": await loop.run_in_executor(state.executor, build),
    })


async def profiles(request: web.Request) -> web.Response:
    from localai_tpu.obs.profiler import PROFILER

    return web.json_response(PROFILER.report())


async def history_index(request: web.Request) -> web.Response:
    """GET /debug/history — the multi-resolution metrics history
    (obs.history): every recorded series name plus the ring geometry, so
    a dashboard can enumerate before querying."""
    from localai_tpu.obs import history as obs_history

    return web.json_response({
        "series": obs_history.HISTORY.series_names(),
        "resolutions_s": list(obs_history.RESOLUTIONS),
        "capacity": {str(r): c
                     for r, c in obs_history.CAPACITY.items()},
    })


async def history_series(request: web.Request) -> web.Response:
    """GET /debug/history/{series}?res=<1|10|300>&since=<unix ts> — one
    series' ring at one resolution. Counters return the bucket max
    (monotone totals), gauges the bucket mean. Pure in-memory ring reads
    — no device work, no locks held across the render."""
    from localai_tpu.obs import history as obs_history

    name = request.match_info["series"]
    try:
        res = int(request.query.get("res", 10))
    except ValueError:
        raise web.HTTPBadRequest(text="res must be an integer (seconds)")
    try:
        since = float(request.query.get("since", 0.0))
    except ValueError:
        raise web.HTTPBadRequest(text="since must be a unix timestamp")
    out = obs_history.HISTORY.query(name, res=res, since=since)
    if out is None:
        raise web.HTTPNotFound(text=f"unknown series {name!r}")
    return web.json_response(out)


async def kv(request: web.Request) -> web.Response:
    state = _state(request)
    loop = asyncio.get_running_loop()

    def build() -> dict:
        # allocator walks + invariant checks scale with table count:
        # executor-side, like every other debug-pane builder here
        models = {}
        for name, sm in state.manager.loaded_snapshot().items():
            sched = getattr(sm, "scheduler", None)
            alloc = getattr(getattr(sm, "runner", None), "allocator", None)
            if alloc is None:
                # fleet facades have no local allocator, but their KV
                # economy plane (prefix directory + sibling/migration
                # counters) is this endpoint's business too
                directory = getattr(sched, "directory", None)
                if directory is not None:
                    models[name] = {
                        "directory": directory.stats(),
                        "sibling_transfers": sched.sibling_transfers,
                        "sibling_fallbacks": sched.sibling_fallbacks,
                        "migrations": sched.migrations,
                        "migration_fallbacks": sched.migration_fallbacks,
                    }
                    # host-tier roll-up across replicas rides the same
                    # metrics pane the /metrics scrape reads
                    m = sched.metrics()
                    if "kv_tier_spills" in m:
                        models[name]["tier"] = {
                            "blocks": m.get("kv_tier_blocks", 0),
                            "bytes": m.get("kv_tier_bytes", 0),
                            "spills_total": m.get("kv_tier_spills", 0),
                            "reloads_total": m.get("kv_tier_reloads", 0),
                        }
                continue  # contiguous / worker-backed / non-LLM engines
            models[name] = {
                "block_tokens": alloc.block_tokens,
                "blocks": {},
                "tables": {str(s): n
                           for s, n in alloc.tables_snapshot().items()},
                "shared_tokens_total": alloc.shared_tokens_total,
                "evictions_total": alloc.evictions_total,
                "invariant_violations": alloc.check_invariants(),
                "violations_seen": getattr(
                    sched, "kv_invariant_violations", 0),
            }
            st = alloc.stats()
            models[name]["blocks"] = {
                "total": st.total, "free": st.free, "used": st.used,
                "cached": st.cached, "watermark": st.high_watermark,
            }
            ts = alloc.tier_stats()
            if ts is not None:
                models[name]["tier"] = ts
        return models

    return web.json_response(
        {"models": await loop.run_in_executor(state.executor, build)})


async def faults_get(request: web.Request) -> web.Response:
    state = _state(request)
    supervisors = {}
    for name, sm in state.manager.loaded_snapshot().items():
        sup = getattr(getattr(sm, "scheduler", None), "supervisor", None)
        if sup is not None:
            supervisors[name] = sup.status()
    return web.json_response({
        "active": faults.active(),
        "sites": faults.SITES,
        "armed": faults.snapshot(),
        "supervisors": supervisors,
    })


async def faults_post(request: web.Request) -> web.Response:
    try:
        body = await request.json()
    except Exception:  # noqa: BLE001 — malformed body is a client error
        raise web.HTTPBadRequest(text="body must be a JSON object")
    if not isinstance(body, dict) or not body.get("site"):
        raise web.HTTPBadRequest(text='need {"site": ..., ...}')
    allowed = {"site", "mode", "after", "times", "match", "delay_s"}
    unknown = set(body) - allowed
    if unknown:
        raise web.HTTPBadRequest(text=f"unknown fields {sorted(unknown)}")
    try:
        spec = faults.arm(faults.FaultSpec(**body))
    except (TypeError, ValueError) as e:
        raise web.HTTPBadRequest(text=str(e))
    return web.json_response({"armed": spec.to_dict()})


async def faults_delete(request: web.Request) -> web.Response:
    site = request.query.get("site") or None
    return web.json_response({"cleared": faults.clear(site)})


def routes() -> list[web.RouteDef]:
    return [
        web.get("/debug/devices", devices),
        web.get("/debug/programs", programs),
        web.get("/debug/stacks", stacks),
        web.get("/debug/flight", flight),
        web.get("/debug/anatomy", anatomy),
        web.get("/debug/fleet/flight", fleet_flight),
        web.get("/debug/profiles", profiles),
        web.get("/debug/history", history_index),
        web.get("/debug/history/{series}", history_series),
        web.get("/debug/kv", kv),
        web.get("/debug/faults", faults_get),
        web.post("/debug/faults", faults_post),
        web.delete("/debug/faults", faults_delete),
    ]
