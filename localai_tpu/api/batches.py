"""OpenAI Batch API endpoints over the offline batch subsystem.

``POST /v1/batches`` creates a job from an uploaded JSONL file
(``/v1/files`` with ``purpose="batch"`` — the files routes live in
``api/assistants.py`` over the unified FileRegistry), ``GET
/v1/batches`` / ``GET /v1/batches/{id}`` read job state incl. progress
counts, and ``POST /v1/batches/{id}/cancel`` stops a job (in-flight
lines are abandoned; durable results are kept). Completed jobs carry
``output_file_id``/``error_file_id`` downloadable at
``GET /v1/files/{id}/content``.

Execution happens in the background :class:`~localai_tpu.batch.
executor.BatchExecutor` at the scheduler's batch priority — creating a
job costs the serving path nothing.
"""

from __future__ import annotations

import logging

from aiohttp import web

from localai_tpu.api.schema import error_body
from localai_tpu.batch.executor import SUPPORTED_URLS

log = logging.getLogger(__name__)


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


def _bad(msg: str) -> web.Response:
    return web.json_response(error_body(msg, code=400), status=400)


def _not_found(msg: str) -> web.Response:
    return web.json_response(error_body(msg, code=404), status=404)


async def create_batch(request: web.Request) -> web.Response:
    state = _state(request)
    try:
        body = await request.json()
    except Exception:
        return _bad("Cannot parse JSON")
    if not isinstance(body, dict):
        return _bad("body must be a JSON object")
    endpoint = body.get("endpoint") or ""
    if endpoint not in SUPPORTED_URLS:
        return _bad(f"endpoint must be one of {list(SUPPORTED_URLS)}")
    fid = body.get("input_file_id") or ""
    f = state.files.get(fid)
    if f is None:
        return _not_found(f"input file {fid!r} not found")
    if f.get("purpose") != "batch":
        return _bad(
            f"input file {fid!r} has purpose {f.get('purpose')!r}; "
            "upload it with purpose=batch")
    metadata = body.get("metadata")
    if metadata is not None and not isinstance(metadata, dict):
        return _bad("metadata must be an object")
    job = state.batches.create(
        endpoint=endpoint,
        input_file_id=fid,
        completion_window=str(body.get("completion_window") or "24h"),
        metadata=metadata,
    )
    state.batches.export_gauges()
    svc = state.batch_service  # lazily starts the executor thread
    svc.wake()
    return web.json_response(job)


async def list_batches(request: web.Request) -> web.Response:
    jobs = _state(request).batches.list()
    jobs.sort(key=lambda j: j.get("created_at", 0), reverse=True)
    try:
        limit = int(request.query.get("limit", "20"))
    except ValueError:
        return _bad("Invalid limit query value")
    if limit < 1:
        return _bad("limit must be >= 1")
    return web.json_response({"object": "list", "data": jobs[:limit]})


async def get_batch(request: web.Request) -> web.Response:
    job = _state(request).batches.get(request.match_info["batch_id"])
    if job is None:
        return _not_found("Unable to find batch")
    return web.json_response(job)


async def cancel_batch(request: web.Request) -> web.Response:
    state = _state(request)
    job = state.batches.cancel(request.match_info["batch_id"])
    if job is None:
        return _not_found("Unable to find batch")
    state.batches.export_gauges()
    return web.json_response(job)


def routes() -> list[web.RouteDef]:
    # /v1 only (no unversioned aliases): the bare GET /batches path is the
    # web UI's job panel, and the Batch API has no pre-/v1 legacy clients
    return [
        web.post("/v1/batches", create_batch),
        web.get("/v1/batches", list_batches),
        web.get("/v1/batches/{batch_id}", get_batch),
        web.post("/v1/batches/{batch_id}/cancel", cancel_batch),
    ]
