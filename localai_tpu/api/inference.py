"""Request → engine orchestration shared by the OpenAI endpoints.

Parity targets:
  * mergeRequestWithConfig — request overrides per-model YAML defaults
    (/root/reference/core/http/endpoints/openai/request.go:298,51)
  * ComputeChoices — n-choice fan-out (inference.go:11)
  * ModelInference + Finetune post-processing — echo / cutstrings /
    extract_regex / trimspace / trimsuffix (core/backend/llm.go:34-216)
  * tool-grammar wiring (chat.go:268-271) via localai_tpu.functions.
"""

from __future__ import annotations

import dataclasses
import logging
import re
from typing import Any, Optional

from localai_tpu.api.schema import OpenAIRequest
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenHandle, GenRequest
from localai_tpu.models.manager import ServingModel
from localai_tpu.obs import ledger as _obs_ledger

log = logging.getLogger(__name__)


def merge_request(mcfg: ModelConfig, req: OpenAIRequest) -> ModelConfig:
    """Effective config: per-model YAML defaults overridden by request
    fields that were explicitly provided."""
    cfg = mcfg.model_copy(deep=True)
    p = cfg.parameters
    for field in ("temperature", "top_p", "top_k", "min_p", "max_tokens",
                  "seed", "presence_penalty", "frequency_penalty",
                  "repeat_penalty"):
        val = getattr(req, field)
        if val is not None:
            setattr(p, field, val)
    return cfg


@dataclasses.dataclass
class MMContent:
    """Encoded multimodal conditioning: per-item patch embeddings plus the
    row spans videos occupy (a video = several sampled frames)."""

    embeds: Any                                  # [n_rows, n_patches, D]
    video_groups: list[tuple[int, int]]          # [vid-N] → (start, count)


def prepare_multimodal(
    sm: ServingModel, cfg: ModelConfig, req: OpenAIRequest
) -> tuple[list[dict], Optional[MMContent]]:
    """Multipart message content → text with [img-N]/[vid-N] placeholders
    (global running IDs) + encoded image/video-frame embeddings.

    Parity: the reference's per-message image collection + multimodal
    templating (/root/reference/core/http/endpoints/openai/chat.go:296-441,
    pkg/templates/multimodal.go) and the vLLM backend's image+video
    multimodal path (backend/python/vllm/backend.py); the CLIP encode
    happens here instead of inside the worker (grpc-server.cpp:1397-1424).
    Videos decode to uniformly-sampled frames (utils.media), each encoded
    like an image and injected as consecutive patch blocks.
    Returns (message dicts for templating, MMContent or None when the
    request has no media or the model has no vision tower).
    """
    from localai_tpu.templates.chat import multimodal_placeholders

    messages: list[dict] = []
    refs: list[str] = []
    vid_refs: list[str] = []
    for m in req.messages:
        d = m.model_dump(exclude_none=True)
        imgs = m.media_parts("image")
        vids = m.media_parts("video")
        if imgs or vids:
            d["content"] = multimodal_placeholders(
                cfg.template.multimodal or "",
                m.text_content(),
                n_images=len(imgs),
                n_video=len(vids),
                first_image_id=len(refs),
                first_video_id=len(vid_refs),
            )
            refs.extend(imgs)
            vid_refs.extend(vids)
        messages.append(d)
    if not refs and not vid_refs:
        return messages, None
    if sm.vision is None:
        log.warning(
            "model %s received %d image(s)/%d video(s) but has no vision "
            "tower (set mmproj or use a llava checkpoint); serving "
            "text-only", sm.name, len(refs), len(vid_refs),
        )
        return messages, None
    from concurrent.futures import ThreadPoolExecutor

    from localai_tpu.utils.media import fetch_image, fetch_video_frames

    # fetch concurrently: latency bounds to the slowest single item, not
    # the sum over refs (remote URLs each carry a 30s timeout)
    with ThreadPoolExecutor(max_workers=min(8, len(refs) + len(vid_refs))) \
            as pool:
        img_it = pool.map(fetch_image, refs)
        vid_it = pool.map(fetch_video_frames, vid_refs)
        images = list(img_it)
        frame_lists = list(vid_it)
    video_groups: list[tuple[int, int]] = []
    start = len(images)
    frames: list = []
    for fl in frame_lists:
        video_groups.append((start, len(fl)))
        frames.extend(fl)
        start += len(fl)
    return messages, MMContent(
        embeds=sm.vision.encode(images + frames),
        video_groups=video_groups,
    )


def expand_image_placeholders(
    sm: ServingModel, prompt: str, mm: Any
) -> tuple[list[int], Optional[Any], Optional[Any]]:
    """Tokenize a prompt with [img-N]/[vid-N] placeholders: each image
    placeholder becomes n_patches image-token ids (a video: n_frames x
    n_patches), and the matching embedding rows + positions are returned
    for scatter-injection at prefill (ModelRunner._prefill_mm).

    The TPU-shaped version of llama.cpp's interleaved text/image batch
    build (grpc-server.cpp:1397-1424): one token stream, one scatter."""
    import numpy as np

    if isinstance(mm, MMContent):
        embeds, video_groups = mm.embeds, mm.video_groups
    else:  # raw [n, patches, D] array (image-only callers/tests)
        embeds, video_groups = mm, []
    n_images = embeds.shape[0] - sum(c for _, c in video_groups)

    segs = re.split(r"\[(img|vid)-(\d+)\]", prompt)
    tokens = sm.tokenizer.encode(segs[0], add_bos=True)
    rows, poss = [], []
    n_patches = embeds.shape[1]

    def inject(row_start: int, count: int):
        start = len(tokens)
        tokens.extend([sm.image_token_id] * (n_patches * count))
        poss.extend(range(start, start + n_patches * count))
        rows.append(embeds[row_start: row_start + count].reshape(
            count * n_patches, -1))

    for i in range(1, len(segs), 3):
        kind, idx = segs[i], int(segs[i + 1])
        if kind == "img" and 0 <= idx < n_images:
            inject(idx, 1)
        elif kind == "vid" and 0 <= idx < len(video_groups):
            inject(*video_groups[idx])
        tail = segs[i + 2]
        if tail:
            tokens.extend(sm.tokenizer.encode(tail, add_bos=False))
    injected = sum(r.shape[0] // n_patches for r in rows)
    if injected < embeds.shape[0]:
        # a custom template.multimodal without the media loops eats the
        # placeholders — surface it instead of silently serving text-only
        log.warning(
            "%d of %d encoded media item(s) had no [img-N]/[vid-N] "
            "placeholder in the rendered prompt (check template.multimodal)",
            embeds.shape[0] - injected, embeds.shape[0],
        )
    if not rows:
        return tokens, None, None
    return tokens, np.concatenate(rows, 0), np.asarray(poss, np.int32)


def request_deadline_s(cfg: Any = None) -> float:
    """The per-request generation deadline in seconds: AppConfig's
    ``request_deadline_s`` when a config is at hand, else the
    ``LOCALAI_REQUEST_DEADLINE_S`` environment override, else 600.
    Deadline expiry CANCELS the generation (the decode slot frees instead
    of generating into the void — see :func:`run_choices` and the API
    tier's ``_await_handles``)."""
    import os

    v = getattr(cfg, "request_deadline_s", None) if cfg is not None else None
    if v is None:
        try:
            v = float(os.environ.get("LOCALAI_REQUEST_DEADLINE_S", ""))
        except ValueError:
            v = None
    return float(v) if v and v > 0 else 600.0


def shed_check(model: str, scheduler: Any = None) -> None:
    """SLO burn-rate admission control (obs.slo): when the observatory
    says this model is out of its error budget on BOTH the fast and slow
    windows, refuse new generation work with 429 + ``Retry-After`` rather
    than queueing it into a latency spiral. Recovery is automatic — shed
    requests never become SLO events, so the fast window drains and the
    next check admits again. No-op with no targets configured."""
    from aiohttp import web

    from localai_tpu.obs import slo as obs_slo

    if not obs_slo.SLO.should_shed(model):
        return
    retry = obs_slo.SLO.shed(model)
    if scheduler is not None:
        scheduler.note_shed()
    # waste decomposition (obs.ledger): a shed admission is one whole
    # refused request — attributed to the caller's tenant bucket here,
    # the only tier that ever sees it
    _obs_ledger.LEDGER.note_waste(
        "shed", model=model, tenant=_obs_ledger.current_tenant(),
        requests=1)
    raise web.HTTPTooManyRequests(
        text=f"model {model!r} is shedding load (SLO burn rate over "
             f"threshold); retry after {retry}s",
        headers={"Retry-After": str(retry)},
    )


def correlation_id(request: Any) -> str:
    """X-Correlation-ID request header, for tracing a request through the
    scheduler/worker tier (parity: chat.go:164-169 — header, else the
    generated request id; callers fall back to their rid)."""
    try:
        return request.headers.get("X-Correlation-ID", "")
    except AttributeError:
        return ""


def trace_id(request: Any) -> str:
    """The trace id the obs middleware stamped on this HTTP request — the
    engine's lifecycle spans group under the same id, so
    /debug/timeline/{id} shows the HTTP span and every generation it
    spawned together. Empty when the middleware isn't installed (direct
    handler tests)."""
    try:
        return request.get("trace_id", "")
    except (AttributeError, TypeError):
        return ""


def build_gen_request(
    sm: ServingModel,
    cfg: ModelConfig,
    req: OpenAIRequest,
    prompt: str,
    *,
    constraint: Any = None,
    seed_offset: int = 0,
    mm_embeds: Any = None,
    correlation_id: str = "",
    trace_id: str = "",
    priority: int = 0,
    tenant: str = "",
) -> GenRequest:
    p = cfg.parameters
    mm_flat = mm_pos = None
    if mm_embeds is not None:
        tokens, mm_flat, mm_pos = expand_image_placeholders(
            sm, prompt, mm_embeds
        )
    else:
        tokens = sm.tokenizer.encode(prompt, add_bos=True)
    logit_bias = None
    if req.logit_bias:
        logit_bias = {}
        for k, v in req.logit_bias.items():
            try:
                logit_bias[int(k)] = float(v)
            except (TypeError, ValueError):
                continue
    seed = p.seed
    if seed is not None and seed_offset:
        seed = seed + seed_offset
    return GenRequest(
        prompt=tokens,
        max_new_tokens=p.max_tokens or 2048,
        temperature=p.temperature,
        top_k=p.top_k,
        top_p=p.top_p,
        min_p=p.min_p,
        repeat_penalty=p.repeat_penalty,
        presence_penalty=p.presence_penalty,
        frequency_penalty=p.frequency_penalty,
        seed=seed,
        logit_bias=logit_bias,
        stop=tuple(cfg.stopwords) + tuple(req.stop_list()),
        ignore_eos=req.ignore_eos,
        constraint=constraint,
        correlation_id=correlation_id or req.user or "",
        trace_id=trace_id or correlation_id,
        # usage accounting: the auth middleware's contextvar reaches here
        # even through executor threads (api.server.ContextExecutor), so
        # every HTTP-born request carries its tenant bucket without each
        # endpoint threading it explicitly
        tenant=tenant or _obs_ledger.current_tenant(),
        stream=bool(req.stream),
        mm_embeds=mm_flat,
        mm_positions=mm_pos,
        priority=priority,
    )


def finetune_result(cfg: ModelConfig, prompt: str, text: str,
                    *, echo: bool = False) -> str:
    """Post-inference text shaping (parity: Finetune, llm.go:168-216)."""
    if echo:
        text = prompt + text
    for c in cfg.cutstrings:
        text = re.sub(c, "", text)
    for ex in cfg.extract_regex:
        m = re.search(ex, text)
        if m:
            text = m.group(1) if m.groups() else m.group(0)
            break
    for t in cfg.trimspace:
        text = text.strip()
        break
    for suf in cfg.trimsuffix:
        text = text.removesuffix(suf)
    return text


@dataclasses.dataclass
class ToolContext:
    """What the chat endpoint needs to post-process a tools response."""

    functions: list[dict]
    config_fn: Any  # FunctionsConfig
    no_action_name: str
    constraint: Any = None


class ToolGrammarError(ValueError):
    """tool_choice='required' whose grammar can't be built — a client
    error (the endpoint maps it to 400)."""


def prepare_tools(
    sm: ServingModel, cfg: ModelConfig, req: OpenAIRequest
) -> Optional[ToolContext]:
    """Normalize tools, apply tool_choice, build the FSM constraint.
    Returns None when the request carries no usable tools or disables them
    (parity: chat.go tool gating + grammar build, chat.go:222-280)."""
    if req.tools_disabled():
        return None
    functions = req.tool_definitions()
    if not functions:
        return None
    from localai_tpu import functions as fx

    fn_cfg = cfg.function
    if req.tool_choice == "required" or req.function_call == "required":
        # OpenAI semantics: the model MUST call some tool — skip the
        # no-action escape hatch so the grammar only admits real calls
        funcs = list(functions)
    else:
        funcs = fx.inject_no_action(functions, fn_cfg)
    choice = req.tool_choice_name()
    if choice:
        funcs = fx.select_function(funcs, choice)
    required = (req.tool_choice == "required"
                or req.function_call == "required")
    constraint = None
    try:
        constraint, _built = fx.build_tool_constraint(
            funcs, fn_cfg, sm.tokenizer
        )
    except Exception as e:  # noqa: BLE001 — bad schema ≠ failed request...
        if required:
            # ...EXCEPT under tool_choice="required": without the grammar
            # the "must call a tool" contract can't be honored — reject
            # rather than silently return prose
            raise ToolGrammarError(
                f"tool_choice='required' but the tool grammar could not "
                f"be built: {e}") from e
        log.warning("tool grammar build failed (%s); decoding unconstrained", e)
    return ToolContext(
        functions=funcs,
        config_fn=fn_cfg,
        no_action_name=fn_cfg.no_action_function_name or "answer",
        constraint=constraint,
    )


def response_format_constraint(
    sm: ServingModel, req: OpenAIRequest
) -> Optional[Any]:
    """response_format json_object/json_schema → decoding constraint
    (parity: chat.go JSON-mode via JSONBNF; json_schema is the modern
    OpenAI structured-output shape)."""
    rf = req.response_format
    if rf is None:
        return None
    if isinstance(rf, str):
        kind = rf
        payload: dict[str, Any] = {}
    else:
        kind = str(rf.get("type", ""))
        payload = rf
    from localai_tpu import functions as fx

    if kind == "json_object":
        return fx.constraint_for_regex(fx.JSON_OBJECT_REGEX, sm.tokenizer)
    if kind == "json_schema":
        schema = (payload.get("json_schema") or {}).get("schema")
        if schema:
            return fx.constraint_for_schema(schema, sm.tokenizer)
    return None


def parse_tool_calls(text: str, tctx: ToolContext) -> tuple[str, list[dict]]:
    """LLM output → (content, OpenAI tool_calls). The no-action function's
    message becomes plain content (parity: chat.go:107-154 + parse.go)."""
    from localai_tpu import functions as fx
    from localai_tpu.api.schema import new_id

    cleaned = fx.cleanup_llm_result(text, tctx.config_fn)
    calls = fx.parse_function_call(cleaned, tctx.config_fn)
    content = ""
    tool_calls: list[dict] = []
    for call in calls:
        if call.name == tctx.no_action_name:
            import json as _json

            try:
                args = _json.loads(call.arguments or "{}")
                content = str(args.get("message", "")) or cleaned
            except Exception:  # noqa: BLE001
                content = cleaned
            continue
        tool_calls.append({
            "id": new_id("call"),
            "index": len(tool_calls),
            "type": "function",
            "function": {"name": call.name, "arguments": call.arguments},
        })
    if not calls:
        content = fx.parse_text_content(cleaned, tctx.config_fn) or cleaned
    elif not content and not tool_calls:
        content = cleaned
    return content, tool_calls


def run_choices(
    sm: ServingModel,
    cfg: ModelConfig,
    req: OpenAIRequest,
    prompt: str,
    *,
    constraint_factory=None,
    timeout: Optional[float] = None,
) -> list[GenHandle]:
    """Submit n parallel generations and wait (parity: ComputeChoices loop,
    inference.go:11 — but concurrent via the continuous-batching engine
    rather than sequential).

    ``timeout=None`` resolves the deadline from the environment/default
    only (:func:`request_deadline_s` with no config — this helper has no
    AppConfig at hand); callers holding an AppConfig should pass
    ``timeout=request_deadline_s(app_config)`` explicitly, as the API
    tier's ``_await_handles`` does. On expiry every handle is CANCELLED —
    the decode slots free on the next engine step — before the
    TimeoutError propagates."""
    if timeout is None:
        timeout = request_deadline_s()
    n = max(1, req.n or 1)
    handles = []
    for i in range(n):
        constraint = constraint_factory() if constraint_factory else None
        gr = build_gen_request(
            sm, cfg, req, prompt, constraint=constraint, seed_offset=i
        )
        handles.append(sm.scheduler.submit(gr))
    try:
        for h in handles:
            h.result(timeout)
    except TimeoutError:
        for h in handles:
            h.cancel()
        raise
    return handles
