"""Gallery HTTP endpoints: apply/delete models, job status, browse.

Parity: /root/reference/core/http/endpoints/localai/gallery.go +
routes/localai.go:25-44 — POST /models/apply, POST /models/delete/:name,
GET /models/available, GET /models/jobs/:uuid, GET /models/jobs,
GET+POST+DELETE /models/galleries.
"""

from __future__ import annotations

import logging

from aiohttp import web

from localai_tpu.gallery import (
    EMBEDDED_MODELS,
    Gallery,
    GalleryModel,
    GalleryOp,
    available_models,
    resolve_ref,
)

log = logging.getLogger(__name__)


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


async def apply_model(request: web.Request) -> web.Response:
    """POST /models/apply — async install; returns a job uuid + status URL
    (parity: ApplyModelGalleryEndpoint, gallery.go)."""
    state = _state(request)
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")

    ref = body.get("id") or body.get("model") or ""
    op = GalleryOp(
        id="", kind="apply",
        install_name=body.get("name") or "",
        overrides=body.get("overrides") or {},
    )
    inline = None
    if body.get("url") or body.get("config_url"):
        inline = GalleryModel(
            name=op.install_name or ref or "model",
            url=body.get("url") or body.get("config_url"),
        )
    elif body.get("files") or body.get("config_file"):
        inline = GalleryModel.model_validate({
            "name": op.install_name or ref or "model",
            "files": body.get("files") or [],
            "config_file": body.get("config_file"),
        })
    elif ref:
        # shared resolution chain (embedded → URL → gallery); gallery refs
        # resolve lazily in the job worker so a slow index never blocks here
        inline = resolve_ref([], ref, name=op.install_name)
        if inline is not None and not inline.url:
            op.install_name = op.install_name or ref
    else:
        raise web.HTTPBadRequest(
            text="need one of: id (gallery@name), url, files"
        )
    op.model = inline
    op.gallery_ref = ref
    job_id = state.gallery_service.submit(op)
    return web.json_response({
        "uuid": job_id,
        "status": f"/models/jobs/{job_id}",
    })


async def delete_model_endpoint(request: web.Request) -> web.Response:
    state = _state(request)
    name = request.match_info["name"]
    op = GalleryOp(id="", kind="delete", install_name=name)
    job_id = state.gallery_service.submit(op)
    # drop any loaded instance so HBM frees immediately
    try:
        state.manager.shutdown_model(name, force=True)
    except Exception:  # noqa: BLE001
        log.debug("no loaded instance of %s to shut down", name)
    return web.json_response({
        "uuid": job_id,
        "status": f"/models/jobs/{job_id}",
    })


async def job_status(request: web.Request) -> web.Response:
    state = _state(request)
    st = state.gallery_service.status(request.match_info["uuid"])
    if st is None:
        raise web.HTTPNotFound(text="no such job")
    return web.json_response(st.as_dict())


async def all_jobs(request: web.Request) -> web.Response:
    return web.json_response(_state(request).gallery_service.all_status())


async def list_available(request: web.Request) -> web.Response:
    """GET /models/available — gallery models + embedded library entries
    (parity: ListModelFromGalleryEndpoint)."""
    import asyncio

    state = _state(request)
    out = []
    # gallery indexes are fetched over the network — keep it off the loop
    models = await asyncio.get_running_loop().run_in_executor(
        state.executor, available_models, state.galleries,
        state.config.model_path,
    )
    for m in models:
        out.append(m.model_dump(exclude={"config_file"}))
    for _name, m in sorted(EMBEDDED_MODELS.items()):
        d = m.model_dump(exclude={"config_file"})
        d["gallery"] = "embedded"
        out.append(d)
    return web.json_response(out)


async def list_galleries(request: web.Request) -> web.Response:
    return web.json_response([
        {"name": g.name, "url": g.url} for g in _state(request).galleries
    ])


async def add_gallery(request: web.Request) -> web.Response:
    state = _state(request)
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    name, url = body.get("name"), body.get("url")
    if not name or not url:
        raise web.HTTPBadRequest(text="need name and url")
    if any(g.name == name for g in state.galleries):
        raise web.HTTPConflict(text=f"gallery {name!r} already exists")
    state.add_gallery(Gallery(name=name, url=url))
    return web.json_response({"name": name, "url": url})


async def remove_gallery(request: web.Request) -> web.Response:
    state = _state(request)
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    name = body.get("name")
    if not state.remove_gallery(name):
        raise web.HTTPNotFound(text=f"no gallery {name!r}")
    return web.json_response({"removed": name})


def routes() -> list[web.RouteDef]:
    return [
        web.post("/models/apply", apply_model),
        web.post("/models/delete/{name}", delete_model_endpoint),
        web.get("/models/available", list_available),
        web.get("/models/jobs/{uuid}", job_status),
        web.get("/models/jobs", all_jobs),
        web.get("/models/galleries", list_galleries),
        web.post("/models/galleries", add_gallery),
        web.delete("/models/galleries", remove_gallery),
    ]
