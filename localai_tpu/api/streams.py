"""Sync engine streams → async HTTP responses.

The scheduler fills GenHandle queues from its engine thread
(localai_tpu.engine.scheduler); aiohttp handlers consume them through an
asyncio bridge so one slow SSE client never blocks the event loop or the
engine (parity concern: the reference's per-request goroutine + channel
fan-out, chat.go:455-508).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, AsyncIterator

from localai_tpu.engine.scheduler import GenHandle, StreamItem


async def aiter_handle(handle: GenHandle) -> AsyncIterator[StreamItem]:
    """Async view of a GenHandle's delta stream."""
    loop = asyncio.get_running_loop()
    q: asyncio.Queue = asyncio.Queue()

    def pump() -> None:
        for item in handle:
            loop.call_soon_threadsafe(q.put_nowait, item)

    t = threading.Thread(target=pump, daemon=True,
                         name=f"sse-pump-{handle.id}")
    t.start()
    while True:
        item = await q.get()
        yield item
        if item.finish_reason is not None:
            return


def mark_first_write(handle: GenHandle) -> None:
    """Record the first-token SSE write on the request's trace: the
    client-observable TTFT (engine first-token + queue/bridge latency).
    Idempotent — writers call it after EVERY content frame and only the
    first call records, so no per-loop first-flags are needed."""
    tr = getattr(handle, "trace", None)
    if tr is None or getattr(handle, "_first_write_marked", False):
        return
    handle._first_write_marked = True
    tr.event("first_sse_write")


def sse_event(payload: Any) -> bytes:
    """One `data: {json}` SSE frame (chat.go:463-508 wire shape)."""
    return b"data: " + json.dumps(
        payload, separators=(",", ":"), ensure_ascii=False
    ).encode("utf-8") + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"
SSE_HEADERS = {
    "Content-Type": "text/event-stream",
    "Cache-Control": "no-cache",
    "Connection": "keep-alive",
    "X-Accel-Buffering": "no",
}
