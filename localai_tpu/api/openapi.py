"""OpenAPI document + Swagger UI endpoint.

Parity: /root/reference/core/http/app.go:30 (the /swagger handler served
from generated swagger docs). The reference generates its spec offline
with swaggo annotations; here the spec is assembled at request time from
the live route table — every registered route appears, enriched with
hand-written schemas for the OpenAI-compatible surfaces, so the document
can never drift from the actual router. The UI page is self-contained
(zero-egress environment: no CDN assets) — a minimal request explorer
over the spec.
"""

from __future__ import annotations

import html

from aiohttp import web

from localai_tpu.version import __version__

# richer docs for the endpoints users hit most; everything else gets an
# auto-generated stub from the route table
_DOCS: dict[tuple[str, str], dict] = {
    ("POST", "/v1/chat/completions"): {
        "summary": "OpenAI-compatible chat completion",
        "requestBody": {
            "model": "string", "messages": "array", "stream": "boolean",
            "tools": "array", "max_tokens": "integer",
            "temperature": "number",
        },
    },
    ("POST", "/v1/completions"): {
        "summary": "Text completion (list prompts fan out to choices)",
        "requestBody": {"model": "string", "prompt": "string|array",
                        "stream": "boolean", "n": "integer"},
    },
    ("POST", "/v1/embeddings"): {
        "summary": "Embeddings (LLM mean-pool or bert sentence encoder)",
        "requestBody": {"model": "string", "input": "string|array"},
    },
    ("POST", "/v1/images/generations"): {
        "summary": "Image generation (diffusers-class pipelines)",
        "requestBody": {"model": "string", "prompt": "string",
                        "size": "string", "response_format": "string"},
    },
    ("POST", "/v1/audio/transcriptions"): {
        "summary": "Speech-to-text (whisper engine, multipart upload)",
    },
    ("POST", "/v1/audio/speech"): {
        "summary": "Text-to-speech",
        "requestBody": {"model": "string", "input": "string",
                        "voice": "string"},
    },
    ("POST", "/v1/rerank"): {
        "summary": "Jina-compatible rerank (cross-encoder or cosine)",
        "requestBody": {"model": "string", "query": "string",
                        "documents": "array", "top_n": "integer"},
    },
    ("POST", "/v1/files"): {"summary": "Upload a file (multipart)"},
    ("POST", "/v1/assistants"): {"summary": "Create an assistant"},
    ("POST", "/models/apply"): {
        "summary": "Install a model from a gallery (async job)",
        "requestBody": {"id": "string", "name": "string"},
    },
}


def build_spec(app: web.Application) -> dict:
    """Live route table → OpenAPI 3.0 document."""
    paths: dict[str, dict] = {}
    for route in app.router.routes():
        resource = route.resource
        if resource is None or route.method in ("HEAD", "OPTIONS"):
            continue
        path = resource.canonical
        doc = _DOCS.get((route.method, path), {})
        op: dict = {
            "summary": doc.get(
                "summary",
                (route.handler.__doc__ or "").strip().split("\n")[0]
                or f"{route.method} {path}",
            ),
            "responses": {"200": {"description": "OK"}},
        }
        body = doc.get("requestBody")
        if body:
            op["requestBody"] = {"content": {"application/json": {
                "schema": {
                    "type": "object",
                    "properties": {
                        k: {"type": "string"
                            if "|" in v or v == "string" else v}
                        for k, v in body.items()
                    },
                },
            }}}
        params = [p[1:-1] for p in path.split("/")
                  if p.startswith("{") and p.endswith("}")]
        if params:
            op["parameters"] = [
                {"name": p, "in": "path", "required": True,
                 "schema": {"type": "string"}} for p in params
            ]
        paths.setdefault(path, {})[route.method.lower()] = op
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "LocalAI-TPU API",
            "description": "OpenAI-compatible serving on JAX/TPU",
            "version": __version__,
        },
        "security": [{"bearerAuth": []}],
        "components": {"securitySchemes": {"bearerAuth": {
            "type": "http", "scheme": "bearer",
        }}},
        "paths": dict(sorted(paths.items())),
    }


async def spec_json(request: web.Request) -> web.Response:
    """GET /swagger/doc.json (the generated-docs path in the reference)."""
    return web.json_response(build_spec(request.app))


async def swagger_ui(request: web.Request) -> web.Response:
    """GET /swagger — a self-contained API explorer over the live spec."""
    doc = f"""<!doctype html><html><head><meta charset="utf-8">
<title>LocalAI-TPU API</title>
<style>body{{font:14px/1.5 system-ui;background:#0f1217;color:#e6e9ee;
margin:1.5rem auto;max-width:900px;padding:0 1rem}}
.ep{{border:1px solid #2a3240;border-radius:8px;margin:.5rem 0;
background:#171c24}}summary{{padding:.5rem .8rem;cursor:pointer}}
.m{{display:inline-block;min-width:52px;font-weight:700}}
.GET{{color:#38b26f}}.POST{{color:#4f9cf7}}.DELETE{{color:#d9573b}}
pre{{background:#0c0f14;padding:.6rem .8rem;border-radius:6px;
overflow:auto;margin:.4rem .8rem .8rem}}</style></head><body>
<h2>LocalAI-TPU API <small style="color:#8b95a5">{html.escape(__version__)}
</small></h2>
<p><a href="/swagger/doc.json" style="color:#4f9cf7">doc.json</a>
(OpenAPI 3.0)</p><div id="eps">loading…</div>
<script>
(async () => {{
  const spec = await (await fetch('/swagger/doc.json')).json();
  const out = [];
  for (const [path, ops] of Object.entries(spec.paths)) {{
    for (const [m, op] of Object.entries(ops)) {{
      const M = m.toUpperCase();
      const body = op.requestBody
        ? '<pre>' + JSON.stringify(
            op.requestBody.content['application/json'].schema.properties,
            null, 2) + '</pre>' : '';
      out.push(`<details class="ep"><summary><span class="m ${{M}}">${{M}}
        </span> <code>${{path}}</code> — ${{op.summary || ''}}</summary>
        ${{body}}</details>`);
    }}
  }}
  document.getElementById('eps').innerHTML = out.join('');
}})();
</script></body></html>"""
    return web.Response(text=doc, content_type="text/html")


def routes() -> list[web.RouteDef]:
    return [
        web.get("/swagger", swagger_ui),
        web.get("/swagger/doc.json", spec_json),
    ]
