"""LocalAI-specific endpoints: tokenize, metrics, system info, backend
monitor/shutdown, readiness.

Parity: /root/reference/core/http/routes/localai.go:20-67 and
core/http/endpoints/localai/ (tokenize, system, backend_monitor,
welcome/health).
"""

from __future__ import annotations

import asyncio
import logging
import time

from aiohttp import web

from localai_tpu.api.metrics import REGISTRY
from localai_tpu.version import __version__

log = logging.getLogger(__name__)


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


async def healthz(_request: web.Request) -> web.Response:
    return web.json_response({"status": "ok"})


async def readyz(request: web.Request) -> web.Response:
    """Ready = config loader up; per-model engines load lazily."""
    state = _state(request)
    return web.json_response({
        "status": "ok",
        "models_configured": len(state.loader.names()),
        "models_loaded": state.manager.loaded_names(),
    })


async def version(_request: web.Request) -> web.Response:
    return web.json_response({"version": __version__})


async def tokenize(request: web.Request) -> web.Response:
    """POST {model, content} → {tokens} (parity: TokenizeEndpoint,
    core/http/endpoints/localai/tokenize.go + TokenizeString RPC)."""
    from localai_tpu.api.openai import _serving
    from localai_tpu.api.schema import OpenAIRequest

    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")
    state = _state(request)
    model = body.get("model") or (state.loader.names() or [""])[0]
    if not model:
        raise web.HTTPNotFound(text="no models configured")
    content = body.get("content") or body.get("prompt") or ""
    sm, _cfg = await _serving(request, OpenAIRequest(model=model))
    ids = sm.tokenizer.encode(str(content), add_bos=False)
    return web.json_response({"tokens": ids})


async def metrics(request: web.Request) -> web.Response:
    # refresh token/slot/engine series from live engine state at scrape
    # time (counters are monotone: scheduler totals only grow; gauges are
    # point-in-time) — the decode loop itself never touches the registry
    from localai_tpu.obs.device import update_device_gauges
    from localai_tpu.obs.metrics import update_engine_gauges

    from localai_tpu.obs.history import HISTORY
    from localai_tpu.obs.ledger import LEDGER

    state = _state(request)
    # a fleet-served model's metrics() pulls one stats RPC per replica —
    # off the event loop, or a wedged replica freezes every endpoint for
    # the duration of its RPC timeout (single-engine models are host-side
    # reads and ride along unharmed)
    loop = asyncio.get_running_loop()
    engine_metrics = await loop.run_in_executor(None, state.manager.metrics)
    for name, m in engine_metrics.items():
        if isinstance(m, dict):
            update_engine_gauges(name, m)
            # multi-resolution history: every scrape doubles as a
            # sampling tick (host-side dict reads — no device work)
            HISTORY.observe_engine(name, m)
    # usage ledger → tenant/goodput/waste families + history series
    LEDGER.export(REGISTRY)
    HISTORY.observe_ledger(LEDGER)
    # fleet replica-state gauges refresh at scrape time too (host-side
    # state reads only; the routed/transfer counters are event-driven)
    for sm in state.manager.loaded_snapshot().values():
        export = getattr(getattr(sm, "scheduler", None),
                         "export_gauges", None)
        if export is not None:
            export()
    # device health at scrape time is host metadata only (memory_stats +
    # live-array census) — never a device dispatch: a scrape must not
    # queue work behind a wedged tunnel (the probe lives in /debug/devices)
    runners = [
        r for r in (
            getattr(sm, "runner", None)
            for sm in state.manager.loaded_snapshot().values()
        ) if r is not None
    ]
    update_device_gauges(runners)
    # SLO observatory: burn-rate + shedding gauges refresh at scrape time
    # too (host-side window scans only — never a device dispatch)
    from localai_tpu.obs import slo as obs_slo
    from localai_tpu.obs import trace as obs_trace

    obs_slo.SLO.export_gauges()
    # trace-store sizing receipt (LOCALAI_TRACE_CAPACITY): dashboards can
    # tell "trace evicted from the ring" from "trace never recorded"
    REGISTRY.trace_ring_size.set(obs_trace.STORE.capacity)
    # offline batch subsystem: job-state gauge + lane-paused flag refresh
    # at scrape time (host-side JSON reads only)
    state.batches.export_gauges()
    svc = state._batch_service
    REGISTRY.batch_lane_paused.set(
        1 if (svc is not None and svc.paused) else 0
    )
    return web.Response(
        text=REGISTRY.render(),
        content_type="text/plain",
        charset="utf-8",
    )


async def usage(request: web.Request) -> web.Response:
    """GET /v1/usage — the usage accounting plane (obs.ledger): per-tenant
    delivered tokens / dispatch-ms / queue-wait / KV-block-seconds by
    (model, lane), the goodput-vs-waste decomposition, and — for
    fleet-served models — per-replica drill-down panes harvested over
    GetTelemetry.

    Query params: ``?since=<unix ts>`` or ``?window=<seconds>`` narrow
    the per-tenant rows to the ledger's event ring (bounded — the
    response says how far back its coverage actually reaches); without
    them the lifetime totals answer. Tenants are hashed buckets
    (``t-<sha256/12>``) or ``anonymous`` — a raw API key never appears
    here. ``?replicas=1`` adds the fleet drill-down (one bounded RPC per
    replica, off the event loop)."""
    from localai_tpu.obs.fleetview import fleet_usage
    from localai_tpu.obs.ledger import LEDGER

    def num(name):
        raw = request.query.get(name)
        if raw is None or raw == "":
            return None
        try:
            return float(raw)
        except ValueError:
            raise web.HTTPBadRequest(text=f"{name} must be a number")

    since = num("since")
    window = num("window")
    state = _state(request)
    want_replicas = request.query.get("replicas") not in (None, "", "0")

    def build() -> dict:
        payload = LEDGER.usage_payload(since=since, window=window)
        if want_replicas:
            panes = {}
            for name, sm in state.manager.loaded_snapshot().items():
                if getattr(sm, "pool", None) is not None:
                    panes[name] = fleet_usage(sm)
            payload["replicas"] = panes
        return payload

    # the fleet drill-down pulls one bounded RPC per replica — executor,
    # never the event loop (same rule as every other harvest endpoint)
    loop = asyncio.get_running_loop()
    return web.json_response(await loop.run_in_executor(
        _state(request).executor, build))


async def slo_report(_request: web.Request) -> web.Response:
    """GET /v1/slo — the SLO observatory: per-model sliding-window
    (1m/5m/30m) TTFT/TPOT/e2e/queue-wait percentiles, burn rates against
    the configured p95 targets, and load-shedding state (obs.slo)."""
    from localai_tpu.obs import slo as obs_slo

    return web.json_response(obs_slo.SLO.report())


async def fleet_status(request: web.Request) -> web.Response:
    """GET /v1/fleet — the fleet observatory: per-model replica states,
    dial health, routing counters (affinity/least_loaded/failover +
    route-around), prefix-transfer stats, and per-replica shedding
    (localai_tpu.fleet). Models served by a single engine are listed with
    ``fleet: false`` so the panel shows the whole serving surface."""
    state = _state(request)
    loop = asyncio.get_running_loop()
    out: dict[str, dict] = {}
    for name, sm in state.manager.loaded_snapshot().items():
        status_fn = getattr(sm, "fleet_status", None)
        if status_fn is None:
            out[name] = {"fleet": False}
            continue
        # the status pulls one metrics RPC per replica — off the loop
        out[name] = {"fleet": True,
                     **await loop.run_in_executor(None, status_fn)}
    return web.json_response({
        "configured_replicas": state.config.fleet_replicas,
        "configured_prefill_replicas": state.config.fleet_prefill_replicas,
        "backend": state.config.fleet_backend,
        "models": out,
    })


async def fleet_register(request: web.Request) -> web.Response:
    """POST /federated/register on the SERVING instance: a remote worker
    announces itself (``{"address": "host:port", "model": optional,
    "role": "decode"|"prefill"}``) and is adopted into the matching fleet
    pools as a RemoteReplica — the fleet-tier twin of the federation
    router's registry, with the same ``peer_token`` guard, the same
    unroutable-address rejection, and offline-eviction parity (a peer
    that stops answering dials is evicted from routing and redialed on
    backoff, exactly like the router flips nodes offline)."""
    import hmac

    from localai_tpu.federation.server import validate_advertised_address

    state = _state(request)
    if state.config.peer_token:
        header = request.headers.get("Authorization", "")
        token = header.removeprefix("Bearer ").strip()
        if not hmac.compare_digest(token, state.config.peer_token):
            return web.json_response({"error": "invalid peer token"},
                                     status=401)
    try:
        body = await request.json()
        address = str(body["address"])
    except Exception:
        return web.json_response({"error": "address is required"},
                                 status=400)
    try:
        validate_advertised_address(address)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    role = str(body.get("role", "decode"))
    if role not in ("decode", "prefill"):
        return web.json_response(
            {"error": f"unknown role {role!r} (decode|prefill)"},
            status=400)
    model = body.get("model")
    targets = {}
    for name, sm in state.manager.loaded_snapshot().items():
        if model and name != model:
            continue
        if hasattr(sm, "adopt_remote"):
            targets[name] = sm
    if not targets:
        return web.json_response(
            {"error": (f"model {model!r} is not fleet-served" if model
                       else "no fleet-served model loaded")},
            status=409)
    if len(targets) > 1:
        # a worker process holds ONE model: adopting it into several
        # pools would leave every pool after the first seeing Status
        # READY and silently serving the FIRST pool's model under its
        # own name — the registration must say which model the peer is
        # for
        return web.json_response(
            {"error": "multiple fleet-served models are loaded "
                      f"({sorted(targets)}); pass \"model\" to say which "
                      "one the peer serves"},
            status=409)
    loop = asyncio.get_running_loop()
    adopted = {}
    for name, sm in targets.items():
        # the adoption dials + LoadModels the peer — off the event loop
        adopted[name] = await loop.run_in_executor(
            None, sm.adopt_remote, address, role)
    return web.json_response({"address": address, "adopted": adopted})


async def fleet_swap(request: web.Request) -> web.Response:
    """POST /v1/fleet/{model}/swap: hot weight swap as the deploy
    primitive — boot fresh replicas (``{"checkpoint": "ref"}`` switches
    weights; an empty body recycles the current ones), shift router
    traffic, drain and retire the old generation. Same ``peer_token``
    guard as fleet registration: this mutates serving capacity."""
    import hmac

    state = _state(request)
    if state.config.peer_token:
        header = request.headers.get("Authorization", "")
        token = header.removeprefix("Bearer ").strip()
        if not hmac.compare_digest(token, state.config.peer_token):
            return web.json_response({"error": "invalid peer token"},
                                     status=401)
    checkpoint = None
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON body"},
                                     status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be a JSON "
                                               "object"}, status=400)
        checkpoint = body.get("checkpoint")
        if checkpoint is not None and not isinstance(checkpoint, str):
            return web.json_response({"error": "checkpoint must be a "
                                               "string"}, status=400)
    name = request.match_info["model"]
    sm = state.manager.loaded_snapshot().get(name)
    if sm is None:
        return web.json_response({"error": f"model {name!r} is not "
                                           "loaded"}, status=404)
    swap_fn = getattr(sm, "swap", None)
    if swap_fn is None:
        return web.json_response({"error": f"model {name!r} is not "
                                           "fleet-served"}, status=409)
    loop = asyncio.get_running_loop()
    # the swap boots replicas and drains the old generation — off the loop
    result = await loop.run_in_executor(None, swap_fn, checkpoint)
    return web.json_response({"model": name, **result},
                             status=200 if result.get("ok") else 409)


async def system(request: web.Request) -> web.Response:
    """GET /system (parity: SystemInformations, routes/localai.go:64 —
    CPU/GPU info becomes the JAX device inventory)."""
    import jax

    state = _state(request)
    devices = [
        {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", ""),
            "process_index": d.process_index,
        }
        for d in jax.devices()
    ]
    return web.json_response({
        "version": __version__,
        "devices": devices,
        "backends": ["jax"],
        "loaded_models": state.manager.loaded_names(),
        "configured_models": state.loader.names(),
    })


async def backend_monitor(request: web.Request) -> web.Response:
    """POST {model} → engine status (parity: BackendMonitorEndpoint,
    core/http/endpoints/localai/backend_monitor.go)."""
    body = await request.json()
    name = body.get("model", "")
    if not name:
        raise web.HTTPBadRequest(text="missing 'model'")
    return web.json_response(_state(request).manager.monitor(name))


async def backend_shutdown(request: web.Request) -> web.Response:
    body = await request.json()
    name = body.get("model", "")
    if not name:
        raise web.HTTPBadRequest(text="missing 'model'")
    ok = _state(request).manager.shutdown_model(name)
    return web.json_response({"shutdown": ok, "model": name})


async def engine_metrics(request: web.Request) -> web.Response:
    """Per-model live slot metrics (parity: the GetMetrics RPC surface,
    grpc-server.cpp:2434-2457, exposed over /backend/monitor)."""
    loop = asyncio.get_running_loop()
    metrics = await loop.run_in_executor(
        None, _state(request).manager.metrics)
    return web.json_response(metrics)


async def backend_trace(request: web.Request) -> web.Response:
    """POST {seconds?, dir?} → capture a device/XLA profiler trace
    (jax.profiler, TensorBoard/XProf format) while serving continues.
    The TPU-era upgrade of the reference's pprof-style debug surface:
    traces show per-program device time, fusion layout, and HBM traffic —
    the ground truth for kernel/serving optimization. API-key-protected;
    one capture at a time; ``dir`` must stay under generated assets."""
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:  # malformed body is a client error, not a 500
            raise web.HTTPBadRequest(text="invalid JSON body")
        if not isinstance(body, dict):
            raise web.HTTPBadRequest(text="body must be a JSON object")
    else:
        body = {}
    try:
        seconds = float(body.get("seconds", 3.0))
    except (TypeError, ValueError):
        raise web.HTTPBadRequest(text="seconds must be a number")
    if not 0.1 <= seconds <= 60.0:
        raise web.HTTPBadRequest(text="seconds must be in [0.1, 60]")
    from localai_tpu.utils.paths import verify_path

    state = _state(request)
    base = state.config.backend_assets_path or "."
    try:
        out = verify_path(str(body.get("dir", "traces")), base)
    except ValueError as e:
        raise web.HTTPBadRequest(text=str(e))

    def capture() -> str:
        import jax

        # single-flight is SHARED with the anomaly profiler
        # (obs.profiler): the device runs at most one capture at a time
        # no matter which surface asked for it
        from localai_tpu.obs.profiler import PROFILER

        if not PROFILER.acquire_capture():
            raise RuntimeError("a trace capture is already running")
        try:
            path = str(out / time.strftime("trace-%Y%m%d-%H%M%S"))
            jax.profiler.start_trace(path)
            time.sleep(seconds)
            jax.profiler.stop_trace()
            return path
        finally:
            PROFILER.release_capture()

    loop = asyncio.get_running_loop()
    try:
        path = await loop.run_in_executor(None, capture)
    except RuntimeError as e:
        raise web.HTTPConflict(text=str(e))
    return web.json_response({"trace_dir": path, "seconds": seconds})


def routes() -> list[web.RouteDef]:
    return [
        web.get("/healthz", healthz),
        web.get("/readyz", readyz),
        web.get("/version", version),
        web.get("/metrics", metrics),
        web.get("/v1/usage", usage),
        web.get("/v1/slo", slo_report),
        web.get("/v1/fleet", fleet_status),
        web.post("/v1/fleet/{model}/swap", fleet_swap),
        web.post("/federated/register", fleet_register),
        web.get("/system", system),
        web.post("/v1/tokenize", tokenize),
        web.post("/tokenize", tokenize),
        web.post("/backend/monitor", backend_monitor),
        web.post("/backend/shutdown", backend_shutdown),
        web.get("/backend/metrics", engine_metrics),
        web.post("/backend/trace", backend_trace),
    ]
