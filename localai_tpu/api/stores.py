"""Vector-store HTTP endpoints.

Parity: /root/reference/core/http/endpoints/localai/stores.go +
routes/localai.go (POST /stores/set, /stores/get, /stores/find,
/stores/delete) backed by the jitted VectorStore instead of a spawned
local-store process.
"""

from __future__ import annotations

import base64
import logging

from aiohttp import web

log = logging.getLogger(__name__)


def _state(request: web.Request):
    from localai_tpu.api.server import STATE_KEY

    return request.app[STATE_KEY]


async def _body(request: web.Request) -> dict:
    try:
        return await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")


def _store(request: web.Request, body: dict):
    return _state(request).stores.get(body.get("store") or "default")


def _decode_values(raw: list) -> list[bytes]:
    out = []
    for v in raw:
        if isinstance(v, str):
            out.append(v.encode("utf-8"))
        elif isinstance(v, dict) and "b64" in v:
            try:
                out.append(base64.b64decode(v["b64"]))
            except Exception:
                raise web.HTTPBadRequest(text="invalid base64 value")
        else:
            raise web.HTTPBadRequest(
                text="values must be strings or {\"b64\": ...} objects"
            )
    return out


async def _run(request: web.Request, fn, *args):
    """Store ops touch the device (jit, matmul, O(N·D) rebuilds) — run
    them on the executor, mapping input errors to 400."""
    import asyncio

    try:
        return await asyncio.get_running_loop().run_in_executor(
            _state(request).executor, fn, *args
        )
    except ValueError as e:
        raise web.HTTPBadRequest(text=str(e))


async def stores_set(request: web.Request) -> web.Response:
    body = await _body(request)
    keys = body.get("keys") or []
    values = _decode_values(body.get("values") or [])
    await _run(request, _store(request, body).set, keys, values)
    return web.json_response({})


async def stores_get(request: web.Request) -> web.Response:
    body = await _body(request)
    st = _store(request, body)
    keys, values = await _run(request, st.get, body.get("keys") or [])
    found_keys, found_vals = [], []
    for k, v in zip(keys, values):
        if v is not None:
            found_keys.append(k)
            found_vals.append(v.decode("utf-8", "replace"))
    return web.json_response({"keys": found_keys, "values": found_vals})


async def stores_delete(request: web.Request) -> web.Response:
    body = await _body(request)
    await _run(request, _store(request, body).delete,
               body.get("keys") or [])
    return web.json_response({})


async def stores_find(request: web.Request) -> web.Response:
    body = await _body(request)
    key = body.get("key")
    if not key:
        raise web.HTTPBadRequest(text="need key")
    try:
        top_k = int(body.get("topk") or body.get("top_k") or 10)
    except (TypeError, ValueError):
        raise web.HTTPBadRequest(text="topk must be an integer")
    if top_k < 1:
        raise web.HTTPBadRequest(text="topk must be >= 1")
    keys, values, sims = await _run(
        request, _store(request, body).find, key, top_k)
    return web.json_response({
        "keys": keys,
        "values": [v.decode("utf-8", "replace") for v in values],
        "similarities": sims,
    })


def routes() -> list[web.RouteDef]:
    return [
        web.post("/stores/set", stores_set),
        web.post("/stores/get", stores_get),
        web.post("/stores/delete", stores_delete),
        web.post("/stores/find", stores_find),
    ]
