/* Native DFA-over-token-trie walk: the host-side hot loop of
 * grammar-constrained decoding.
 *
 * Role parity: the reference's grammar engine runs inside llama.cpp as
 * C++ (llama_grammar_* — /root/reference/backend/cpp/llama/grpc-server.cpp
 * wiring llama.cpp's grammar sampler); our token masks are computed on the
 * host between device steps, so this walk sits on the per-token latency
 * path for every constrained request (function calling, response_format).
 *
 * The trie stores nodes in creation order, so every parent id precedes its
 * children: one linear pass computes each node's DFA state from its
 * parent's. The Python fallback does the same with one numpy gather per
 * trie LEVEL (localai_tpu/functions/constraint.py TokenTrie.walk); this
 * kernel is a single cache-friendly O(n_nodes) loop with no temporary
 * index arrays. Compiled on demand by localai_tpu.native (cc -O3 -fPIC
 * -shared); the numpy path remains the fallback when no compiler exists.
 */

#include <stdint.h>

/* states[i] = trans[states[parent[i]] * n_classes + byte_class[edge[i]]]
 * for i in [1, n_nodes); states[0] is the start state (pre-filled).
 * trans rows for the DEAD state (-1) are handled by the caller giving a
 * DEAD row in trans itself (the DFA stores total transitions). */
void fsm_walk(const int32_t *trans, int32_t n_classes,
              const uint8_t *byte_class, const int64_t *parent,
              const int64_t *edge, int64_t n_nodes, int32_t *states) {
    for (int64_t i = 1; i < n_nodes; i++) {
        int32_t ps = states[parent[i]];
        states[i] = trans[(int64_t)ps * n_classes +
                          byte_class[edge[i]]];
    }
}

/* Mask build fused with the final-state gather: for each token id, row[id]
 * = 0.0f when the token is walkable and its leaf state is not DEAD, else
 * -1e30f. Saves two [V] temporaries per (state, grammar) cache miss. */
void fsm_mask(const int32_t *states, const int64_t *leaf_of_token,
              const uint8_t *token_ok, int64_t vocab, int32_t dead,
              float *row) {
    for (int64_t t = 0; t < vocab; t++) {
        row[t] = (token_ok[t] && states[leaf_of_token[t]] != dead)
                     ? 0.0f
                     : -1e30f;
    }
}
