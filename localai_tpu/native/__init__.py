"""Native (C) runtime components, compiled on demand.

The reference's runtime hot paths are native (llama.cpp's grammar
sampler, tokenizer, slot engine — C++); our device math lives in XLA, but
a few HOST-side per-token paths deserve native code too. Modules here
compile with the system compiler at first use (cc -O3 -shared) into the
user cache dir and load via ctypes — no pip, no pybind11, and every
caller keeps a pure-Python fallback, so a missing toolchain degrades to
the numpy path instead of failing.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

_SRC_DIR = Path(__file__).parent
_cache: dict[str, Optional[ctypes.CDLL]] = {}


def _build_dir() -> Path:
    d = Path(os.environ.get("LOCALAI_NATIVE_CACHE")
             or Path(tempfile.gettempdir()) / "localai_tpu_native")
    d.mkdir(parents=True, exist_ok=True)
    return d


def load(name: str) -> Optional[ctypes.CDLL]:
    """Compile (once per source hash) and load ``name``.c; None when no
    compiler is available — callers fall back to Python."""
    if name in _cache:
        return _cache[name]
    lib: Optional[ctypes.CDLL] = None
    try:
        src = _SRC_DIR / f"{name}.c"
        code = src.read_bytes()
        tag = hashlib.sha256(code).hexdigest()[:16]
        out = _build_dir() / f"{name}-{tag}.so"
        if not out.exists():
            for cc in ("cc", "gcc", "clang"):
                try:
                    subprocess.run(
                        [cc, "-O3", "-fPIC", "-shared", str(src),
                         "-o", str(out)],
                        check=True, capture_output=True, timeout=120,
                    )
                    break
                except (OSError, subprocess.SubprocessError):
                    continue
            else:
                raise RuntimeError("no working C compiler")
        lib = ctypes.CDLL(str(out))
        log.debug("native module %s loaded from %s", name, out)
    except Exception as e:  # noqa: BLE001 — fall back to Python
        log.info("native module %s unavailable (%s); using Python path",
                 name, e)
        lib = None
    _cache[name] = lib
    return lib
