"""Llama-family decoder (Llama 2/3, Mistral, Qwen2, Hermes, ...) — pure
functional JAX, designed for TPU serving.

This is the engine that replaces llama.cpp's C++ decode loop
(/root/reference/backend/cpp/llama/grpc-server.cpp:1546-1990) as the main LLM
compute path. Architectural choices are TPU-first, not a translation:

  * params are a pytree of stacked per-layer weights; the layer loop is a
    single ``lax.scan`` → one compiled layer body, O(1) XLA graph size.
  * all shapes are static: fixed slot count, fixed context; continuous
    batching is masking over slot tensors (see engine.scheduler), not
    ragged mutation.
  * bfloat16 weights/activations (MXU-native), float32 for RMSNorm,
    softmax and RoPE tables.
  * GQA is computed grouped ([S, n_kv, q_per_kv, ...]) so the KV repeat is
    a broadcast inside einsum, never materialized.
  * rope scaling supports linear / llama3 / yarn — parity with the
    reference's rope plumbing (/root/reference/core/config/
    backend_config.go:157-163, grpc-server.cpp:2279-2299).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from localai_tpu.models import quant as qnt

PyTree = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False          # Qwen2-style qkv bias
    rope_scaling: Optional[dict] = None   # HF rope_scaling dict
    sliding_window: Optional[int] = None  # Mistral-style (mask-only)
    num_experts: int = 0                  # Mixtral-class sparse MoE MLP
                                          # (0 = dense mlp)
    num_experts_per_tok: int = 2          # router top-k
    dtype: str = "bfloat16"

    @property
    def hd(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @classmethod
    def from_hf(cls, hf: dict) -> "LlamaConfig":
        """Build from an HF config.json dict (llama/mistral/qwen2 families)."""
        return cls(
            vocab_size=hf.get("vocab_size", 32000),
            hidden_size=hf.get("hidden_size", 4096),
            intermediate_size=hf.get("intermediate_size", 11008),
            num_layers=hf.get("num_hidden_layers", 32),
            num_heads=hf.get("num_attention_heads", 32),
            num_kv_heads=hf.get("num_key_value_heads",
                                hf.get("num_attention_heads", 32)),
            head_dim=hf.get("head_dim"),
            rope_theta=hf.get("rope_theta", 10000.0),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            attention_bias=hf.get("attention_bias", False)
            or hf.get("model_type") == "qwen2",
            rope_scaling=hf.get("rope_scaling"),
            sliding_window=hf.get("sliding_window"),
            num_experts=hf.get("num_local_experts", 0),
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        )


# ---------------------------------------------------------------------------
# RoPE
# ---------------------------------------------------------------------------

def rope_table(cfg: LlamaConfig, max_len: int,
               freq_base: Optional[float] = None,
               freq_scale: Optional[float] = None) -> tuple[jax.Array, jax.Array]:
    """Precompute (cos, sin) [max_len, hd/2] in float32.

    Supports HF rope_scaling types 'linear', 'llama3', 'yarn' and the
    reference's raw rope_freq_base/rope_freq_scale overrides
    (/root/reference/core/config/backend_config.go:162-163).
    """
    hd = cfg.hd
    base = freq_base or cfg.rope_theta
    inv_freq = 1.0 / (base ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    sc = cfg.rope_scaling or {}
    rtype = sc.get("rope_type", sc.get("type", "default"))
    attn_factor = 1.0

    if rtype == "linear":
        inv_freq = inv_freq / float(sc.get("factor", 1.0))
    elif rtype == "llama3":
        factor = float(sc.get("factor", 8.0))
        lo = float(sc.get("low_freq_factor", 1.0))
        hi = float(sc.get("high_freq_factor", 4.0))
        old_ctx = float(sc.get("original_max_position_embeddings", 8192))
        wavelen = 2 * math.pi / inv_freq
        # three bands: scale long wavelengths, keep short, smooth in between
        smooth = (old_ctx / wavelen - lo) / (hi - lo)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / factor
        inv_freq = (1 - smooth) * scaled + smooth * inv_freq
    elif rtype == "yarn":
        # YaRN (arXiv:2309.00071) NTK-by-parts interpolation, as plumbed by
        # the reference's yarn_* options (backend.proto:225-229).
        factor = float(sc.get("factor", 1.0))
        old_ctx = float(sc.get("original_max_position_embeddings", 4096))
        beta_fast = float(sc.get("beta_fast", 32.0))
        beta_slow = float(sc.get("beta_slow", 1.0))
        attn_factor = float(sc.get("attention_factor") or
                            (0.1 * math.log(factor) + 1.0 if factor > 1 else 1.0))

        def corr_dim(n_rot: float) -> float:
            return (hd * math.log(old_ctx / (n_rot * 2 * math.pi))) / (
                2 * math.log(base)
            )

        low = max(math.floor(corr_dim(beta_fast)), 0)
        high = min(math.ceil(corr_dim(beta_slow)), hd // 2 - 1)
        ramp = jnp.clip(
            (jnp.arange(hd // 2, dtype=jnp.float32) - low) / max(high - low, 1),
            0.0, 1.0,
        )
        inv_freq = inv_freq / factor * ramp + inv_freq * (1 - ramp)

    if freq_scale:
        inv_freq = inv_freq * freq_scale
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_len, hd/2]
    return jnp.cos(freqs) * attn_factor, jnp.sin(freqs) * attn_factor


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [..., heads, hd]; cos/sin broadcastable [..., 1, hd/2].

    Uses the HF 'rotate_half' convention (pairs are (i, i+hd/2)) to match
    safetensors weights without permutation.
    """
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def param_shapes(cfg: LlamaConfig) -> dict:
    """Shapes of the stacked-parameter pytree."""
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    Hq, Hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    shapes = {
        "embed": (cfg.vocab_size, D),
        "final_norm": (D,),
        "layers": {
            "attn_norm": (L, D),
            "wq": (L, D, Hq * hd),
            "wk": (L, D, Hkv * hd),
            "wv": (L, D, Hkv * hd),
            "wo": (L, Hq * hd, D),
            "mlp_norm": (L, D),
            "w_gate": (L, D, F),
            "w_up": (L, D, F),
            "w_down": (L, F, D),
        },
    }
    if cfg.num_experts:
        E = cfg.num_experts
        # Mixtral-class sparse MoE: expert-stacked ffn + a tiny router.
        # The leading E axis shards over the 'expert' mesh axis
        # (parallel.sharding), F over 'model' — expert × tensor parallelism.
        shapes["layers"].update({
            "moe_gate": (L, D, E),
            "w_gate": (L, E, D, F),
            "w_up": (L, E, D, F),
            "w_down": (L, E, F, D),
        })
    if cfg.attention_bias:
        shapes["layers"]["bq"] = (L, Hq * hd)
        shapes["layers"]["bk"] = (L, Hkv * hd)
        shapes["layers"]["bv"] = (L, Hkv * hd)
    if not cfg.tie_word_embeddings:
        shapes["lm_head"] = (D, cfg.vocab_size)
    return shapes


def init_params(rng: jax.Array, cfg: LlamaConfig) -> PyTree:
    """Random init (testing / benchmarking with synthetic weights)."""
    shapes = param_shapes(cfg)
    flat, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(flat))
    dtype = jnp.dtype(cfg.dtype)

    def mk(k, shape):
        if len(shape) == 1:  # norm gains
            return jnp.ones(shape, dtype)
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dtype)

    return jax.tree.unflatten(treedef, [mk(k, s) for k, s in zip(keys, flat)])


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layer(cfg: LlamaConfig, x, lp, cos, sin, attend, reduce=None):
    """One decoder layer. ``attend(q, k_new, v_new) -> (attn_out, new_kv)``
    is injected so prefill/decode/KV-cache policies stay out of the math.

    ``reduce`` (optional) is applied to the two row-parallel matmul outputs
    (attention-out, mlp-down) — under manual tensor parallelism inside
    shard_map it is ``lax.psum(·, 'model')``, turning the per-device
    partial sums into the Megatron two-psums-per-layer pattern. When None
    (single device, or GSPMD-managed sharding) the products are complete."""
    Hq, Hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    if reduce is not None:
        # local head counts under manual TP: weight shards carry Hq/tp and
        # Hkv/tp heads on each device
        Hq = lp["wq"].shape[-1] // hd
        Hkv = lp["wk"].shape[-1] // hd

    h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = qnt.matmul(h, lp["wq"])
    k = qnt.matmul(h, lp["wk"])
    v = qnt.matmul(h, lp["wv"])
    if "bq" in lp:
        q = q + lp["bq"].astype(q.dtype)
        k = k + lp["bk"].astype(k.dtype)
        v = v + lp["bv"].astype(v.dtype)
    q = q.reshape(*q.shape[:-1], Hq, hd)
    k = k.reshape(*k.shape[:-1], Hkv, hd)
    v = v.reshape(*v.shape[:-1], Hkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    attn, new_kv = attend(q, k, v)
    attn = attn.reshape(*attn.shape[:-2], Hq * hd)
    wo_out = qnt.matmul(attn, lp["wo"])
    x = x + (reduce(wo_out) if reduce is not None else wo_out)

    h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    if "moe_gate" in lp:
        out = _moe_mlp(cfg, h, lp, reduce)
    else:
        gated = (jax.nn.silu(qnt.matmul(h, lp["w_gate"]))
                 * qnt.matmul(h, lp["w_up"]))
        down = qnt.matmul(gated, lp["w_down"])
        out = reduce(down) if reduce is not None else down
    x = x + out
    return x, new_kv


def _moe_mlp(cfg: LlamaConfig, h, lp, reduce=None):
    """Mixtral-class sparse MoE MLP (parity: the reference's Mixtral GGUFs
    served by llama.cpp, gallery/index.yaml mixtral entries).

    Routing matches HF MixtralSparseMoeBlock: softmax over ALL experts,
    top-k, renormalize the selected weights. Compute is the dense-einsum
    formulation: every expert runs on every token and the router weights
    (zero off the top-k) select — the idiomatic TPU layout, since decode is
    weight-bandwidth-bound anyway (all expert weights stream from HBM once
    per step regardless) and it keeps static shapes/no gathers, letting the
    E axis shard over the 'expert' mesh axis and F over 'model'."""
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    logits = qnt.matmul(h, lp["moe_gate"]).astype(jnp.float32)   # [B, T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = lax.top_k(probs, k)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    # scatter the renormalized top-k back to a dense [B, T, E] weighting
    wfull = jnp.sum(
        jax.nn.one_hot(topi, E, dtype=topv.dtype) * topv[..., None], axis=-2
    )
    g = qnt.moe_up(h, lp["w_gate"])                              # [B, T, E, F]
    u = qnt.moe_up(h, lp["w_up"])
    a = jax.nn.silu(g) * u
    d = qnt.moe_down(a, lp["w_down"])                            # [B, T, E, D]
    out = jnp.einsum("...te,...ted->...td", wfull.astype(d.dtype), d)
    return reduce(out) if reduce is not None else out


def _grouped_attn(cfg: LlamaConfig, q, keys, values, mask):
    """Grouped-query attention.

    q: [S, T, Hq, hd], keys/values head-major: [S, Hkv, Lk, hd],
    mask: [S, T, Lk] bool (True = attend). Returns [S, T, Hq, hd].

    Head counts come from the operand SHAPES, not cfg: under manual tensor
    parallelism (shard_map bodies — parallel.ring, parallel.overlap) each
    device carries Hq/tp and Hkv/tp heads, and the same math applies to
    the local group."""
    S, T, Hq = q.shape[0], q.shape[1], q.shape[2]
    Hkv, hd = keys.shape[1], cfg.hd
    g = Hq // Hkv
    qg = q.reshape(S, T, Hkv, g, hd)
    scores = jnp.einsum("stkgh,sklh->skgtl", qg, keys) / math.sqrt(hd)
    scores = scores.astype(jnp.float32)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(values.dtype)
    out = jnp.einsum("skgtl,sklh->stkgh", probs, values)
    return out.reshape(S, T, Hq, hd)


def forward(
    cfg: LlamaConfig,
    params: PyTree,
    tokens: jax.Array,      # [B, T] int32
    positions: jax.Array,   # [B, T] int32 (absolute positions for RoPE)
    kv_write: Any,          # KV write policy: fn(layer_kv, k, v) -> (new_layer_kv, keys, values)
    kv_stack: Any,          # stacked KV pytree scanned alongside layers (or None)
    mask: jax.Array,        # [B, T, Lk] bool attention mask
    rope: tuple[jax.Array, jax.Array],
    attn: Any = None,       # optional override: fn(q, keys, values, mask) -> out
                            # (Pallas flash kernels inject here; None = XLA)
    embeds: Optional[jax.Array] = None,  # [B, T, D] input embeddings override
                            # (multimodal injection bypasses the token gather)
    reduce: Any = None,     # manual-TP row-parallel reduction applied to the
                            # attention-out / mlp-down products inside a
                            # shard_map body (parallel.overlap) — plain psum
                            # or the chunked psum_scatter+all_gather overlap
                            # decomposition; None = single device / GSPMD
) -> tuple[jax.Array, Any]:
    """Shared transformer trunk: returns (hidden [B, T, D], updated kv_stack).

    The layer loop is ``lax.scan`` over stacked weights + stacked KV so XLA
    compiles one layer body regardless of depth.
    """
    cos_t, sin_t = rope
    cos = cos_t[positions][:, :, None, :]  # [B, T, 1, hd/2]
    sin = sin_t[positions][:, :, None, :]
    if embeds is None:
        x = qnt.embed_rows(params["embed"], tokens, jnp.dtype(cfg.dtype))
    else:
        x = embeds.astype(jnp.dtype(cfg.dtype))
    if attn is None:
        attn = lambda q, keys, values, m: _grouped_attn(cfg, q, keys, values, m)  # noqa: E731

    def body(carry, layer_in):
        lp, layer_kv = layer_in

        def attend(q, k_new, v_new):
            new_kv, keys, values = kv_write(layer_kv, k_new, v_new)
            return attn(q, keys, values, mask), new_kv

        y, new_kv = _layer(cfg, carry, lp, cos, sin, attend, reduce=reduce)
        return y, new_kv

    x, new_kv_stack = lax.scan(body, x, (params["layers"], kv_stack))
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return x, new_kv_stack


def logits_from_hidden(cfg: LlamaConfig, params: PyTree, x: jax.Array) -> jax.Array:
    if cfg.tie_word_embeddings:
        return qnt.matmul_t(x, params["embed"])
    return qnt.matmul(x, params["lm_head"])
