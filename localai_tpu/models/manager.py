"""ModelManager: name → live serving engine, loaded on demand.

TPU-era redesign of the reference's model-lifecycle layer
(/root/reference/pkg/model/loader.go:22-206, initializers.go:271-540,
watchdog.go:19-156): where the reference spawns one gRPC worker *process*
per model and health-checks/respawns it, the in-process manager owns one
ModelRunner+Scheduler per model inside the server process. Process-level
isolation (crash containment) is provided by the separate gRPC worker tier
(localai_tpu.worker) — this manager is the in-process fast path, and both
expose the same surface.

Watchdog parity: busy-too-long requests are cancelled, idle-too-long
models are evicted to free HBM (defaults 5m/15m — core/cli/run.go:66-69).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Optional

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import Scheduler
from localai_tpu.templates.cache import TemplateCache

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ServingModel:
    """One loaded model: engine + tokenizer + its declarative config."""

    name: str
    config: ModelConfig
    runner: ModelRunner
    scheduler: Scheduler
    tokenizer: Any
    templates: TemplateCache
    vision: Optional[Any] = None      # VisionTower when the model is
                                      # multimodal (mmproj / llava checkpoint)
    image_token_id: int = 0
    loaded_at: float = dataclasses.field(default_factory=time.monotonic)
    last_used: float = dataclasses.field(default_factory=time.monotonic)

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        return self.scheduler.busy

    def alive(self) -> bool:
        """The engine thread is the health signal (a dead thread → reload,
        parity: CheckIsLoaded health path, loader.go:170-206)."""
        return self.scheduler._thread.is_alive()

    def close(self) -> None:
        self.scheduler.shutdown()

    def engine_metrics(self) -> dict:
        return self.scheduler.metrics()


@dataclasses.dataclass
class ImageServingModel:
    """A loaded diffusion pipeline under the same lifecycle management as
    LLMs: idle/busy watchdog, eviction, /backend/monitor visibility,
    single_active_backend accounting (VERDICT r2: the image cache used to
    bypass ModelManager entirely)."""

    name: str
    config: ModelConfig
    pipeline: Any
    loaded_at: float = dataclasses.field(default_factory=time.monotonic)
    last_used: float = dataclasses.field(default_factory=time.monotonic)
    _inflight: int = 0
    _lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    generated: int = 0

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def in_use(self):
        """Context manager holding the busy flag across a multi-image
        request so eviction sweeps can't null the pipeline between items."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            with self._lock:
                self._inflight += 1
            try:
                yield self
            finally:
                with self._lock:
                    self._inflight -= 1

        return cm()

    def alive(self) -> bool:
        return self.pipeline is not None

    def close(self) -> None:
        self.pipeline = None  # frees params (HBM) once consumers drop refs

    def engine_metrics(self) -> dict:
        return {"type": "image", "images_generated": self.generated}

    def generate(self, *args, **kwargs):
        """Run the pipeline with busy accounting (watchdog-visible).

        Snapshots the pipeline ref first: a concurrent eviction nulls
        self.pipeline, but an in-flight request keeps generating against
        its snapshot (params stay alive until the last ref drops)."""
        pipe = self.pipeline
        if pipe is None:
            raise RuntimeError(f"image model {self.name} was evicted")
        with self._lock:
            self._inflight += 1
        try:
            out = pipe.generate(*args, **kwargs)
        finally:
            with self._lock:
                self._inflight -= 1
        self.generated += 1
        self.touch()
        return out


@dataclasses.dataclass
class RerankServingModel:
    """A loaded cross-encoder under the same lifecycle management as LLMs
    (watchdog, eviction, /backend/monitor) — parity: the rerankers backend
    process, /root/reference/backend/python/rerankers/backend.py."""

    name: str
    config: ModelConfig
    encoder: Any                      # models.reranker.CrossEncoder
    loaded_at: float = dataclasses.field(default_factory=time.monotonic)
    last_used: float = dataclasses.field(default_factory=time.monotonic)
    _inflight: int = 0
    _lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    scored: int = 0

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def alive(self) -> bool:
        return self.encoder is not None

    def close(self) -> None:
        self.encoder = None  # frees params once in-flight scores finish

    def engine_metrics(self) -> dict:
        return {"type": "rerank", "pairs_scored": self.scored}

    def score(self, query: str, documents: list[str]):
        """(scores, total_tokens). Token counts come from the same encoder
        snapshot as the scores — the shared self.encoder may be nulled by
        an eviction the moment the in-flight count drops."""
        enc = self.encoder  # snapshot: eviction mid-request keeps params
        if enc is None:
            raise RuntimeError(f"reranker {self.name} was evicted")
        with self._lock:
            self._inflight += 1
        try:
            out, total_tokens = enc.score_with_usage(query, documents)
        finally:
            with self._lock:
                self._inflight -= 1
        self.scored += len(documents)
        self.touch()
        return out, total_tokens


@dataclasses.dataclass
class EmbeddingServingModel:
    """A loaded sentence encoder under lifecycle management (parity: the
    sentencetransformers backend process,
    /root/reference/backend/python/sentencetransformers/backend.py)."""

    name: str
    config: ModelConfig
    encoder: Any                      # models.reranker.SentenceEncoder
    loaded_at: float = dataclasses.field(default_factory=time.monotonic)
    last_used: float = dataclasses.field(default_factory=time.monotonic)
    _inflight: int = 0
    _lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    embedded: int = 0

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def alive(self) -> bool:
        return self.encoder is not None

    def close(self) -> None:
        self.encoder = None

    def engine_metrics(self) -> dict:
        return {"type": "embeddings", "texts_embedded": self.embedded}

    def embed(self, texts: list[str]):
        """(vectors, total_tokens) — token counts come from the same
        encoder snapshot as the vectors (eviction can null self.encoder
        the moment the in-flight count drops)."""
        enc = self.encoder  # snapshot vs concurrent eviction
        if enc is None:
            raise RuntimeError(f"embedder {self.name} was evicted")
        with self._lock:
            self._inflight += 1
        try:
            out, total = enc.embed_with_usage(texts)
        finally:
            with self._lock:
                self._inflight -= 1
        self.embedded += len(texts)
        self.touch()
        return out, total


@dataclasses.dataclass
class AudioServingModel:
    """A loaded whisper or VITS model under lifecycle management —
    idle/busy watchdog, eviction, /backend/monitor visibility (the same
    contract the image pipelines got in round 2; the audio caches used to
    live in private AppState dicts outside the manager)."""

    name: str
    config: ModelConfig
    model: Any                        # WhisperModel | VitsTTS
    kind: str = "whisper"             # "whisper" | "vits"
    loaded_at: float = dataclasses.field(default_factory=time.monotonic)
    last_used: float = dataclasses.field(default_factory=time.monotonic)
    _inflight: int = 0
    _lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    served: int = 0

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def alive(self) -> bool:
        return self.model is not None

    def close(self) -> None:
        self.model = None

    def engine_metrics(self) -> dict:
        return {"type": self.kind, "requests_served": self.served}

    def run(self, fn_name: str, *args, **kwargs):
        """Invoke a model method with busy accounting (watchdog-visible);
        snapshots the model ref so a concurrent eviction can't null it
        mid-request."""
        model = self.model
        if model is None:
            raise RuntimeError(f"{self.kind} model {self.name} was evicted")
        with self._lock:
            self._inflight += 1
        try:
            out = getattr(model, fn_name)(*args, **kwargs)
        finally:
            with self._lock:
                self._inflight -= 1
        self.served += 1
        self.touch()
        return out


def _auto_mesh(cfg, num_slots: int):
    """The no-flag meshed-serving default (ROADMAP item 3): on a single
    host with >1 visible accelerator, build a dp×tp mesh with tp as wide
    as the q-head count allows (``model=all`` when it divides). CPU stays
    single-device — tier-1 semantics are byte-identical without a mesh —
    unless ``LOCALAI_MESH_AUTO=1`` forces the auto path (CPU-mesh smoke
    tests); ``LOCALAI_MESH_AUTO=0`` disables it on accelerators. Explicit
    topology (``--mesh`` / ``LOCALAI_MESH`` / sharding config) never
    reaches this function. Returns None when a mesh buys nothing."""
    import jax

    from localai_tpu.parallel.mesh import (MeshPlan, build_mesh,
                                           default_tensor_parallel)

    auto = os.environ.get("LOCALAI_MESH_AUTO", "")
    if auto == "0":
        return None
    devs = jax.devices()
    if len(devs) < 2 or (devs[0].platform == "cpu" and auto != "1"):
        return None
    tp = default_tensor_parallel(len(devs), cfg.num_heads)
    if tp < 2:
        log.warning(
            "auto mesh: %d devices visible but num_heads=%d admits no "
            "tensor-parallel split; serving single-device",
            len(devs), cfg.num_heads)
        return None
    dp = len(devs) // tp
    if dp > 1 and num_slots % dp:
        # the decode state shards slots over 'data'; an indivisible slot
        # count keeps TP only (on tp devices) rather than failing the load
        log.warning(
            "auto mesh: max_slots=%d not divisible by data=%d; using "
            "model=%d on %d of %d devices", num_slots, dp, tp, tp,
            len(devs))
        return build_mesh(MeshPlan(model=tp), devices=devs[:tp])
    return build_mesh(MeshPlan(data=dp, model=tp))


def build_runner(mcfg: ModelConfig, app: AppConfig) -> tuple[Any, ModelRunner]:
    """Config → (resolved model, live ModelRunner): weights, mesh,
    shardings. Shared by the serving path and multi-host followers — a
    follower MUST construct a bit-identical runner (same config, same
    seed) so replayed commands keep every host in the same program."""
    from localai_tpu.models.registry import resolve_model

    eng = mcfg.engine
    shard = mcfg.sharding
    mesh = None
    explicit_mesh = False
    want_tp = max(1, shard.tensor_parallel_size)
    want_sp = max(1, shard.sequence_parallel_size)
    want_ep = max(1, shard.expert_parallel_size)
    want_pp = max(1, shard.pipeline_parallel_size)
    want_dp = shard.data_parallel_size  # 0 = auto
    if (want_tp > 1 or want_sp > 1 or want_ep > 1 or want_pp > 1
            or want_dp not in (0, 1) or app.mesh_shape):
        from localai_tpu.parallel.mesh import MeshPlan, build_mesh

        explicit_mesh = True
        if app.mesh_shape:
            mesh = build_mesh(MeshPlan(**app.mesh_shape))
        elif want_pp > 1:
            import jax

            if want_tp > 1 or want_sp > 1 or want_ep > 1 \
                    or want_dp not in (0, 1):
                # fail loudly: silently dropping the other knobs would
                # serve an unsharded layout the user didn't configure
                raise ValueError(
                    "pipeline_parallel_size composes with no other "
                    "sharding axis yet; unset tensor/sequence/expert/"
                    "data_parallel_size")
            # pipeline capacity mode runs the 'pipe' axis alone — claim
            # exactly pp devices
            mesh = build_mesh(MeshPlan(pipe=want_pp),
                              devices=jax.devices()[:want_pp])
        else:
            import jax

            nd = len(jax.devices())
            dp = want_dp or max(1, nd // (want_tp * want_sp * want_ep))
            mesh = build_mesh(
                MeshPlan(data=dp, seq=want_sp, expert=want_ep,
                         model=want_tp)
            )

    model = resolve_model(
        mcfg.model or mcfg.name,
        model_path=app.model_path,
        dtype=eng.dtype,
    )
    if mesh is None and not explicit_mesh:
        # meshed serving is the default hot path whenever >1 accelerator
        # is visible (pjit tensor-parallel, paged pool sharded over
        # 'model'); modes whose runners assume single-device layouts keep
        # it off: multi-host command mirroring builds its own topology
        # and self-extend forces the unroped single-row cache.
        # Speculative decoding composes now — the draft runner shares
        # the target's mesh (localai_tpu.spec.ModelDrafter)
        if not (app.mirror_port or eng.grp_attn_n > 1):
            mesh = _auto_mesh(model.cfg, eng.max_slots)
            if mesh is not None:
                log.info("auto mesh for %s: %s", mcfg.name,
                         dict(mesh.shape))
    params = model.params
    if eng.quantization:
        from localai_tpu.models.quant import quantize_params

        params = quantize_params(params, eng.quantization)
    if mesh is not None:
        if mesh.shape.get("pipe", 1) > 1:
            # layer-sharded capacity mode (parallel.pipeline)
            from localai_tpu.parallel.pipeline import shard_params_pp

            params = shard_params_pp(params, model.cfg, mesh)
        else:
            from localai_tpu.parallel import sharding as shd

            params = shd.shard_params(params, model.cfg, mesh)
    ctx = mcfg.context_size or app.context_size
    # self-extend lifts the trained-context ceiling by the group factor
    # (llama.cpp: n_ctx >= n_ctx_train * ga_n, grpc-server.cpp:535)
    ctx = min(ctx, model.cfg.max_position_embeddings * max(eng.grp_attn_n, 1))
    # paged KV (block pool + chunked prefill): the serving default for
    # single-device AND meshed engines alike (the pool shards its kv-head
    # axis over 'model'; the table mirror rides 'data'). Speculative
    # decoding runs block-native on this layout (localai_tpu.spec), so
    # draft-model engines are paged too; only multi-host mirroring still
    # drives the contiguous layout, and the runner itself gates off
    # pipeline-parallel/self-extend. Explicit per-model config wins;
    # otherwise the compatibility decision applies and LOCALAI_KV_PAGED=0
    # force-disables (=1 adds nothing here: auto already enables
    # everything compatible, and overriding the mirror exclusion would
    # crash that engine at load).
    paged = eng.kv_paged
    if paged is None:
        paged = ((mesh is None or mesh.shape.get("pipe", 1) == 1)
                 and eng.grp_attn_n <= 1
                 and not app.mirror_port
                 and os.environ.get("LOCALAI_KV_PAGED", "") != "0")
    # LOCALAI_KV_DTYPE flips the KV-cache dtype fleet-wide (int8 halves
    # KV bytes vs bf16; int4 halves them again via the nibble-packed
    # paged pool). Explicit per-model config wins; int4 only exists for
    # the paged layout, so contiguous engines (mirrors, self-extend)
    # keep their configured dtype with a warning instead of crashing
    # at runner construction.
    kv_dtype = eng.kv_dtype
    env_kv = os.environ.get("LOCALAI_KV_DTYPE", "").strip()
    if env_kv and kv_dtype == "bfloat16":
        if env_kv == "int4" and not paged:
            log.warning(
                "LOCALAI_KV_DTYPE=int4 ignored for %s: int4 KV requires "
                "the paged layout (engine is contiguous)", mcfg.name)
        else:
            kv_dtype = env_kv
    runner = ModelRunner(
        model.cfg,
        params,
        num_slots=eng.max_slots,
        max_ctx=ctx,
        prefill_buckets=eng.prefill_buckets,
        kv_dtype=kv_dtype,
        rope_freq_base=mcfg.rope_freq_base,
        rope_freq_scale=mcfg.rope_freq_scale,
        seed=mcfg.seed or 0,
        mesh=mesh,
        sp_threshold=eng.sp_prefill_threshold,
        attn_impl=eng.attn_impl,
        ga_n=eng.grp_attn_n,
        ga_w=eng.grp_attn_w,
        paged=paged,
        kv_block_tokens=eng.kv_block_tokens,
        kv_num_blocks=eng.kv_num_blocks,
        prefill_chunk=eng.prefill_chunk,
    )
    return model, runner


def build_serving_model(mcfg: ModelConfig, app: AppConfig) -> ServingModel:
    """Config → live engine: resolve weights, build mesh/shardings, runner,
    scheduler, tokenizer, templates. Shared by the in-process manager and
    the gRPC worker tier (localai_tpu.worker.server), so both load paths
    behave identically."""
    t0 = time.monotonic()
    eng = mcfg.engine
    model, runner = build_runner(mcfg, app)
    mesh = runner.mesh
    ctx = runner.max_ctx
    if app.mirror_port:
        # multi-host leader: every engine call re-broadcasts to the
        # follower group before running locally (parallel/multihost.py)
        from localai_tpu.parallel.multihost import (
            MirroredRunner,
            get_leader,
        )

        leader = get_leader(app.mirror_port, app.mirror_followers,
                            token=app.peer_token)
        if app.mirror_followers:
            leader.wait_for(app.mirror_followers)
        runner = MirroredRunner(runner, leader, mcfg.name)
    # block-native speculative decoding (localai_tpu.spec): the default
    # for paged engines — the self-drafting n-gram lane needs no second
    # model, so single-model deployments get speculation out of the box;
    # a configured draft_model upgrades the drafter to a co-located
    # draft runner sharing the mesh. Contiguous engines opt in via
    # draft_model (the legacy shape). Knobs: engine.spec/spec_drafter/
    # spec_gamma, LOCALAI_SPEC=0 kill switch, LOCALAI_SPEC_DRAFTER /
    # LOCALAI_SPEC_GAMMA / LOCALAI_SPEC_NGRAM_MAX env overrides.
    spec = None
    spec_want = eng.spec
    if spec_want is None:
        spec_want = ((getattr(runner, "paged", False)
                      or bool(eng.draft_model))
                     and os.environ.get("LOCALAI_SPEC", "") != "0")
    if spec_want and app.mirror_port:
        log.warning(
            "%s: speculative decoding is not supported with multi-host "
            "command mirroring yet; serving without it", mcfg.name
        )
    elif spec_want and eng.grp_attn_n > 1:
        log.warning(
            "%s: speculative decoding is not supported with self-extend "
            "(grp_attn_n>1); serving without it", mcfg.name,
        )
    elif spec_want and getattr(runner, "pp_enabled", False):
        log.warning(
            "%s: speculative decoding is not supported with pipeline "
            "parallelism; serving without it", mcfg.name,
        )
    elif spec_want:
        from localai_tpu.spec import build_spec_engine

        drafter = (os.environ.get("LOCALAI_SPEC_DRAFTER", "")
                   or eng.spec_drafter or "auto")
        if drafter == "model" and not eng.draft_model:
            log.warning(
                "%s: spec_drafter=model but no draft_model configured; "
                "using the n-gram self-drafter", mcfg.name)
            drafter = "ngram"
        gamma = eng.spec_gamma
        if gamma is None and eng.draft_model:
            gamma = max(1, eng.n_draft)
        spec = build_spec_engine(
            runner,
            drafter=drafter,
            draft_ref=eng.draft_model,
            model_path=app.model_path,
            gamma=gamma,
            dtype=eng.dtype,
        )
        log.info(
            "%s: speculative decoding on (%s drafter, gamma=%d, %s KV)",
            mcfg.name, spec.drafter.name, spec.gamma,
            "paged" if spec.paged else "contiguous",
        )
    prompt_cache = None
    if mcfg.prompt_cache_path and app.mirror_port:
        log.warning(
            "%s: prompt_cache_path is not supported with multi-host command "
            "mirroring (KV loads would desync followers); ignoring", mcfg.name
        )
    elif mcfg.prompt_cache_path:
        from pathlib import Path

        from localai_tpu.engine.promptcache import PromptKVCache

        pc_path = Path(mcfg.prompt_cache_path)
        if not pc_path.is_absolute():
            pc_path = Path(app.model_path) / pc_path
        prompt_cache = PromptKVCache(
            pc_path, read_only=mcfg.prompt_cache_ro,
            min_prefix=runner.prefix_reuse_min,
        )
        log.info(
            "%s: prompt KV cache at %s (%s%s)", mcfg.name, pc_path,
            "ro, " if mcfg.prompt_cache_ro else "",
            "prompt+generation" if mcfg.prompt_cache_all else "prompt only",
        )
    from localai_tpu.obs import EngineTelemetry

    scheduler = Scheduler(
        runner,
        model.tokenizer,
        default_max_tokens=mcfg.parameters.max_tokens or 2048,
        multi_step=eng.decode_steps_per_dispatch,
        pipeline_depth=eng.pipeline_depth,
        stream_latency_target=eng.stream_latency_ms / 1000.0,
        spec=spec,
        prompt_cache=prompt_cache,
        prompt_cache_all=mcfg.prompt_cache_all,
        telemetry=EngineTelemetry(model=mcfg.name),
    )
    # self-healing supervisor (localai_tpu.faults): a watchdog stall on
    # this engine's channel escalates trace → drain-with-5xx → runner
    # re-init → probe dispatch, bounded+backed-off, then marks the model
    # failed (the dead-engine reload path here owns further recovery).
    # SpecEngine engines rebuild too (drafter.reinit rides the runner
    # re-init); only legacy spec objects without supports_rebuild are
    # excluded. LOCALAI_SELF_HEAL=0 disables. (multi-host mirrored
    # runners are also excluded: a leader-local rebuild would desync the
    # follower group's replayed command stream)
    if ((spec is None or getattr(spec, "supports_rebuild", False))
            and not app.mirror_port
            and os.environ.get("LOCALAI_SELF_HEAL", "1") != "0"):
        from localai_tpu.faults import EngineSupervisor

        EngineSupervisor(scheduler)
    # vision tower: explicit mmproj ref, or auto from a llava checkpoint dir
    vision = None
    vt_ref = mcfg.mmproj or (
        str(model.model_dir) if model.hf_type == "llava" else None
    )
    if vt_ref:
        from localai_tpu.models.vision import resolve_vision_tower

        vision = resolve_vision_tower(
            vt_ref,
            projection_dim=model.cfg.hidden_size,
            model_path=app.model_path,
            seed=mcfg.seed or 0,
        )
        log.info("loaded vision tower %s: %d patches -> D=%d",
                 vt_ref, vision.n_patches, model.cfg.hidden_size)
    log.info(
        "loaded model %s (%s) in %.1fs: slots=%d ctx=%d mesh=%s",
        mcfg.name, mcfg.model, time.monotonic() - t0,
        eng.max_slots, ctx, mesh.shape if mesh else None,
    )
    return ServingModel(
        name=mcfg.name,
        config=mcfg,
        runner=runner,
        scheduler=scheduler,
        tokenizer=model.tokenizer,
        templates=TemplateCache(app.model_path),
        vision=vision,
        image_token_id=(
            mcfg.image_token_id if mcfg.image_token_id is not None
            else (model.image_token_id or 0)
        ),
    )


class ModelManager:
    """Thread-safe registry of loaded models (parity: ModelLoader map +
    mutex, loader.go:22-40)."""

    def __init__(
        self,
        app_config: Optional[AppConfig] = None,
        loader: Optional[ConfigLoader] = None,
    ):
        self.app = app_config or AppConfig()
        self.loader = loader or ConfigLoader(self.app.model_path)
        self._models: dict[str, Any] = {}   # ServingModel | WorkerServingModel
                                            # | ImageServingModel
        self._load_locks: dict[str, threading.Lock] = {}
        self._reranker_detect: dict[tuple, bool] = {}
        self._lock = threading.RLock()
        self._pool = None                   # WorkerPool, created on demand
        self._watchdog: Optional[_Watchdog] = None
        if self.app.watchdog_idle or self.app.watchdog_busy:
            self._watchdog = _Watchdog(self)
            self._watchdog.start()

    def pool(self):
        """Lazy worker-process pool (spawn tier)."""
        with self._lock:
            if self._pool is None:
                from localai_tpu.worker.process import WorkerPool

                self._pool = WorkerPool()
            return self._pool

    # -- lookup / load ----------------------------------------------------

    def loaded_names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def loaded_snapshot(self) -> dict[str, Any]:
        """Point-in-time view of the loaded models (never triggers a load)
        — the /debug/devices HBM census walks in-process runners through
        this."""
        with self._lock:
            return dict(self._models)

    def is_loaded(self, name: str) -> bool:
        with self._lock:
            return name in self._models

    def get(self, name: str) -> ServingModel:
        """Idempotent load-or-get (parity: ModelLoader.LoadModel +
        CheckIsLoaded health path, loader.go:96-206). A dead engine
        (in-process thread or worker process) → reload/respawn."""
        return self._get_typed(name, self._load, kind="llm")

    def get_image(self, name: str) -> ImageServingModel:
        """Load-or-get a diffusion pipeline under lifecycle management
        (watchdog, eviction, monitor — same contract as LLMs)."""
        return self._get_typed(name, self._load_image, kind="image")

    def get_reranker(self, name: str) -> RerankServingModel:
        """Load-or-get a cross-encoder reranker (same lifecycle contract)."""
        return self._get_typed(name, self._load_reranker, kind="rerank")

    def get_embedder(self, name: str) -> EmbeddingServingModel:
        """Load-or-get a bert-class sentence encoder (same contract)."""
        return self._get_typed(name, self._load_embedder, kind="embed")

    def get_whisper(self, name: str) -> AudioServingModel:
        """Load-or-get a whisper STT model (same lifecycle contract)."""
        return self._get_typed(name, self._load_whisper, kind="whisper")

    def get_vits(self, name: str) -> AudioServingModel:
        """Load-or-get a VITS voice (same lifecycle contract)."""
        return self._get_typed(name, self._load_vits, kind="vits")

    def is_embedder(self, mcfg: ModelConfig) -> bool:
        """Route /v1/embeddings to the sentence encoder for bert-class
        checkpoints (backend: bert-embeddings, set explicitly or by
        autodetection at config load)."""
        return mcfg.backend in ("bert-embeddings", "sentencetransformers")

    def is_reranker(self, mcfg: ModelConfig) -> bool:
        """Route a model to the cross-encoder path: explicit
        ``backend: reranker`` or a bert-class checkpoint (auto-detect,
        guesser parity). The filesystem sniff is cached — this runs on
        every /v1/rerank request, on the event loop."""
        if mcfg.backend == "reranker":
            return True
        if mcfg.backend:
            return False
        key = (mcfg.name, mcfg.model)
        now = time.monotonic()
        with self._lock:
            hit = self._reranker_detect.get(key)
        # positive hits are stable (a bert checkpoint stays bert);
        # negatives expire so installing the checkpoint later is picked
        # up without a restart
        if hit is not None:
            found, at = hit
            if found or now - at < 30.0:
                return found
        from localai_tpu.models.reranker import is_reranker_checkpoint

        found = is_reranker_checkpoint(
            mcfg.model or mcfg.name, self.app.model_path
        )
        with self._lock:
            self._reranker_detect[key] = (found, now)
        return found

    def _get_typed(self, name: str, load, *, kind: str) -> Any:
        # fast path + cache maintenance under the global lock; the load
        # itself (worker spawn / weight read, tens of seconds) runs under a
        # per-name lock so one cold model never stalls warm lookups
        cached = self._check_cached(name, kind)
        if cached is not None:
            return cached
        with self._lock:
            lk = self._load_locks.setdefault(name, threading.Lock())
        with lk:
            cached = self._check_cached(name, kind)  # raced loader won?
            if cached is not None:
                return cached
            mcfg = self.loader.get(name)
            if mcfg is None:
                raise KeyError(f"no configuration for model {name!r}")
            if self.app.single_active_backend:
                with self._lock:
                    for other in list(self._models):
                        if not self._models[other].busy:
                            self._evict_locked(other)
            sm = load(mcfg)
            with self._lock:
                self._models[name] = sm
            return sm

    def _check_cached(self, name: str, kind: str) -> Optional[Any]:
        """Return the cached model if it is the right kind and alive;
        evict (and return None) otherwise."""
        with self._lock:
            sm = self._models.get(name)
            if sm is None:
                return None
            cached_kind = (
                "image" if isinstance(sm, ImageServingModel)
                else "rerank" if isinstance(sm, RerankServingModel)
                else "embed" if isinstance(sm, EmbeddingServingModel)
                else sm.kind if isinstance(sm, AudioServingModel)
                else "llm"
            )
            if cached_kind != kind:
                # one name, two modalities: latest request wins (same
                # semantics as single_active_backend), unless in use
                if sm.busy:
                    raise RuntimeError(
                        f"model {name!r} is busy serving as {cached_kind}"
                    )
                log.info("model %s switching modality; reloading", name)
                self._evict_locked(name)
                return None
            if not sm.alive():
                log.warning("model %s engine died; reloading", name)
                self._evict_locked(name)
                return None
            sm.touch()
            return sm

    def _load(self, mcfg: ModelConfig) -> Any:
        # fleet tier: with --fleet-replicas N (N>1) an LLM is served from
        # N data-parallel engine replicas behind one facade (cache-aware
        # routing, failover, optional prefill/decode disaggregation —
        # localai_tpu.fleet). Modality backends, externally managed
        # workers, and embeddings/rerank-capable models keep their
        # single-engine paths: the fleet facade only speaks the streaming
        # generation protocol, and /v1/embeddings//v1/rerank need the
        # in-process runner.embed surface.
        ext = self.app.external_backends.get(mcfg.name)
        # remote hosts alone are enough to go fleet-tier: a box with one
        # (or zero) local engines can still front a pod of adopted peers
        if ((self.app.fleet_replicas > 1 or self.app.fleet_hosts)
                and not ext and mcfg.backend in ("", "worker")):
            from localai_tpu.config.model_config import Usecase

            if (mcfg.has_usecase(Usecase.EMBEDDINGS)
                    or mcfg.has_usecase(Usecase.RERANK)):
                log.warning(
                    "model %s: embeddings/rerank-capable models are not "
                    "fleet-served; keeping the single-engine path",
                    mcfg.name)
            else:
                return self._load_fleet(mcfg)
        # worker-tier routing: `backend: worker` spawns a gRPC worker
        # process (crash isolation, initializers.go:271-407);
        # external_backends route to an externally managed worker address
        if ext or mcfg.backend == "worker":
            from localai_tpu.worker.serving import WorkerServingModel

            return WorkerServingModel(
                mcfg, self.app, self.pool(), external_address=ext or None
            )
        if mcfg.backend in ("huggingface", "langchain-huggingface"):
            from localai_tpu.models.hf_api import HFApiServingModel

            return HFApiServingModel(mcfg, self.app)
        if mcfg.backend in ("mamba", "rwkv"):
            from localai_tpu.models.mamba_serving import MambaServingModel

            return MambaServingModel(mcfg, self.app)
        try:
            return build_serving_model(mcfg, self.app)
        except Exception:
            # greedy-chain tail: name the engine the checkpoint actually
            # belongs to instead of a cryptic tensor-mapping error
            # (parity: initializers.go falling through its backend list)
            from localai_tpu.models.detect import detect_backend

            family = detect_backend(
                mcfg.model or mcfg.name, self.app.model_path
            )
            if family:
                raise RuntimeError(
                    f"model {mcfg.name!r} is a {family} checkpoint, not "
                    f"an LLM — set `backend: {family}` (or use the "
                    f"matching endpoint)"
                ) from None
            raise

    def _load_fleet(self, mcfg: ModelConfig) -> Any:
        """Build a FleetServingModel: N engine replicas behind one facade
        (localai_tpu.fleet). fleet_backend picks the replica shape —
        ``worker`` (default) spawns one gRPC worker process per replica
        (crash isolation; pin devices per replica via worker_env),
        ``inprocess`` builds N engines in this process (CPU tests, CI
        smoke, single-host experiments). On top of the local replicas,
        every ``host:port`` in app.fleet_hosts is adopted as a
        RemoteReplica (cross-host serving; the facade reads the list off
        the app config), and more peers can join at runtime through
        POST /federated/register."""
        from localai_tpu.fleet import FleetServingModel
        from localai_tpu.fleet.replica import InProcessReplica, WorkerReplica

        app = self.app
        # hot-swap indirection: the factory reads its model config from
        # this holder at SPAWN time, so rebinding it (fleet.autoscale
        # density.hot_swap) makes every later runtime spawn boot the new
        # checkpoint while the running generation keeps its own
        cfg_ref = {"mcfg": mcfg}
        if app.fleet_backend == "inprocess":
            def factory(rid: str, role: str):
                # each replica engine gets its own identity: under the
                # shared name its telemetry/SLO events would double-count
                # every request the fleet tier already records (worker
                # replicas are naturally separate — their own process,
                # their own registry)
                live = cfg_ref["mcfg"]
                rcfg = live.model_copy(update={
                    "name": rid, "model": live.model or live.name})
                return InProcessReplica(
                    rid, role, lambda: build_serving_model(rcfg, app))
        else:
            total = app.fleet_replicas + app.fleet_prefill_replicas

            def factory(rid: str, role: str):
                env = dict(app.worker_env or {})
                if app.fleet_device_pinning:
                    # rid suffixes are rN (decode) / pN (prefill) in pool
                    # construction order; prefill replicas take the slices
                    # after the decode block so all of them partition one
                    # host without overlap (fleet.pinning)
                    from localai_tpu.fleet.pinning import pinned_worker_env

                    kind, num = rid.rsplit("/", 1)[-1][0], rid.rsplit("/", 1)[-1][1:]
                    idx = int(num) + (app.fleet_replicas
                                      if kind == "p" else 0)
                    # runtime spawns (autoscale/hot swap) mint ever-higher
                    # indexes; fold them back into the boot partition —
                    # the replica they replace has retired its slice
                    env = pinned_worker_env(app.worker_env, idx % total,
                                            total)
                return WorkerReplica(rid, role, cfg_ref["mcfg"], app,
                                     env=env or None)
        fm = FleetServingModel(
            mcfg, app, factory,
            replicas=app.fleet_replicas,
            prefill_replicas=app.fleet_prefill_replicas,
        )
        fm.cfg_ref = cfg_ref
        if app.autoscale:
            from localai_tpu.fleet.autoscale import AutoscaleController

            fm.autoscaler = AutoscaleController(fm, manager=self)
            fm.autoscaler.start()
        return fm

    def _load_image(self, mcfg: ModelConfig) -> ImageServingModel:
        from localai_tpu.image import resolve_image_model

        kwargs = {}
        d = mcfg.diffusers
        if d.scheduler_type:
            kwargs["default_scheduler"] = d.scheduler_type
        if d.steps:
            kwargs["default_steps"] = d.steps
        if d.cfg_scale is not None:
            kwargs["default_cfg_scale"] = d.cfg_scale
        if d.clip_skip:
            kwargs["clip_skip"] = d.clip_skip
        if mcfg.lora_adapter:
            from pathlib import Path

            lp = Path(mcfg.lora_adapter)
            if not lp.is_absolute():
                # relative adapters resolve against the models dir
                # (parity: backend.py:300-305)
                lp = Path(self.app.model_path) / lp
            kwargs["lora_adapter"] = str(lp)
            kwargs["lora_scale"] = mcfg.lora_scale
        t0 = time.monotonic()
        pipe = resolve_image_model(
            mcfg.model or mcfg.name, model_path=self.app.model_path, **kwargs
        )
        if d.control_net:
            pipe.attach_controlnet(d.control_net, self.app.model_path)
        log.info("loaded image model %s in %.1fs", mcfg.name,
                 time.monotonic() - t0)
        return ImageServingModel(name=mcfg.name, config=mcfg, pipeline=pipe)

    def _load_embedder(self, mcfg: ModelConfig) -> EmbeddingServingModel:
        from localai_tpu.models.reranker import resolve_sentence_encoder

        t0 = time.monotonic()
        enc = resolve_sentence_encoder(
            mcfg.model or mcfg.name, model_path=self.app.model_path,
            seed=mcfg.seed or 0,
        )
        log.info("loaded sentence encoder %s in %.1fs", mcfg.name,
                 time.monotonic() - t0)
        return EmbeddingServingModel(name=mcfg.name, config=mcfg,
                                     encoder=enc)

    def _load_whisper(self, mcfg: ModelConfig) -> AudioServingModel:
        from pathlib import Path

        from localai_tpu.models import whisper as wh

        ref = mcfg.model or mcfg.name
        t0 = time.monotonic()
        if ref.startswith("debug:"):
            model = wh.debug_model()
        else:
            for cand in (Path(ref), Path(self.app.model_path) / ref):
                if (cand / "config.json").exists():
                    model = wh.load_hf_whisper(cand)
                    break
            else:
                raise FileNotFoundError(f"whisper model {ref!r} not found")
        log.info("loaded whisper %s in %.1fs", mcfg.name,
                 time.monotonic() - t0)
        return AudioServingModel(name=mcfg.name, config=mcfg, model=model,
                                 kind="whisper")

    def _load_vits(self, mcfg: ModelConfig) -> AudioServingModel:
        from pathlib import Path

        from localai_tpu.audio.vits import load_hf_vits

        ref = mcfg.model or mcfg.name
        t0 = time.monotonic()
        for cand in (Path(ref), Path(self.app.model_path) / ref):
            if (cand / "config.json").exists():
                model = load_hf_vits(cand)
                break
        else:
            raise FileNotFoundError(f"vits model {ref!r} not found")
        log.info("loaded vits voice %s in %.1fs", mcfg.name,
                 time.monotonic() - t0)
        return AudioServingModel(name=mcfg.name, config=mcfg, model=model,
                                 kind="vits")

    def _load_reranker(self, mcfg: ModelConfig) -> RerankServingModel:
        from localai_tpu.models.reranker import resolve_reranker

        t0 = time.monotonic()
        enc = resolve_reranker(
            mcfg.model or mcfg.name, model_path=self.app.model_path,
            seed=mcfg.seed or 0,
        )
        log.info("loaded reranker %s in %.1fs", mcfg.name,
                 time.monotonic() - t0)
        return RerankServingModel(name=mcfg.name, config=mcfg, encoder=enc)

    # -- shutdown ---------------------------------------------------------

    def _evict_locked(self, name: str) -> None:  # jaxlint: guarded-by(_lock)
        sm = self._models.pop(name, None)
        if sm is not None:
            sm.close()

    def shutdown_model(self, name: str, *, force: bool = False,
                       wait: float = 30.0) -> bool:
        """Graceful single-model shutdown: wait for in-flight work unless
        forced (parity: ShutdownModel wait loop, loader.go:143-168)."""
        deadline = time.monotonic() + wait
        while not force:
            with self._lock:
                sm = self._models.get(name)
                if sm is None:
                    return False
                if not sm.busy:
                    break
            if time.monotonic() > deadline:
                log.warning("%s still busy after %.0fs; forcing", name, wait)
                break
            time.sleep(0.1)
        with self._lock:
            if name not in self._models:
                return False
            self._evict_locked(name)
            return True

    def shutdown_all(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
        with self._lock:
            for name in list(self._models):
                self._evict_locked(name)
            if self._pool is not None:
                self._pool.shutdown_all()

    # -- observability -----------------------------------------------------

    def metrics(self) -> dict:
        # engine_metrics() runs OUTSIDE the manager lock: on fleet/worker
        # models it pulls stats RPCs (bounded, but seconds when a replica
        # is wedged) and holding _lock across those would stall every
        # request's model resolution for the duration of a scrape
        with self._lock:
            models = list(self._models.items())
        return {name: sm.engine_metrics() for name, sm in models}

    def monitor(self, name: str) -> dict:
        """Per-model status (parity: /backend/monitor via gopsutil,
        core/services/backend_monitor.go — process stats become engine
        stats in-process)."""
        with self._lock:
            sm = self._models.get(name)
        if sm is None:
            return {"loaded": False, "name": name}
        return {
            "loaded": True,
            "name": name,
            "busy": sm.busy,
            "age_seconds": time.monotonic() - sm.loaded_at,
            "idle_seconds": time.monotonic() - sm.last_used,
            **sm.engine_metrics(),
        }


class _Watchdog(threading.Thread):
    """Busy/idle sweeper (parity: WatchDog.Run/checkBusy/checkIdle,
    /root/reference/pkg/model/watchdog.go:82-156)."""

    INTERVAL = 5.0

    def __init__(self, manager: ModelManager):
        super().__init__(name="watchdog", daemon=True)
        self.manager = manager
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        app = self.manager.app
        while not self._stop.wait(self.INTERVAL):
            now = time.monotonic()
            with self.manager._lock:
                items = list(self.manager._models.items())
            for name, sm in items:
                if (app.watchdog_idle and not sm.busy
                        and now - sm.last_used > app.watchdog_idle_timeout):
                    log.info("watchdog: evicting idle model %s", name)
                    self.manager.shutdown_model(name, force=True)
                elif app.watchdog_busy and sm.busy:
                    self._cancel_stuck(sm, now)

    def _cancel_stuck(self, sm: Any, now: float) -> None:
        if not isinstance(sm, ServingModel):
            # worker tier has its own busy watchdog (worker.process.Watchdog);
            # image generations are bounded by their step count
            return
        timeout = self.manager.app.watchdog_busy_timeout
        with sm.scheduler._lock:
            stuck = [
                ctx.handle
                for ctx in sm.scheduler._slots.values()
                if now - ctx.handle.t_submit > timeout
            ]
        for handle in stuck:
            log.warning("watchdog: cancelling stuck request %d (>%ds)",
                        handle.id, int(timeout))
            handle.cancel()
