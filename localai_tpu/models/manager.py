"""ModelManager: name → live serving engine, loaded on demand.

TPU-era redesign of the reference's model-lifecycle layer
(/root/reference/pkg/model/loader.go:22-206, initializers.go:271-540,
watchdog.go:19-156): where the reference spawns one gRPC worker *process*
per model and health-checks/respawns it, the in-process manager owns one
ModelRunner+Scheduler per model inside the server process. Process-level
isolation (crash containment) is provided by the separate gRPC worker tier
(localai_tpu.worker) — this manager is the in-process fast path, and both
expose the same surface.

Watchdog parity: busy-too-long requests are cancelled, idle-too-long
models are evicted to free HBM (defaults 5m/15m — core/cli/run.go:66-69).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Optional

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import Scheduler
from localai_tpu.templates.cache import TemplateCache

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ServingModel:
    """One loaded model: engine + tokenizer + its declarative config."""

    name: str
    config: ModelConfig
    runner: ModelRunner
    scheduler: Scheduler
    tokenizer: Any
    templates: TemplateCache
    vision: Optional[Any] = None      # VisionTower when the model is
                                      # multimodal (mmproj / llava checkpoint)
    image_token_id: int = 0
    loaded_at: float = dataclasses.field(default_factory=time.monotonic)
    last_used: float = dataclasses.field(default_factory=time.monotonic)

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        return self.scheduler.busy


def build_serving_model(mcfg: ModelConfig, app: AppConfig) -> ServingModel:
    """Config → live engine: resolve weights, build mesh/shardings, runner,
    scheduler, tokenizer, templates. Shared by the in-process manager and
    the gRPC worker tier (localai_tpu.worker.server), so both load paths
    behave identically."""
    from localai_tpu.models.registry import resolve_model

    eng = mcfg.engine
    shard = mcfg.sharding
    mesh = None
    t0 = time.monotonic()
    want_tp = max(1, shard.tensor_parallel_size)
    want_dp = shard.data_parallel_size  # 0 = auto
    if want_tp > 1 or want_dp not in (0, 1) or app.mesh_shape:
        from localai_tpu.parallel.mesh import MeshPlan, build_mesh

        if app.mesh_shape:
            mesh = build_mesh(MeshPlan(**app.mesh_shape))
        else:
            import jax

            nd = len(jax.devices())
            dp = want_dp or max(1, nd // want_tp)
            mesh = build_mesh(MeshPlan(data=dp, model=want_tp))

    model = resolve_model(
        mcfg.model or mcfg.name,
        model_path=app.model_path,
        dtype=eng.dtype,
    )
    params = model.params
    if eng.quantization:
        from localai_tpu.models.quant import quantize_params

        params = quantize_params(params, eng.quantization)
    if mesh is not None:
        from localai_tpu.parallel import sharding as shd

        params = shd.shard_params(params, model.cfg, mesh)
    ctx = mcfg.context_size or app.context_size
    ctx = min(ctx, model.cfg.max_position_embeddings)
    runner = ModelRunner(
        model.cfg,
        params,
        num_slots=eng.max_slots,
        max_ctx=ctx,
        prefill_buckets=eng.prefill_buckets,
        kv_dtype=eng.kv_dtype,
        rope_freq_base=mcfg.rope_freq_base,
        rope_freq_scale=mcfg.rope_freq_scale,
        seed=mcfg.seed or 0,
        mesh=mesh,
    )
    scheduler = Scheduler(
        runner,
        model.tokenizer,
        default_max_tokens=mcfg.parameters.max_tokens or 2048,
        multi_step=eng.decode_steps_per_dispatch,
        pipeline_depth=eng.pipeline_depth,
    )
    # vision tower: explicit mmproj ref, or auto from a llava checkpoint dir
    vision = None
    vt_ref = mcfg.mmproj or (
        str(model.model_dir) if model.hf_type == "llava" else None
    )
    if vt_ref:
        from localai_tpu.models.vision import resolve_vision_tower

        vision = resolve_vision_tower(
            vt_ref,
            projection_dim=model.cfg.hidden_size,
            model_path=app.model_path,
            seed=mcfg.seed or 0,
        )
        log.info("loaded vision tower %s: %d patches -> D=%d",
                 vt_ref, vision.n_patches, model.cfg.hidden_size)
    log.info(
        "loaded model %s (%s) in %.1fs: slots=%d ctx=%d mesh=%s",
        mcfg.name, mcfg.model, time.monotonic() - t0,
        eng.max_slots, ctx, mesh.shape if mesh else None,
    )
    return ServingModel(
        name=mcfg.name,
        config=mcfg,
        runner=runner,
        scheduler=scheduler,
        tokenizer=model.tokenizer,
        templates=TemplateCache(app.model_path),
        vision=vision,
        image_token_id=(
            mcfg.image_token_id if mcfg.image_token_id is not None
            else (model.image_token_id or 0)
        ),
    )


class ModelManager:
    """Thread-safe registry of loaded models (parity: ModelLoader map +
    mutex, loader.go:22-40)."""

    def __init__(
        self,
        app_config: Optional[AppConfig] = None,
        loader: Optional[ConfigLoader] = None,
    ):
        self.app = app_config or AppConfig()
        self.loader = loader or ConfigLoader(self.app.model_path)
        self._models: dict[str, ServingModel] = {}
        self._lock = threading.RLock()
        self._watchdog: Optional[_Watchdog] = None
        if self.app.watchdog_idle or self.app.watchdog_busy:
            self._watchdog = _Watchdog(self)
            self._watchdog.start()

    # -- lookup / load ----------------------------------------------------

    def loaded_names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def is_loaded(self, name: str) -> bool:
        with self._lock:
            return name in self._models

    def get(self, name: str) -> ServingModel:
        """Idempotent load-or-get (parity: ModelLoader.LoadModel +
        CheckIsLoaded health path, loader.go:96-206). The engine thread is
        the health signal: a dead thread → reload."""
        with self._lock:
            sm = self._models.get(name)
            if sm is not None:
                if sm.scheduler._thread.is_alive():
                    sm.touch()
                    return sm
                log.warning("model %s engine thread died; reloading", name)
                self._evict_locked(name)
            mcfg = self.loader.get(name)
            if mcfg is None:
                raise KeyError(f"no configuration for model {name!r}")
            if self.app.single_active_backend:
                for other in list(self._models):
                    if not self._models[other].busy:
                        self._evict_locked(other)
            sm = self._load(mcfg)
            self._models[name] = sm
            return sm

    def _load(self, mcfg: ModelConfig) -> ServingModel:
        return build_serving_model(mcfg, self.app)

    # -- shutdown ---------------------------------------------------------

    def _evict_locked(self, name: str) -> None:
        sm = self._models.pop(name, None)
        if sm is not None:
            sm.scheduler.shutdown()

    def shutdown_model(self, name: str, *, force: bool = False,
                       wait: float = 30.0) -> bool:
        """Graceful single-model shutdown: wait for in-flight work unless
        forced (parity: ShutdownModel wait loop, loader.go:143-168)."""
        deadline = time.monotonic() + wait
        while not force:
            with self._lock:
                sm = self._models.get(name)
                if sm is None:
                    return False
                if not sm.busy:
                    break
            if time.monotonic() > deadline:
                log.warning("%s still busy after %.0fs; forcing", name, wait)
                break
            time.sleep(0.1)
        with self._lock:
            if name not in self._models:
                return False
            self._evict_locked(name)
            return True

    def shutdown_all(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
        with self._lock:
            for name in list(self._models):
                self._evict_locked(name)

    # -- observability -----------------------------------------------------

    def metrics(self) -> dict:
        with self._lock:
            return {
                name: sm.scheduler.metrics()
                for name, sm in self._models.items()
            }

    def monitor(self, name: str) -> dict:
        """Per-model status (parity: /backend/monitor via gopsutil,
        core/services/backend_monitor.go — process stats become engine
        stats in-process)."""
        with self._lock:
            sm = self._models.get(name)
            if sm is None:
                return {"loaded": False, "name": name}
            return {
                "loaded": True,
                "name": name,
                "busy": sm.busy,
                "age_seconds": time.monotonic() - sm.loaded_at,
                "idle_seconds": time.monotonic() - sm.last_used,
                **sm.scheduler.metrics(),
            }


class _Watchdog(threading.Thread):
    """Busy/idle sweeper (parity: WatchDog.Run/checkBusy/checkIdle,
    /root/reference/pkg/model/watchdog.go:82-156)."""

    INTERVAL = 5.0

    def __init__(self, manager: ModelManager):
        super().__init__(name="watchdog", daemon=True)
        self.manager = manager
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        app = self.manager.app
        while not self._stop.wait(self.INTERVAL):
            now = time.monotonic()
            with self.manager._lock:
                items = list(self.manager._models.items())
            for name, sm in items:
                if (app.watchdog_idle and not sm.busy
                        and now - sm.last_used > app.watchdog_idle_timeout):
                    log.info("watchdog: evicting idle model %s", name)
                    self.manager.shutdown_model(name, force=True)
                elif app.watchdog_busy and sm.busy:
                    self._cancel_stuck(sm, now)

    def _cancel_stuck(self, sm: ServingModel, now: float) -> None:
        timeout = self.manager.app.watchdog_busy_timeout
        with sm.scheduler._lock:
            stuck = [
                ctx.handle
                for ctx in sm.scheduler._slots.values()
                if now - ctx.handle.t_submit > timeout
            ]
        for handle in stuck:
            log.warning("watchdog: cancelling stuck request %d (>%ds)",
                        handle.id, int(timeout))
            handle.cancel()
