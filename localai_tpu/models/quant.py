"""Weight-only int8 quantization for the llama engine.

TPU-era replacement for the reference's quantized-serving story (its default
text config is a q4 GGUF served by llama.cpp; the autogptq/exllama2 Python
backends serve GPTQ/EXL2 — /root/reference/aio/cpu/text-to-text.yaml,
backend/python/autogptq/backend.py). GGUF block formats are llama.cpp-native
and gain nothing on TPU; the idiomatic design is symmetric **per-channel
int8** kept quantized in HBM and dequantized inside the matmul:

    y = (x @ q.astype(bf16)) * scale        # scale per output channel

which XLA fuses into the matmul epilogue — the weight HBM read (the decode
bottleneck; see BENCH notes) is halved, while the MXU still runs bf16.

Granularity: one f32 scale per output channel (per matmul column, per
embedding row), the same granularity llama.cpp uses per 32-elem block but
without the block bookkeeping that would defeat XLA tiling.

``QuantizedTensor`` is a pytree node whose leaves (q, scale) stack/scan like
plain arrays, so the stacked-layer ``lax.scan`` in models.llama and the
NamedSharding placement in parallel.sharding both work unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


def block_w8_kernel_params(params: PyTree, reason: str = "") -> PyTree:
    """Mark every QuantizedTensor in ``params`` kernel-blocked.

    The Pallas call carries no partitioning rule, so under a multi-device
    mesh GSPMD would replicate (all-gather) the full weight per step — a
    meshed ModelRunner blocks the kernel for ITS OWN weights at init. The
    block rides the tensors (``kernel_ok`` pytree metadata), not process
    state: a single-device runner built later — a draft model, a second
    served model — keeps the opt-in kernel (ADVICE r5 #1 replaced the old
    one-way process-global latch with this)."""
    if os.environ.get("LOCALAI_W8_KERNEL"):
        import logging

        logging.getLogger(__name__).warning(
            "LOCALAI_W8_KERNEL disabled for these weights: %s",
            reason or "meshed serving")

    def mark(leaf):
        if isinstance(leaf, QuantizedTensor) and leaf.kernel_ok:
            return dataclasses.replace(leaf, kernel_ok=False)
        return leaf

    return jax.tree.map(
        mark, params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def _w8_kernel_mode() -> str:
    """'' (off) | 'tpu' | 'interpret' — the Pallas dequant-matmul opt-in
    (ops.qmatmul; LOCALAI_W8_KERNEL=1 enables on TPU, =interpret for CPU
    tests; any other value is off). Read per call: tests flip it at
    runtime. Per-tensor blocking (meshed weights) is carried by
    ``QuantizedTensor.kernel_ok``, checked at the matmul call sites."""
    v = os.environ.get("LOCALAI_W8_KERNEL", "").strip().lower()
    if v in ("1", "tpu"):
        return "tpu"
    if v == "interpret":
        return "interpret"
    return ""


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("q", "scale"),
    meta_fields=("axis", "mode", "kernel_ok"),
)
@dataclasses.dataclass
class QuantizedTensor:
    """Symmetric per-channel int8 weight.

    q:     int8, the original weight shape.
    scale: f32, the weight shape with ``axis`` (the matmul contraction dim)
           removed — one scale per output channel.
    axis:  which original axis was reduced (static metadata; used for
           sharding-spec derivation, not in the compute path).
    mode:  'w8'   — weight-only: q is cast to the activation dtype in the
                    matmul (bit-exact dequant, but XLA materializes the cast
                    so the HBM saving is partial);
           'w8a8' — activations are dynamically quantized per-token and the
                    MXU runs a native int8×int8→int32 dot: the weight stays
                    int8 all the way from HBM to the systolic array (the
                    full 2× bandwidth + int8-MXU win; adds per-token
                    activation rounding error);
           'w4'   — group-wise int4 weight-only (native jnp.int4 storage —
                    XLA packs two nibbles per byte in HBM, halving the int8
                    read again). scale keeps the contraction axis at
                    K/group size, one scale per (group, output channel) —
                    the GPTQ/q4 granularity (parity: the reference's
                    default q4 GGUF, aio/cpu/text-to-text.yaml, and its
                    autogptq/exllama2 backends) without block bookkeeping.
    """

    q: jax.Array
    scale: jax.Array
    axis: int
    mode: str = "w8"
    # False when these weights live on a runner whose mesh makes the
    # Pallas kernel a pessimization (see block_w8_kernel_params) — static
    # pytree metadata, so the block scopes to the runner, not the process
    kernel_ok: bool = True

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.q.shape)

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def group(self) -> int:
        """Contraction-axis group size (w4 modes); 0 for per-channel int8."""
        if self.mode not in ("w4",):
            return 0
        return self.q.shape[self.axis] // self.scale.shape[self.axis]


def quantize_tensor(w, axis: int) -> QuantizedTensor:
    """Symmetric per-channel int8: scale = amax|w| / 127 over ``axis``."""
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(wf / jnp.expand_dims(scale, axis)), -127, 127
    ).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale, axis=axis)


def _group_size(K: int, group: int) -> int:
    """Largest divisor of K that is ≤ group (small debug dims stay exact)."""
    g = min(K, group)
    while K % g:
        g -= 1
    return g


def quantize_tensor4(w, axis: int, group: int = 128) -> QuantizedTensor:
    """Symmetric group-wise int4: the contraction axis splits into groups of
    ``group``; scale = amax|w| / 7 per (group, output channel). q is native
    jnp.int4 in [-7, 7]; scale keeps the axis at size K/group."""
    wf = jnp.asarray(w).astype(jnp.float32)
    shape = wf.shape
    K = shape[axis]
    g = _group_size(K, group)
    gc = K // g
    grouped = wf.reshape(shape[:axis] + (gc, g) + shape[axis + 1:])
    amax = jnp.max(jnp.abs(grouped), axis=axis + 1)        # [..., gc, ...]
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(
        jnp.round(grouped / jnp.expand_dims(scale, axis + 1)), -7, 7
    ).astype(jnp.int4)
    return QuantizedTensor(
        q=q.reshape(shape), scale=scale, axis=axis, mode="w4"
    )


def _grouped_dequant(qt: QuantizedTensor, dtype) -> jax.Array:
    """w4 dequant to ``dtype``: expand scale over its groups."""
    shape = qt.q.shape
    gc = qt.scale.shape[qt.axis]
    g = shape[qt.axis] // gc
    grouped = qt.q.reshape(
        shape[:qt.axis] + (gc, g) + shape[qt.axis + 1:]
    ).astype(dtype)
    out = grouped * jnp.expand_dims(qt.scale, qt.axis + 1).astype(dtype)
    return out.reshape(shape)


def quantize_lastdim(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Dynamic symmetric int8 over the last axis: x [..., K] →
    (q int8 [..., K], scale f32 [...]). The shared recipe for activation
    quantization (w8a8 matmuls) and the scaled int8 KV cache."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


_quant_activations = quantize_lastdim


def quantize_lastdim4(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Dynamic symmetric int4 over the last axis, nibble-packed: x [..., K]
    (K even) → (packed int8 [..., K/2], scale f32 [...]). The scaled-int4
    KV pool recipe (engine.kvcache): scale = amax|x| / 7 per row, values
    clipped to [-7, 7]. Packing is HALVES layout — element i of the first
    half lands in the LOW nibble of byte i, element i of the second half
    in the HIGH nibble — so :func:`unpack_int4_lastdim` is two shifts and
    a concat (no interleave/relayout on the TPU lane axis)."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 7.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -7, 7).astype(jnp.int8)
    half = q.shape[-1] // 2
    lo = q[..., :half]
    hi = q[..., half:]
    packed = jnp.bitwise_or(
        jnp.bitwise_and(lo, jnp.int8(0x0F)),
        jnp.left_shift(hi, 4).astype(jnp.int8),
    )
    return packed, scale


def unpack_int4_lastdim(packed: jax.Array) -> jax.Array:
    """Inverse of the :func:`quantize_lastdim4` packing: int8 [..., K/2] →
    int8 [..., K] in [-8, 7]. Low nibbles sign-extend via the left/right
    arithmetic-shift pair; high nibbles via a plain arithmetic right
    shift — both are VPU-native, no lookup tables."""
    lo = jnp.right_shift(jnp.left_shift(packed, 4).astype(jnp.int8), 4)
    hi = jnp.right_shift(packed, 4)
    return jnp.concatenate([lo, hi], axis=-1).astype(jnp.int8)


def _int8_dot(xq: jax.Array, wq: jax.Array, transpose_w: bool) -> jax.Array:
    """Native int8×int8→int32 dot over the last axis of xq."""
    k_axis = 1 if transpose_w else 0
    return jax.lax.dot_general(
        xq, wq,
        (((xq.ndim - 1,), (k_axis,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def matmul(x: jax.Array, w) -> jax.Array:
    """x @ w for plain or quantized weights.

    'w8': the int8 weight is cast to x.dtype inside the matmul and the
    per-output-channel scale applied to the product — exactly
    x @ (q * scale) with the scale factored out of the contraction.
    'w8a8': x is dynamically quantized per token and the dot runs on the
    int8 MXU path; both scales are applied to the int32 accumulator.
    'w4': group-wise scales can't factor out of the whole contraction, so
    the dot runs per group (a [Gc]-batched matmul with G-deep contractions
    — still MXU-shaped at G=128) and the scaled partials sum.
    """
    if not isinstance(w, QuantizedTensor):
        return x @ w
    if w.mode == "w4":
        mode = _w8_kernel_mode() if w.kernel_ok else ""
        if mode:
            from localai_tpu.ops import qmatmul

            if qmatmul.w4_eligible(x.shape, w.q, w.scale):
                x2 = x.reshape(-1, x.shape[-1])
                y = qmatmul.w4_matmul(x2, w.q, w.scale,
                                      interpret=mode == "interpret")
                return y.reshape(*x.shape[:-1], y.shape[-1])
        K, N = w.q.shape[-2], w.q.shape[-1]
        gc = w.scale.shape[-2]
        wg = w.q.reshape(gc, K // gc, N).astype(x.dtype)
        xg = x.reshape(*x.shape[:-1], gc, K // gc)
        acc = jnp.einsum("...gk,gkn->...gn", xg, wg)
        return (acc * w.scale.astype(x.dtype)).sum(-2)
    if w.mode == "w8a8":
        xq, xs = _quant_activations(x)
        acc = _int8_dot(xq, w.q, transpose_w=False).astype(jnp.float32)
        return (acc * xs[..., None] * w.scale).astype(x.dtype)
    mode = _w8_kernel_mode() if w.kernel_ok else ""
    if mode:
        from localai_tpu.ops import qmatmul

        if qmatmul.eligible(x.shape, w.q, w.scale, transpose_w=False):
            x2 = x.reshape(-1, x.shape[-1])
            y = qmatmul.w8_matmul(x2, w.q, w.scale,
                                  interpret=mode == "interpret")
            return y.reshape(*x.shape[:-1], y.shape[-1])
    return (x @ w.q.astype(x.dtype)) * w.scale.astype(x.dtype)


def matmul_t(x: jax.Array, w) -> jax.Array:
    """x @ w.T (tied-embedding lm_head). Per-row scales become per-output-
    column scales under the transpose, so the factoring still holds."""
    if not isinstance(w, QuantizedTensor):
        return x @ w.T.astype(x.dtype)
    # no 'w4' branch: quantize_params keeps embedding tables per-row int8
    # even in int4 mode (gather + tied-logits exactness; ~2% of 4-bit 8B),
    # so a w4 table can never reach the transposed path
    if w.mode == "w8a8":
        xq, xs = _quant_activations(x)
        acc = _int8_dot(xq, w.q, transpose_w=True).astype(jnp.float32)
        return (acc * xs[..., None] * w.scale).astype(x.dtype)
    mode = _w8_kernel_mode() if w.kernel_ok else ""
    if mode:
        from localai_tpu.ops import qmatmul

        if qmatmul.eligible(x.shape, w.q, w.scale, transpose_w=True):
            x2 = x.reshape(-1, x.shape[-1])
            y = qmatmul.w8_matmul(x2, w.q, w.scale, transpose_w=True,
                                  interpret=mode == "interpret")
            return y.reshape(*x.shape[:-1], y.shape[-1])
    return (x @ w.q.T.astype(x.dtype)) * w.scale.astype(x.dtype)


def moe_up(x: jax.Array, w) -> jax.Array:
    """x [..., D] against expert-stacked w [E, D, F] → [..., E, F].

    MoE expert weights quantize per-channel int8 only (mode 'w8'): the
    expert einsum layout is fixed here, so the (post-scan-slice) axis
    metadata a w4 group dequant would need never comes into play."""
    if not isinstance(w, QuantizedTensor):
        return jnp.einsum("...d,edf->...ef", x, w)
    acc = jnp.einsum("...d,edf->...ef", x, w.q.astype(x.dtype))
    return acc * w.scale.astype(x.dtype)          # scale [E, F]


def moe_down(a: jax.Array, w) -> jax.Array:
    """a [..., E, F] against expert-stacked w [E, F, D] → [..., E, D]."""
    if not isinstance(w, QuantizedTensor):
        return jnp.einsum("...ef,efd->...ed", a, w)
    acc = jnp.einsum("...ef,efd->...ed", a, w.q.astype(a.dtype))
    return acc * w.scale.astype(a.dtype)          # scale [E, D]


def embed_rows(w, tokens: jax.Array, dtype) -> jax.Array:
    """Embedding gather for plain or per-row-quantized tables."""
    if isinstance(w, QuantizedTensor):
        return w.q[tokens].astype(dtype) * w.scale[tokens][..., None].astype(dtype)
    return w[tokens].astype(dtype)


# Which params get quantized, and the contraction axis for each.
# Norm gains and qkv biases stay in their source dtype (tiny, 1-D).
_LAYER_AXES = {
    "wq": 1, "wk": 1, "wv": 1, "wo": 1,
    "w_gate": 1, "w_up": 1, "w_down": 1,
}


def quantize_params(params: PyTree, mode: str = "int8",
                    group: int = 128) -> PyTree:
    """Quantize a llama param pytree's matmul weights in place of bf16.

    embed is quantized per-row (axis=-1) so both the gather and the
    tied-embedding logits matmul stay exact per-channel; lm_head per
    output column (axis=0). Stacked layer weights [L, K, N] quantize over
    K (axis=1) so scales stack [L, N] and scan alongside the weights.

    mode: 'int8' (weight-only), 'int8_w8a8' (+ dynamic activation quant,
    native int8 MXU dot), or 'int4' (group-wise int4 weight-only, the
    TPU analogue of the reference's default q4 serving — see
    QuantizedTensor). For 'int4', layer matmuls go group-wise while embed
    stays per-row int8: gather accuracy is cheap (int8 embed is 2% of 4-bit
    8B total) and the tied-logits path keeps its exact per-channel form.
    """
    if mode not in ("int8", "int8_w8a8", "int4"):
        raise ValueError(f"unsupported quantization mode {mode!r}")

    if mode == "int4":
        def qt(w, axis):
            return quantize_tensor4(w, axis, group=group)
    else:
        mm_mode = "w8a8" if mode == "int8_w8a8" else "w8"

        def qt(w, axis):
            return dataclasses.replace(quantize_tensor(w, axis), mode=mm_mode)

    out = dict(params)
    out["embed"] = (quantize_tensor(params["embed"], axis=1)
                    if mode == "int4" else qt(params["embed"], axis=1))
    if "lm_head" in params:
        out["lm_head"] = qt(params["lm_head"], axis=0)
    layers = dict(params["layers"])
    moe = layers.get("w_gate") is not None and layers["w_gate"].ndim == 4
    for name, axis in _LAYER_AXES.items():
        if moe and name in ("w_gate", "w_up", "w_down"):
            # expert-stacked [L, E, K, N]: contraction K is axis 2;
            # per-channel int8 regardless of mode (moe_up/moe_down fix the
            # einsum layout — group-wise w4 metadata wouldn't survive the
            # scan slice)
            layers[name] = quantize_tensor(layers[name], axis=2)
        else:
            layers[name] = qt(layers[name], axis=axis)
    out["layers"] = layers
    return out


def dequantize_tensor(qt: QuantizedTensor, dtype="float32") -> jax.Array:
    if qt.mode == "w4":
        return _grouped_dequant(qt, dtype)
    return qt.q.astype(dtype) * jnp.expand_dims(qt.scale, qt.axis).astype(dtype)


def quantized_spec(qt_path_spec, axis: int, grouped: bool = False):
    """Derive the scale PartitionSpec from the weight spec: drop the
    contracted axis (per-channel int8) or keep it (group-wise w4 — the
    scale's group axis tiles the weight's contraction axis, so it shards
    the same way when divisible; parallel.sharding sanitizes the rest)."""
    from jax.sharding import PartitionSpec as P

    if grouped:
        return P(*qt_path_spec)
    entries = list(qt_path_spec)
    # P shorter than rank means trailing dims replicated; pad first
    while len(entries) < axis + 1:
        entries.append(None)
    del entries[axis]
    return P(*entries)
