"""Weight-only int8 quantization for the llama engine.

TPU-era replacement for the reference's quantized-serving story (its default
text config is a q4 GGUF served by llama.cpp; the autogptq/exllama2 Python
backends serve GPTQ/EXL2 — /root/reference/aio/cpu/text-to-text.yaml,
backend/python/autogptq/backend.py). GGUF block formats are llama.cpp-native
and gain nothing on TPU; the idiomatic design is symmetric **per-channel
int8** kept quantized in HBM and dequantized inside the matmul:

    y = (x @ q.astype(bf16)) * scale        # scale per output channel

which XLA fuses into the matmul epilogue — the weight HBM read (the decode
bottleneck; see BENCH notes) is halved, while the MXU still runs bf16.

Granularity: one f32 scale per output channel (per matmul column, per
embedding row), the same granularity llama.cpp uses per 32-elem block but
without the block bookkeeping that would defeat XLA tiling.

``QuantizedTensor`` is a pytree node whose leaves (q, scale) stack/scan like
plain arrays, so the stacked-layer ``lax.scan`` in models.llama and the
NamedSharding placement in parallel.sharding both work unchanged.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


@partial(
    jax.tree_util.register_dataclass,
    data_fields=("q", "scale"),
    meta_fields=("axis", "mode"),
)
@dataclasses.dataclass
class QuantizedTensor:
    """Symmetric per-channel int8 weight.

    q:     int8, the original weight shape.
    scale: f32, the weight shape with ``axis`` (the matmul contraction dim)
           removed — one scale per output channel.
    axis:  which original axis was reduced (static metadata; used for
           sharding-spec derivation, not in the compute path).
    mode:  'w8'   — weight-only: q is cast to the activation dtype in the
                    matmul (bit-exact dequant, but XLA materializes the cast
                    so the HBM saving is partial);
           'w8a8' — activations are dynamically quantized per-token and the
                    MXU runs a native int8×int8→int32 dot: the weight stays
                    int8 all the way from HBM to the systolic array (the
                    full 2× bandwidth + int8-MXU win; adds per-token
                    activation rounding error).
    """

    q: jax.Array
    scale: jax.Array
    axis: int
    mode: str = "w8"

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.q.shape)

    @property
    def dtype(self):
        return self.q.dtype


def quantize_tensor(w, axis: int) -> QuantizedTensor:
    """Symmetric per-channel int8: scale = amax|w| / 127 over ``axis``."""
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(wf / jnp.expand_dims(scale, axis)), -127, 127
    ).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale, axis=axis)


def quantize_lastdim(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Dynamic symmetric int8 over the last axis: x [..., K] →
    (q int8 [..., K], scale f32 [...]). The shared recipe for activation
    quantization (w8a8 matmuls) and the scaled int8 KV cache."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


_quant_activations = quantize_lastdim


def _int8_dot(xq: jax.Array, wq: jax.Array, transpose_w: bool) -> jax.Array:
    """Native int8×int8→int32 dot over the last axis of xq."""
    k_axis = 1 if transpose_w else 0
    return jax.lax.dot_general(
        xq, wq,
        (((xq.ndim - 1,), (k_axis,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def matmul(x: jax.Array, w) -> jax.Array:
    """x @ w for plain or quantized weights.

    'w8': the int8 weight is cast to x.dtype inside the matmul and the
    per-output-channel scale applied to the product — exactly
    x @ (q * scale) with the scale factored out of the contraction.
    'w8a8': x is dynamically quantized per token and the dot runs on the
    int8 MXU path; both scales are applied to the int32 accumulator.
    """
    if not isinstance(w, QuantizedTensor):
        return x @ w
    if w.mode == "w8a8":
        xq, xs = _quant_activations(x)
        acc = _int8_dot(xq, w.q, transpose_w=False).astype(jnp.float32)
        return (acc * xs[..., None] * w.scale).astype(x.dtype)
    return (x @ w.q.astype(x.dtype)) * w.scale.astype(x.dtype)


def matmul_t(x: jax.Array, w) -> jax.Array:
    """x @ w.T (tied-embedding lm_head). Per-row scales become per-output-
    column scales under the transpose, so the factoring still holds."""
    if not isinstance(w, QuantizedTensor):
        return x @ w.T.astype(x.dtype)
    if w.mode == "w8a8":
        xq, xs = _quant_activations(x)
        acc = _int8_dot(xq, w.q, transpose_w=True).astype(jnp.float32)
        return (acc * xs[..., None] * w.scale).astype(x.dtype)
    return (x @ w.q.T.astype(x.dtype)) * w.scale.astype(x.dtype)


def embed_rows(w, tokens: jax.Array, dtype) -> jax.Array:
    """Embedding gather for plain or per-row-quantized tables."""
    if isinstance(w, QuantizedTensor):
        return w.q[tokens].astype(dtype) * w.scale[tokens][..., None].astype(dtype)
    return w[tokens].astype(dtype)


# Which params get quantized, and the contraction axis for each.
# Norm gains and qkv biases stay in their source dtype (tiny, 1-D).
_LAYER_AXES = {
    "wq": 1, "wk": 1, "wv": 1, "wo": 1,
    "w_gate": 1, "w_up": 1, "w_down": 1,
}


def quantize_params(params: PyTree, mode: str = "int8") -> PyTree:
    """Quantize a llama param pytree's matmul weights in place of bf16.

    embed is quantized per-row (axis=-1) so both the gather and the
    tied-embedding logits matmul stay exact per-channel; lm_head per
    output column (axis=0). Stacked layer weights [L, K, N] quantize over
    K (axis=1) so scales stack [L, N] and scan alongside the weights.

    mode: 'int8' (weight-only) or 'int8_w8a8' (+ dynamic activation quant,
    native int8 MXU dot — the faster serving default; see QuantizedTensor).
    """
    if mode not in ("int8", "int8_w8a8"):
        raise ValueError(f"unsupported quantization mode {mode!r}")
    mm_mode = "w8a8" if mode == "int8_w8a8" else "w8"

    def qt(w, axis):
        return dataclasses.replace(quantize_tensor(w, axis), mode=mm_mode)

    out = dict(params)
    out["embed"] = qt(params["embed"], axis=1)
    if "lm_head" in params:
        out["lm_head"] = qt(params["lm_head"], axis=0)
    layers = dict(params["layers"])
    for name, axis in _LAYER_AXES.items():
        # stacked [L, K, N]: contraction K is axis 1 → per-(layer, col) scale
        layers[name] = qt(layers[name], axis=axis)
    out["layers"] = layers
    return out


def dequantize_tensor(qt: QuantizedTensor, dtype="float32") -> jax.Array:
    return qt.q.astype(dtype) * jnp.expand_dims(qt.scale, qt.axis).astype(dtype)


def quantized_spec(qt_path_spec, axis: int):
    """Derive the scale PartitionSpec from the weight spec by dropping the
    contracted axis (used by parallel.sharding for quantized params)."""
    from jax.sharding import PartitionSpec as P

    entries = list(qt_path_spec)
    # P shorter than rank means trailing dims replicated; pad first
    while len(entries) < axis + 1:
        entries.append(None)
    del entries[axis]
    return P(*entries)
